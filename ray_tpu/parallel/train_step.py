"""Sharded training step: init + step compiled over the mesh.

This is the compute core the Train library (and __graft_entry__) drives:
- parameters/optimizer state sharded by the logical-axis rule table
  (ZeRO-3 over `fsdp`, megatron over `tensor`) — XLA inserts all-gathers /
  reduce-scatters; gradients sync via the shardings alone, no explicit
  collectives (replaces the reference's torch.distributed allreduce path,
  reference: python/ray/train/torch/config.py:153).
- the batch is sharded over (data, fsdp) × seq; ring attention runs as a
  shard_map island over `seq`.
- the step donates the previous state (buffer reuse in HBM).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax.core import FrozenDict
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel import sharding as sharding_lib
from ray_tpu.parallel.mesh import use_mesh


@dataclasses.dataclass
class TrainState:
    step: Any
    params: Any
    opt_state: Any

    def tree_flatten(self):
        return (self.step, self.params, self.opt_state), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten)


def _translate_entry(entry, rules):
    if entry is None:
        return None
    if isinstance(entry, (tuple, list)):
        out = []
        for e in entry:
            m = rules.get(e)
            if m is None:
                continue
            if isinstance(m, (tuple, list)):
                out.extend(m)
            else:
                out.append(m)
        return tuple(out) if out else None
    m = rules.get(entry)
    return tuple(m) if isinstance(m, list) else m


def logical_pspec_to_mesh(spec, rules) -> P:
    if not isinstance(spec, P):
        return P()
    used = set()
    out = []
    for entry in spec:
        m = _translate_entry(entry, rules)
        if m is not None:
            key = m if isinstance(m, tuple) else (m,)
            if any(a in used for a in key):
                m = None
            else:
                used.update(key)
        out.append(m)
    return P(*out)


def _prune_indivisible(spec: P, shape, mesh: Mesh) -> P:
    """Replicate any dimension whose size isn't divisible by its mesh axes
    (e.g. 2 KV heads on an 8-way tensor axis)."""
    if shape is None or len(spec) == 0:
        return spec
    out = []
    for i, entry in enumerate(spec):
        if entry is None or i >= len(shape):
            out.append(entry)
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        size = 1
        for a in axes:
            size *= mesh.shape[a]
        out.append(entry if size and shape[i] % size == 0 else None)
    return P(*out)


def state_shardings(abstract_state, mesh: Mesh, rules=None):
    """Derive NamedShardings for a TrainState from flax Partitioned boxes."""
    rules = rules or sharding_lib.DEFAULT_RULES
    logical = nn.get_partition_spec(abstract_state)

    def mk(sp, node):
        if not isinstance(sp, P):
            return NamedSharding(mesh, P())
        leaves = jax.tree.leaves(node)
        shape = leaves[0].shape if leaves else None
        if shape is not None and len(sp) > len(shape):
            # logical axes outnumber the value's rank: a factored optimizer
            # state (e.g. adafactor's row/col second-moment vectors) that
            # inherited the param's boxes. Which axis was reduced away is
            # unknowable here; the vectors are tiny — replicate
            return NamedSharding(mesh, P())
        mesh_spec = _prune_indivisible(
            logical_pspec_to_mesh(sp, rules), shape, mesh)
        return NamedSharding(mesh, mesh_spec)

    return jax.tree.map(mk, logical, abstract_state,
                        is_leaf=lambda x: isinstance(x, P))


def chunked_cross_entropy(h, unembed, targets, mask=None, chunk=256):
    """Cross-entropy over sequence chunks: logits for one [B,chunk,vocab]
    block at a time (lax.scan, body checkpointed with nothing_saveable so
    the backward recomputes the block's unembed matmul instead of saving
    its output). The full [B,L,vocab] buffer — 0.5 GB for B=8 L=1024
    V=32k bf16, and the round-3 OOM allocation for tpu-1b B=16 — never
    exists in HBM.

    h: [B,L,d] final hidden states; unembed: [d,V]."""
    # pin h to the canonical activation layout at this boundary: the
    # unembed einsum's preferred layout (d over tensor) otherwise
    # propagates backward into the layer-scan while-loop carry and GSPMD
    # bridges the mismatch with an involuntary full rematerialization
    h = sharding_lib.constrain(h, ("batch", "seq", None))
    B, L, d = h.shape
    chunk = min(chunk, L)
    pad = (-L) % chunk
    if pad:
        h = jnp.pad(h, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        pad_mask = jnp.broadcast_to(jnp.arange(L + pad)[None, :] < L,
                                    (B, L + pad))
        mask = pad_mask if mask is None \
            else jnp.logical_and(
                jnp.pad(mask, ((0, 0), (0, pad))).astype(bool), pad_mask)
    n = (L + pad) // chunk
    h_c = jnp.moveaxis(h.reshape(B, n, chunk, d), 1, 0)
    t_c = jnp.moveaxis(targets.reshape(B, n, chunk), 1, 0)
    if mask is not None:
        m_c = jnp.moveaxis(mask.reshape(B, n, chunk), 1, 0) \
            .astype(jnp.float32)
    else:
        m_c = jnp.ones((n, B, chunk), jnp.float32)

    @functools.partial(jax.checkpoint,
                       policy=jax.checkpoint_policies.nothing_saveable)
    def body(carry, xs):
        hc, tc, mc = xs
        logits = jnp.einsum("bcd,dv->bcv", hc, unembed)
        logz = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
        gold = jnp.take_along_axis(logits, tc[..., None], axis=-1)[..., 0]
        nll = (logz - gold.astype(jnp.float32)) * mc
        return (carry[0] + nll.sum(), carry[1] + mc.sum()), None

    (total, denom), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (h_c, t_c, m_c))
    denom = jnp.maximum(denom, 1.0)
    return total / denom, denom


def cross_entropy_loss(logits, targets, mask=None):
    # logits may be bf16 (TransformerConfig.logits_fp32=False): upcast
    # inside the reduction so XLA fuses the convert into logsumexp instead
    # of materializing a [B,L,vocab] fp32 buffer in HBM
    logz = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - gold.astype(jnp.float32)
    if mask is None:
        return nll.mean(), nll.size
    mask = mask.astype(jnp.float32)
    denom = jnp.maximum(mask.sum(), 1.0)
    return (nll * mask).sum() / denom, denom


def make_train_fns(model: nn.Module, optimizer,
                   mesh: Mesh, rules=None,
                   batch_shape: Tuple[int, int] = (8, 512),
                   loss_chunk: Optional[int] = None,
                   profiler=None,
                   ) -> Tuple[Callable, Callable, Any]:
    """Returns (init_fn(rng) -> TrainState, step_fn(state, batch) ->
    (state, metrics), state_sharding_tree). Both are jitted with explicit
    shardings over `mesh`. loss_chunk enables the chunked cross-entropy
    (compute logits `loss_chunk` positions at a time — see
    chunked_cross_entropy; required to fit the larger registry rungs).

    profiler: an optional util.profiling.StepProfiler; the returned
    step_fn then AOT-compiles once per shape (cost_analysis FLOPs feed
    the profiler) and each call is attributed compute-vs-host-gap and
    blocked on the loss, emitting runtime_<name>_mfu gauges + timeline
    spans (the in-runtime answer to the stuck train_step_mfu ratchet)."""
    rules = rules or sharding_lib.DEFAULT_RULES
    tokens0 = jnp.zeros(batch_shape, jnp.int32)

    def init_state(rng):
        variables = model.init(rng, tokens0)
        params = variables["params"]
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=optimizer.init(params))

    abstract = jax.eval_shape(init_state, jax.random.PRNGKey(0))
    shardings = state_shardings(abstract, mesh, rules)
    batch_sharding = NamedSharding(
        mesh, _prune_indivisible(
            logical_pspec_to_mesh(P("batch", "seq"), rules),
            batch_shape, mesh))

    init_fn = jax.jit(init_state, out_shardings=shardings)

    model_cfg = getattr(model, "cfg", None)
    is_moe = bool(getattr(model_cfg, "n_experts", 0))
    aux_coef = float(getattr(model_cfg, "router_aux_coef", 0.0) or 0.0)

    tied = bool(getattr(model_cfg, "tie_embeddings", False))

    def _unembed_of(params):
        raw = params["embed"] if tied else params["unembed"]
        v = raw.unbox() if hasattr(raw, "unbox") else raw
        v = v.astype(getattr(model_cfg, "dtype", v.dtype))
        return v.T if tied else v

    def loss_fn(params, tokens, mask):
        inputs = tokens[:, :-1]
        targets = tokens[:, 1:]
        tgt_mask = None if mask is None else mask[:, 1:]
        kw = {"return_hidden": True} if loss_chunk else {}
        if is_moe:
            out, var = model.apply({"params": params}, inputs,
                                   mutable=["losses"], **kw)
            aux = sum(jax.tree.leaves(var.get("losses", {})),
                      jnp.zeros((), jnp.float32))
        else:
            out = model.apply({"params": params}, inputs, **kw)
            aux = jnp.zeros((), jnp.float32)
        if loss_chunk:
            ce, denom = chunked_cross_entropy(
                out, _unembed_of(params), targets, tgt_mask,
                chunk=loss_chunk)
        else:
            ce, denom = cross_entropy_loss(out, targets, tgt_mask)
        return ce + aux_coef * aux, (denom, ce, aux)

    def step_fn(state: TrainState, tokens, mask=None):
        with use_mesh(mesh):
            (loss, (denom, ce, aux)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params, tokens, mask)
            # pin gradient shardings to the parameter shardings: without
            # this, GSPMD picks its own layout for the scanned-layer grad
            # accumulator inside the backward while-loop and then bridges
            # to the optimizer's layout via an involuntary full
            # rematerialization (a per-step all-gather of the stacked
            # grads — round-4 verdict weak #5)
            grads = jax.lax.with_sharding_constraint(
                grads, shardings.params)
        updates, new_opt = optimizer.update(grads, state.opt_state,
                                            params=state.params)
        new_params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new_state = TrainState(step=state.step + 1, params=new_params,
                               opt_state=new_opt)
        return new_state, {"loss": ce, "total_loss": loss, "moe_aux": aux,
                           "grad_norm": gnorm, "tokens": denom}

    jit_step = jax.jit(
        step_fn,
        in_shardings=(shardings, batch_sharding, None),
        out_shardings=(shardings, None),
        donate_argnums=(0,))

    # jit(step) traces the model outside use_mesh; wrap so tracing also sees
    # the mesh context (shard_map islands need the concrete mesh at trace
    # time, and trace happens at first call)
    profiled_step = profiler.wrap_jit(jit_step) if profiler is not None \
        else None

    def step_with_mesh(state, tokens, mask=None):
        if profiler is None:
            with use_mesh(mesh):
                return jit_step(state, tokens, mask)
        with profiler.step(tokens=int(tokens.size)) as sc:
            sc.data_ready()
            with use_mesh(mesh):
                out = profiled_step(state, tokens, mask)
            sc.block(out[1]["loss"])
        return out

    def init_with_mesh(rng):
        with use_mesh(mesh):
            return init_fn(rng)

    return init_with_mesh, step_with_mesh, shardings


def make_infer_fns(model: nn.Module, mesh: Mesh, rules=None,
                   batch_shape: Tuple[int, int] = (8, 128),
                   ) -> Tuple[Callable, Callable, Any]:
    """Serving-side counterpart of make_train_fns: (init_fn(rng) ->
    params, infer_fn(params, tokens) -> last-position logits,
    param_sharding_tree), both jitted with explicit shardings over
    `mesh`. Params shard per the megatron rule table (tensor/fsdp axes),
    the batch over the data axes, and logits come back replicated —
    the shape a sharded serve replica group runs per request
    (serve/sharded_replica.py; reference has no TPU counterpart).
    Logits are computed at the LAST position only: that is the decode
    shape, and it keeps the unembed matmul at [B, d]·[d, V] instead of
    materializing [B, L, V]."""
    rules = rules or sharding_lib.DEFAULT_RULES
    tokens0 = jnp.zeros(batch_shape, jnp.int32)

    def init_params(rng):
        return model.init(rng, tokens0)["params"]

    abstract = jax.eval_shape(init_params, jax.random.PRNGKey(0))
    shardings = state_shardings(abstract, mesh, rules)
    batch_sharding = NamedSharding(
        mesh, _prune_indivisible(
            logical_pspec_to_mesh(P("batch", "seq"), rules),
            batch_shape, mesh))
    init_fn = jax.jit(init_params, out_shardings=shardings)

    model_cfg = getattr(model, "cfg", None)
    tied = bool(getattr(model_cfg, "tie_embeddings", False))

    def _unembed_of(params):
        raw = params["embed"] if tied else params["unembed"]
        v = raw.unbox() if hasattr(raw, "unbox") else raw
        v = v.astype(getattr(model_cfg, "dtype", v.dtype))
        return v.T if tied else v

    def forward(params, tokens):
        is_moe = bool(getattr(model_cfg, "n_experts", 0))
        kw = {"mutable": ["losses"]} if is_moe else {}
        out = model.apply({"params": params}, tokens,
                          return_hidden=True, **kw)
        h = out[0] if is_moe else out
        return h[:, -1, :] @ _unembed_of(params)

    jit_fwd = jax.jit(forward,
                      in_shardings=(shardings, batch_sharding),
                      out_shardings=NamedSharding(mesh, P()))

    def infer_with_mesh(params, tokens):
        with use_mesh(mesh):
            return jit_fwd(params, tokens)

    def init_with_mesh(rng):
        with use_mesh(mesh):
            return init_fn(rng)

    return init_with_mesh, infer_with_mesh, shardings
