"""Pipeline parallelism: GPipe microbatch schedule over the `stage` mesh axis.

The reference expresses pipeline parallelism only through compiled DAGs —
multi-actor pipelines wired with NCCL P2P channels and a static execution
schedule (reference: python/ray/dag/compiled_dag_node.py:549,
experimental/channel/torch_tensor_nccl_channel.py, schedule in
dag/dag_node_operation.py). The TPU-native equivalent keeps the whole
pipeline inside ONE jitted SPMD program: stage weights are sharded over the
`stage` mesh axis, activations hop stage→stage via `lax.ppermute` (ICI
neighbor transfers), and the GPipe tick loop is a `lax.scan`. XLA overlaps
the ppermute with the next tick's compute; there are no per-hop host round
trips to hide, which is precisely why the µs-scale channel machinery of the
reference is unnecessary here.

Schedule (S stages, M microbatches, T = M + S - 1 ticks):

    tick t: stage s computes microbatch (t - s) if 0 <= t - s < M
            then shifts its output to stage s+1

Bubble fraction = (S-1)/T, the classic GPipe overhead; amortize with M >> S.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.parallel.mesh import AXIS_STAGE


def stack_stage_params(per_stage_params: list):
    """Stack a list of per-stage param pytrees into one tree with a leading
    stage dim (shard it over `stage` with stage_param_specs)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def stage_param_specs(stacked_params, stage_axis: str = AXIS_STAGE):
    """PartitionSpecs sharding the leading (stage) dim of every leaf."""
    return jax.tree.map(
        lambda a: P(stage_axis, *([None] * (a.ndim - 1))), stacked_params)


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stacked_params,
                   microbatches: jax.Array,
                   mesh: Mesh,
                   stage_axis: str = AXIS_STAGE) -> jax.Array:
    """Run `stage_fn` as an S-stage GPipe pipeline.

    stage_fn(params_s, x) -> y must preserve the activation shape (the
    classic homogeneous-stage pipeline; embed/unembed live outside).

    stacked_params: pytree with leading dim S (see stack_stage_params),
        sharded over `stage_axis`.
    microbatches: [M, mb, ...] — M microbatches.
    Returns [M, mb, ...] outputs of the final stage.

    Differentiable: grads flow back through the ppermute chain (XLA emits
    the reverse permutes), so this composes with jax.grad/value_and_grad.
    """
    S = mesh.shape[stage_axis]
    M = microbatches.shape[0]
    T = M + S - 1

    p_specs = stage_param_specs(stacked_params, stage_axis)
    x_spec = P(*([None] * microbatches.ndim))

    def per_stage(params, xs):
        # params leaves arrive as [1, ...] (their stage shard); drop the dim
        params = jax.tree.map(lambda a: a[0], params)
        s = lax.axis_index(stage_axis)

        def tick(carry, t):
            prev_out = carry                       # activation shifted in
            mb_idx = jnp.clip(t, 0, M - 1)
            fresh = lax.dynamic_index_in_dim(xs, mb_idx, 0, keepdims=False)
            my_in = jnp.where(s == 0, fresh, prev_out)
            out = stage_fn(params, my_in)
            shifted = lax.ppermute(
                out, stage_axis, [(i, (i + 1) % S) for i in range(S)])
            return shifted, out

        _, outs = lax.scan(tick, jnp.zeros_like(xs[0]), jnp.arange(T))
        # outs[t] on stage s is microbatch (t - s): slice my M valid ticks
        mine = lax.dynamic_slice_in_dim(outs, s, M, axis=0)
        return mine[None]                          # [1, M, mb, ...]

    y = shard_map(per_stage, mesh=mesh,
                  in_specs=(p_specs, x_spec),
                  out_specs=P(stage_axis),
                  check_rep=False)(stacked_params, microbatches)
    # y: [S, M, mb, ...]; the final stage's row is the pipeline output
    return y[-1]


def make_pipeline_fns(stage_fn: Callable, mesh: Mesh,
                      stage_axis: str = AXIS_STAGE):
    """Convenience: returns apply(params, microbatches) closed over mesh."""
    def apply(stacked_params, microbatches):
        return pipeline_apply(stage_fn, stacked_params, microbatches,
                              mesh, stage_axis)
    return apply


# --------------------------------------------------------- MPMD schedules
# Host-level microbatch schedules for the MPMD pipeline (train/mpmd.py):
# per-stage programs on separate meshes, activations shipped stage-to-
# stage through the object store instead of lax.ppermute. Ops are
# ("F", mb) / ("B", mb) tuples in per-stage execution order — or
# ("F", mb, chunk) triples when the stage hosts interleaved virtual
# chunks (schedule_interleaved_1f1b); cross-stage data dependencies
# (F(vs, m) needs F(vs-1, m)'s activation, B(vs, m) needs B(vs+1, m)'s
# input-gradient, in VIRTUAL stage order vs = chunk*S + s) are enforced
# by the dispatcher, not the schedule — these lists only fix each
# stage's LOCAL order, which is what determines both the bubble and the
# grad-accumulation order (replay determinism depends on the latter).

OP_FWD = "F"
OP_BWD = "B"


def op_chunk(op) -> int:
    """Virtual-chunk index of a schedule op; plain (op, mb) tuples are
    chunk 0."""
    return op[2] if len(op) > 2 else 0


def _schedule_chunks(schedules) -> int:
    """Number of virtual chunks per stage in a schedule (v); 1 for the
    plain 2-tuple schedules."""
    v = 1
    for ops in schedules:
        for op in ops:
            v = max(v, op_chunk(op) + 1)
    return v


def schedule_gpipe(n_stages: int, n_microbatches: int):
    """GPipe (all-forward then all-backward) per-stage op lists. Peak
    live activations = n_microbatches on every stage."""
    if n_stages < 1 or n_microbatches < 1:
        raise ValueError("need n_stages >= 1 and n_microbatches >= 1")
    M = n_microbatches
    return [[(OP_FWD, m) for m in range(M)] + [(OP_BWD, m) for m in range(M)]
            for _ in range(n_stages)]


def schedule_1f1b(n_stages: int, n_microbatches: int):
    """Non-interleaved 1F1B (PipeDream-flush): stage s runs
    min(S-1-s, M) warmup forwards, then alternates one-forward/
    one-backward, then drains the remaining backwards. Same bubble as
    GPipe but peak live activations drop from M to min(S-s, M) — the
    schedule the MPMD trainer defaults to."""
    if n_stages < 1 or n_microbatches < 1:
        raise ValueError("need n_stages >= 1 and n_microbatches >= 1")
    S, M = n_stages, n_microbatches
    out = []
    for s in range(S):
        warmup = min(S - 1 - s, M)
        ops = [(OP_FWD, m) for m in range(warmup)]
        for i in range(M - warmup):
            ops.append((OP_FWD, warmup + i))
            ops.append((OP_BWD, i))
        for i in range(max(M - warmup, 0), M):
            ops.append((OP_BWD, i))
        out.append(ops)
    return out


def schedule_interleaved_1f1b(n_stages: int, n_microbatches: int, v: int):
    """Interleaved (virtual-stage) 1F1B, the arXiv 2412.14374 /
    Megatron-style schedule: each physical stage s hosts v virtual
    chunks, chunk c being virtual stage vs = c*S + s of a V = v*S deep
    virtual pipeline. Forwards fill in round-robin blocks of S
    microbatches per chunk, backwards drain the same way, so the flush
    bubble shrinks from (S-1)/(M+S-1) toward (S-1)/(v*M+S-1).

    Ops are (op, mb, chunk) triples, with each chunk's forwards AND
    backwards in strict microbatch order — the backward order is what
    makes grad accumulation, and therefore recovery replay, bit-
    identical to running the V virtual stages as V plain 1F1B stages.

    When M % S == 0 (Megatron's requirement) the closed-form ordering
    is used and the modeled bubble meets the analytic bound exactly:
    stage s runs 2*(S-1-s) + (v-1)*S warmup forwards, then 1F1B
    alternation, forwards/backwards drawn from chunks in round-robin
    blocks of S microbatches (backwards from the deepest chunk first).
    Otherwise a unit-time greedy simulation over the virtual-stage
    dependency DAG (F(vs, m) after F(vs-1, m); B(vs, m) after F(vs, m)
    and B(vs+1, m)) emits a valid — slightly bubblier — schedule;
    either way the result is deadlock-free (the closed form is
    validated by simulate_schedule, the greedy order is a projection
    of a global topological execution).
    """
    if n_stages < 1 or n_microbatches < 1 or v < 1:
        raise ValueError("need n_stages >= 1, n_microbatches >= 1, v >= 1")
    if v == 1:
        return [[(op, mb, 0) for op, mb in ops]
                for ops in schedule_1f1b(n_stages, n_microbatches)]
    if n_microbatches % n_stages == 0:
        return _interleaved_closed_form(n_stages, n_microbatches, v)
    return _interleaved_greedy(n_stages, n_microbatches, v)


def _interleaved_closed_form(S: int, M: int, v: int):
    """Megatron-style interleaved 1F1B for M % S == 0; bubble hits
    (S-1)/(v*M+S-1) under uniform op times."""
    total = v * M
    out = []
    for s in range(S):
        fseq, fptr = [], [0] * v
        for k in range(total):
            c = (k // S) % v
            fseq.append((OP_FWD, fptr[c], c))
            fptr[c] += 1
        bseq, bptr = [], [0] * v
        for k in range(total):
            c = v - 1 - (k // S) % v
            bseq.append((OP_BWD, bptr[c], c))
            bptr[c] += 1
        warmup = min(2 * (S - 1 - s) + (v - 1) * S, total)
        ops = list(fseq[:warmup])
        for i in range(total - warmup):
            ops.append(fseq[warmup + i])
            ops.append(bseq[i])
        ops.extend(bseq[max(total - warmup, 0):])
        out.append(ops)
    simulate_schedule(out)                 # assert deadlock-freedom
    return out


def _interleaved_greedy(S: int, M: int, v: int):
    """Greedy fallback for M % S != 0: backward-first unit-time
    simulation over the virtual-stage DAG; valid for any (S, M, v) but
    does not always reach the analytic bubble bound."""
    V = v * S
    next_f = [0] * V                     # per-virtual-stage microbatch FIFOs
    next_b = [0] * V
    f_done = [[-1] * M for _ in range(V)]   # finish tick, -1 = not yet
    b_done = [[-1] * M for _ in range(V)]
    out = [[] for _ in range(S)]
    remaining = 2 * V * M
    tick = 0
    while remaining:
        ran_this_tick = []
        for s in range(S):
            # Backward-first (1F1B steady state bounds live activations);
            # among ready ops prefer the one earliest in the interleaved
            # round-robin order: blocks of S microbatches per chunk,
            # deeper chunks drain first on the backward side.
            best = None
            for c in range(v):
                vs = c * S + s
                m = next_b[vs]
                if (m < M and 0 <= f_done[vs][m] < tick
                        and (vs == V - 1 or 0 <= b_done[vs + 1][m] < tick)):
                    key = (0, (m // S) * V + (V - 1 - vs))
                    if best is None or key < best[0]:
                        best = (key, OP_BWD, m, c, vs)
            if best is None:
                for c in range(v):
                    vs = c * S + s
                    m = next_f[vs]
                    if (m < M and
                            (vs == 0 or 0 <= f_done[vs - 1][m] < tick)):
                        key = (1, (m // S) * V + vs)
                        if best is None or key < best[0]:
                            best = (key, OP_FWD, m, c, vs)
            if best is not None:
                ran_this_tick.append(best)
        for _key, op, m, c, vs in ran_this_tick:
            if op == OP_FWD:
                next_f[vs] += 1
                f_done[vs][m] = tick
            else:
                next_b[vs] += 1
                b_done[vs][m] = tick
            out[vs % S].append((op, m, c))
            remaining -= 1
        if not ran_this_tick:          # unreachable for a DAG; guard anyway
            raise ValueError("interleaved schedule generator stalled at "
                             f"tick {tick} with {remaining} ops left")
        tick += 1
    return out


def make_schedule(kind: str, n_stages: int, n_microbatches: int,
                  virtual: int = 1):
    if virtual < 1:
        raise ValueError("virtual stage count must be >= 1")
    if kind == "1f1b":
        if virtual > 1:
            return schedule_interleaved_1f1b(
                n_stages, n_microbatches, virtual)
        return schedule_1f1b(n_stages, n_microbatches)
    if kind == "gpipe":
        if virtual > 1:
            raise ValueError(
                "interleaved virtual stages require the '1f1b' schedule")
        return schedule_gpipe(n_stages, n_microbatches)
    raise ValueError(f"unknown pipeline schedule {kind!r} "
                     "(expected '1f1b' or 'gpipe')")


def peak_live_activations(stage_ops, grad_buffers: bool = True) -> int:
    """Buffer high-water mark of one stage's op list, in microbatch-
    sized units: forwards outstanding (saved inputs awaiting their
    backward) plus — once a chunk's first backward has run — that
    chunk's grad-accumulation buffer, which stays live from first
    backward until the step-boundary apply. The grad buffers are what
    the old activation-only count missed: in 1F1B steady state a stage
    holds min(S-s, M) stashes AND its running grad sum, so the true
    peak is min(S-s, M) + 1. Pass grad_buffers=False for the legacy
    activation-only number."""
    live = peak = 0
    accumulating: set = set()
    for op in stage_ops:
        if op[0] == OP_FWD:
            live += 1
        else:
            live -= 1
            accumulating.add(op_chunk(op))
        held = live + (len(accumulating) if grad_buffers else 0)
        peak = max(peak, held)
    return peak


def pipeline_bubble_fraction(n_stages: int, n_microbatches: int,
                             virtual: int = 1) -> float:
    """Analytic flush-bubble fraction: (S-1)/(M+S-1) for GPipe and
    plain 1F1B, shrinking to (S-1)/(v*M+S-1) under v-way interleaving
    (each stage's idle gaps are filled by the other chunks' work); the
    probe reports the measured per-stage idle fraction next to both
    bounds."""
    return (n_stages - 1) / (virtual * n_microbatches + n_stages - 1)


def simulate_schedule(schedules):
    """Dependency-order simulation of per-stage op lists: repeatedly
    sweep the stages, running each stage's next op when its cross-stage
    input is available. Handles both plain (op, mb) and interleaved
    (op, mb, chunk) schedules — dependencies run in VIRTUAL stage order
    vs = chunk*S + s. Returns the global execution order as
    (sweep, stage, op, mb, chunk) tuples; raises if the schedule
    deadlocks (an op whose dependency can never arrive). The MPMD
    dispatcher uses the same sweep against live stage handles; tests
    use this pure version to pin schedule correctness, and recovery
    replay inherits its determinism from the same per-stage order."""
    S = len(schedules)
    V = S * _schedule_chunks(schedules)
    queues = [list(ops) for ops in schedules]
    fwd_done = [set() for _ in range(V)]   # mb whose F(vs, m) completed
    bwd_done = [set() for _ in range(V)]
    order = []
    tick = 0
    while any(queues):
        progressed = False
        for s in range(S):
            while queues[s]:
                op = queues[s][0]
                kind, mb, chunk = op[0], op[1], op_chunk(op)
                vs = chunk * S + s
                if kind == OP_FWD:
                    ready = vs == 0 or mb in fwd_done[vs - 1]
                else:
                    ready = (mb in fwd_done[vs]
                             and (vs == V - 1 or mb in bwd_done[vs + 1]))
                if not ready:
                    break
                queues[s].pop(0)
                (fwd_done if kind == OP_FWD else bwd_done)[vs].add(mb)
                order.append((tick, s, kind, mb, chunk))
                progressed = True
        if not progressed:
            raise ValueError(
                "pipeline schedule deadlocked; remaining per-stage ops: "
                f"{[q[:2] for q in queues]}")
        tick += 1
    return order


def simulate_timeline(schedules, op_time, transfer_time: float = 0.0):
    """Event-timeline model of a schedule's parallel execution: each
    stage executes its op list in order, an op starting at
    max(stage free, dependencies finished + transfer_time) and running
    for op_time(stage, op_kind, chunk) seconds. This is the physics the
    bubble bounds approximate — the probe feeds it MEASURED per-op
    durations to model the parallel step time and per-stage idle
    fraction on hosts that can't run S real processes side by side.

    Returns {"span": makespan, "stage_busy": [...], "stage_idle_frac":
    [...], "bubble_fraction": mean idle frac} (idle measured against
    the full makespan, matching how the trainer's per-stage
    bubble_fraction gauge is computed)."""
    S = len(schedules)
    order = simulate_schedule(schedules)   # also validates deadlock-freedom
    finish: dict = {}                      # (kind, mb, vs) -> finish time
    stage_free = [0.0] * S
    stage_busy = [0.0] * S
    for _tick, s, kind, mb, chunk in order:
        vs = chunk * S + s
        deps = []
        if kind == OP_FWD:
            if vs > 0:
                deps.append(finish[(OP_FWD, mb, vs - 1)] + transfer_time)
        else:
            deps.append(finish[(OP_FWD, mb, vs)])
            V = S * _schedule_chunks(schedules)
            if vs < V - 1:
                deps.append(finish[(OP_BWD, mb, vs + 1)] + transfer_time)
        start = max([stage_free[s]] + deps)
        dur = float(op_time(s, kind, chunk))
        finish[(kind, mb, vs)] = start + dur
        stage_free[s] = start + dur
        stage_busy[s] += dur
    span = max(stage_free) if S else 0.0
    idle = [1.0 - busy / span if span > 0 else 0.0 for busy in stage_busy]
    return {
        "span": span,
        "stage_busy": stage_busy,
        "stage_idle_frac": idle,
        "bubble_fraction": sum(idle) / S if S else 0.0,
    }
