"""Pipeline parallelism: GPipe microbatch schedule over the `stage` mesh axis.

The reference expresses pipeline parallelism only through compiled DAGs —
multi-actor pipelines wired with NCCL P2P channels and a static execution
schedule (reference: python/ray/dag/compiled_dag_node.py:549,
experimental/channel/torch_tensor_nccl_channel.py, schedule in
dag/dag_node_operation.py). The TPU-native equivalent keeps the whole
pipeline inside ONE jitted SPMD program: stage weights are sharded over the
`stage` mesh axis, activations hop stage→stage via `lax.ppermute` (ICI
neighbor transfers), and the GPipe tick loop is a `lax.scan`. XLA overlaps
the ppermute with the next tick's compute; there are no per-hop host round
trips to hide, which is precisely why the µs-scale channel machinery of the
reference is unnecessary here.

Schedule (S stages, M microbatches, T = M + S - 1 ticks):

    tick t: stage s computes microbatch (t - s) if 0 <= t - s < M
            then shifts its output to stage s+1

Bubble fraction = (S-1)/T, the classic GPipe overhead; amortize with M >> S.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.parallel.mesh import AXIS_STAGE


def stack_stage_params(per_stage_params: list):
    """Stack a list of per-stage param pytrees into one tree with a leading
    stage dim (shard it over `stage` with stage_param_specs)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def stage_param_specs(stacked_params, stage_axis: str = AXIS_STAGE):
    """PartitionSpecs sharding the leading (stage) dim of every leaf."""
    return jax.tree.map(
        lambda a: P(stage_axis, *([None] * (a.ndim - 1))), stacked_params)


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stacked_params,
                   microbatches: jax.Array,
                   mesh: Mesh,
                   stage_axis: str = AXIS_STAGE) -> jax.Array:
    """Run `stage_fn` as an S-stage GPipe pipeline.

    stage_fn(params_s, x) -> y must preserve the activation shape (the
    classic homogeneous-stage pipeline; embed/unembed live outside).

    stacked_params: pytree with leading dim S (see stack_stage_params),
        sharded over `stage_axis`.
    microbatches: [M, mb, ...] — M microbatches.
    Returns [M, mb, ...] outputs of the final stage.

    Differentiable: grads flow back through the ppermute chain (XLA emits
    the reverse permutes), so this composes with jax.grad/value_and_grad.
    """
    S = mesh.shape[stage_axis]
    M = microbatches.shape[0]
    T = M + S - 1

    p_specs = stage_param_specs(stacked_params, stage_axis)
    x_spec = P(*([None] * microbatches.ndim))

    def per_stage(params, xs):
        # params leaves arrive as [1, ...] (their stage shard); drop the dim
        params = jax.tree.map(lambda a: a[0], params)
        s = lax.axis_index(stage_axis)

        def tick(carry, t):
            prev_out = carry                       # activation shifted in
            mb_idx = jnp.clip(t, 0, M - 1)
            fresh = lax.dynamic_index_in_dim(xs, mb_idx, 0, keepdims=False)
            my_in = jnp.where(s == 0, fresh, prev_out)
            out = stage_fn(params, my_in)
            shifted = lax.ppermute(
                out, stage_axis, [(i, (i + 1) % S) for i in range(S)])
            return shifted, out

        _, outs = lax.scan(tick, jnp.zeros_like(xs[0]), jnp.arange(T))
        # outs[t] on stage s is microbatch (t - s): slice my M valid ticks
        mine = lax.dynamic_slice_in_dim(outs, s, M, axis=0)
        return mine[None]                          # [1, M, mb, ...]

    y = shard_map(per_stage, mesh=mesh,
                  in_specs=(p_specs, x_spec),
                  out_specs=P(stage_axis),
                  check_rep=False)(stacked_params, microbatches)
    # y: [S, M, mb, ...]; the final stage's row is the pipeline output
    return y[-1]


def make_pipeline_fns(stage_fn: Callable, mesh: Mesh,
                      stage_axis: str = AXIS_STAGE):
    """Convenience: returns apply(params, microbatches) closed over mesh."""
    def apply(stacked_params, microbatches):
        return pipeline_apply(stage_fn, stacked_params, microbatches,
                              mesh, stage_axis)
    return apply
