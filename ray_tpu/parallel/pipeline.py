"""Pipeline parallelism: GPipe microbatch schedule over the `stage` mesh axis.

The reference expresses pipeline parallelism only through compiled DAGs —
multi-actor pipelines wired with NCCL P2P channels and a static execution
schedule (reference: python/ray/dag/compiled_dag_node.py:549,
experimental/channel/torch_tensor_nccl_channel.py, schedule in
dag/dag_node_operation.py). The TPU-native equivalent keeps the whole
pipeline inside ONE jitted SPMD program: stage weights are sharded over the
`stage` mesh axis, activations hop stage→stage via `lax.ppermute` (ICI
neighbor transfers), and the GPipe tick loop is a `lax.scan`. XLA overlaps
the ppermute with the next tick's compute; there are no per-hop host round
trips to hide, which is precisely why the µs-scale channel machinery of the
reference is unnecessary here.

Schedule (S stages, M microbatches, T = M + S - 1 ticks):

    tick t: stage s computes microbatch (t - s) if 0 <= t - s < M
            then shifts its output to stage s+1

Bubble fraction = (S-1)/T, the classic GPipe overhead; amortize with M >> S.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.parallel.mesh import AXIS_STAGE


def stack_stage_params(per_stage_params: list):
    """Stack a list of per-stage param pytrees into one tree with a leading
    stage dim (shard it over `stage` with stage_param_specs)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def stage_param_specs(stacked_params, stage_axis: str = AXIS_STAGE):
    """PartitionSpecs sharding the leading (stage) dim of every leaf."""
    return jax.tree.map(
        lambda a: P(stage_axis, *([None] * (a.ndim - 1))), stacked_params)


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stacked_params,
                   microbatches: jax.Array,
                   mesh: Mesh,
                   stage_axis: str = AXIS_STAGE) -> jax.Array:
    """Run `stage_fn` as an S-stage GPipe pipeline.

    stage_fn(params_s, x) -> y must preserve the activation shape (the
    classic homogeneous-stage pipeline; embed/unembed live outside).

    stacked_params: pytree with leading dim S (see stack_stage_params),
        sharded over `stage_axis`.
    microbatches: [M, mb, ...] — M microbatches.
    Returns [M, mb, ...] outputs of the final stage.

    Differentiable: grads flow back through the ppermute chain (XLA emits
    the reverse permutes), so this composes with jax.grad/value_and_grad.
    """
    S = mesh.shape[stage_axis]
    M = microbatches.shape[0]
    T = M + S - 1

    p_specs = stage_param_specs(stacked_params, stage_axis)
    x_spec = P(*([None] * microbatches.ndim))

    def per_stage(params, xs):
        # params leaves arrive as [1, ...] (their stage shard); drop the dim
        params = jax.tree.map(lambda a: a[0], params)
        s = lax.axis_index(stage_axis)

        def tick(carry, t):
            prev_out = carry                       # activation shifted in
            mb_idx = jnp.clip(t, 0, M - 1)
            fresh = lax.dynamic_index_in_dim(xs, mb_idx, 0, keepdims=False)
            my_in = jnp.where(s == 0, fresh, prev_out)
            out = stage_fn(params, my_in)
            shifted = lax.ppermute(
                out, stage_axis, [(i, (i + 1) % S) for i in range(S)])
            return shifted, out

        _, outs = lax.scan(tick, jnp.zeros_like(xs[0]), jnp.arange(T))
        # outs[t] on stage s is microbatch (t - s): slice my M valid ticks
        mine = lax.dynamic_slice_in_dim(outs, s, M, axis=0)
        return mine[None]                          # [1, M, mb, ...]

    y = shard_map(per_stage, mesh=mesh,
                  in_specs=(p_specs, x_spec),
                  out_specs=P(stage_axis),
                  check_rep=False)(stacked_params, microbatches)
    # y: [S, M, mb, ...]; the final stage's row is the pipeline output
    return y[-1]


def make_pipeline_fns(stage_fn: Callable, mesh: Mesh,
                      stage_axis: str = AXIS_STAGE):
    """Convenience: returns apply(params, microbatches) closed over mesh."""
    def apply(stacked_params, microbatches):
        return pipeline_apply(stage_fn, stacked_params, microbatches,
                              mesh, stage_axis)
    return apply


# --------------------------------------------------------- MPMD schedules
# Host-level microbatch schedules for the MPMD pipeline (train/mpmd.py):
# per-stage programs on separate meshes, activations shipped stage-to-
# stage through the object store instead of lax.ppermute. Ops are
# ("F", mb) / ("B", mb) tuples in per-stage execution order; cross-stage
# data dependencies (F(s, m) needs F(s-1, m)'s activation, B(s, m) needs
# B(s+1, m)'s input-gradient) are enforced by the dispatcher, not the
# schedule — these lists only fix each stage's LOCAL order, which is what
# determines both the bubble and the grad-accumulation order (replay
# determinism depends on the latter).

OP_FWD = "F"
OP_BWD = "B"


def schedule_gpipe(n_stages: int, n_microbatches: int):
    """GPipe (all-forward then all-backward) per-stage op lists. Peak
    live activations = n_microbatches on every stage."""
    if n_stages < 1 or n_microbatches < 1:
        raise ValueError("need n_stages >= 1 and n_microbatches >= 1")
    M = n_microbatches
    return [[(OP_FWD, m) for m in range(M)] + [(OP_BWD, m) for m in range(M)]
            for _ in range(n_stages)]


def schedule_1f1b(n_stages: int, n_microbatches: int):
    """Non-interleaved 1F1B (PipeDream-flush): stage s runs
    min(S-1-s, M) warmup forwards, then alternates one-forward/
    one-backward, then drains the remaining backwards. Same bubble as
    GPipe but peak live activations drop from M to min(S-s, M) — the
    schedule the MPMD trainer defaults to."""
    if n_stages < 1 or n_microbatches < 1:
        raise ValueError("need n_stages >= 1 and n_microbatches >= 1")
    S, M = n_stages, n_microbatches
    out = []
    for s in range(S):
        warmup = min(S - 1 - s, M)
        ops = [(OP_FWD, m) for m in range(warmup)]
        for i in range(M - warmup):
            ops.append((OP_FWD, warmup + i))
            ops.append((OP_BWD, i))
        for i in range(max(M - warmup, 0), M):
            ops.append((OP_BWD, i))
        out.append(ops)
    return out


def make_schedule(kind: str, n_stages: int, n_microbatches: int):
    if kind == "1f1b":
        return schedule_1f1b(n_stages, n_microbatches)
    if kind == "gpipe":
        return schedule_gpipe(n_stages, n_microbatches)
    raise ValueError(f"unknown pipeline schedule {kind!r} "
                     "(expected '1f1b' or 'gpipe')")


def peak_live_activations(stage_ops) -> int:
    """Max forwards outstanding (saved inputs awaiting their backward)
    at any point of one stage's op list — the stage's activation-memory
    high-water mark in microbatches."""
    live = peak = 0
    for op, _mb in stage_ops:
        live += 1 if op == OP_FWD else -1
        peak = max(peak, live)
    return peak


def pipeline_bubble_fraction(n_stages: int, n_microbatches: int) -> float:
    """Analytic flush-bubble fraction (S-1)/(M+S-1) shared by GPipe and
    non-interleaved 1F1B; the probe reports the measured per-stage idle
    fraction next to this bound."""
    return (n_stages - 1) / (n_microbatches + n_stages - 1)


def simulate_schedule(schedules):
    """Dependency-order simulation of per-stage op lists: repeatedly
    sweep the stages, running each stage's next op when its cross-stage
    input is available. Returns the global execution order as
    (tick, stage, op, mb) tuples; raises if the schedule deadlocks
    (an op whose dependency can never arrive). The MPMD dispatcher uses
    the same sweep against live stage handles; tests use this pure
    version to pin schedule correctness."""
    S = len(schedules)
    queues = [list(ops) for ops in schedules]
    fwd_done = [set() for _ in range(S)]   # mb whose F(s, m) completed
    bwd_done = [set() for _ in range(S)]
    order = []
    tick = 0
    while any(queues):
        progressed = False
        for s in range(S):
            while queues[s]:
                op, mb = queues[s][0]
                if op == OP_FWD:
                    ready = s == 0 or mb in fwd_done[s - 1]
                else:
                    ready = (mb in fwd_done[s]
                             and (s == S - 1 or mb in bwd_done[s + 1]))
                if not ready:
                    break
                queues[s].pop(0)
                (fwd_done if op == OP_FWD else bwd_done)[s].add(mb)
                order.append((tick, s, op, mb))
                progressed = True
        if not progressed:
            raise ValueError(
                "pipeline schedule deadlocked; remaining per-stage ops: "
                f"{[q[:2] for q in queues]}")
        tick += 1
    return order
