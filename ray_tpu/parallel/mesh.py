"""Device-mesh construction for dp/fsdp/tp/sp/ep parallelism.

The TPU-native replacement for the reference's process-group world
(reference: torch.distributed init in python/ray/train/torch/config.py:153,
NCCL groups in python/ray/util/collective/): instead of creating
communicator objects, we build one `jax.sharding.Mesh` whose named axes ARE
the parallelism strategies; XLA inserts the collectives (psum over `data` +
`fsdp` for gradients, all-gather over `fsdp` for params, all-to-all /
ppermute over `seq` for ring attention, etc.) and lays them onto ICI.

Axis convention (scaling-book style):
  data    — pure data parallel (gradient psum)
  fsdp    — data parallel with parameter sharding (ZeRO-3 / XLA SPMD)
  tensor  — megatron-style tensor parallel (activations all-reduce)
  seq     — sequence/context parallel (ring attention over this axis)
  expert  — MoE expert parallel
  stage   — pipeline parallel (GPipe microbatch schedule, parallel/pipeline.py)
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_TENSOR = "tensor"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"
AXIS_STAGE = "stage"

MESH_AXES = (AXIS_DATA, AXIS_FSDP, AXIS_EXPERT, AXIS_STAGE, AXIS_SEQ, AXIS_TENSOR)


@dataclasses.dataclass
class MeshConfig:
    """How many ways each parallelism axis is sharded. -1 on one axis means
    'absorb all remaining devices'."""
    data: int = 1
    fsdp: int = -1
    expert: int = 1
    stage: int = 1
    seq: int = 1
    tensor: int = 1

    def resolve(self, n_devices: int) -> "MeshConfig":
        vals = {f.name: getattr(self, f.name)
                for f in dataclasses.fields(self)}
        wild = [k for k, v in vals.items() if v == -1]
        fixed = math.prod(v for v in vals.values() if v != -1)
        if len(wild) > 1:
            raise ValueError("at most one mesh axis may be -1")
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {vals}")
            vals[wild[0]] = n_devices // fixed
        else:
            if fixed != n_devices:
                raise ValueError(
                    f"mesh {vals} needs {fixed} devices, have {n_devices}")
        return MeshConfig(**vals)

    @property
    def shape(self):
        return (self.data, self.fsdp, self.expert, self.stage, self.seq, self.tensor)


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build the 4-axis mesh. Axis order puts `tensor` innermost so
    tensor-parallel collectives ride the fastest ICI links, then `seq`,
    then fsdp/data outermost (DCN-friendly)."""
    devices = list(devices if devices is not None else jax.devices())
    config = (config or MeshConfig()).resolve(len(devices))
    arr = np.asarray(devices).reshape(config.shape)
    return Mesh(arr, MESH_AXES)


def local_mesh() -> Mesh:
    """Single-host mesh over all visible devices on the fsdp axis."""
    return make_mesh(MeshConfig(data=1, fsdp=-1))


# ---------------------------------------------------------------- context
# The "current mesh" lets model code open shard_map islands (ring attention)
# without threading the Mesh through every module.
_CURRENT_MESH: list = []


class use_mesh:
    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    def __enter__(self):
        _CURRENT_MESH.append(self.mesh)
        return self.mesh

    def __exit__(self, *exc):
        _CURRENT_MESH.pop()


def current_mesh() -> Optional[Mesh]:
    return _CURRENT_MESH[-1] if _CURRENT_MESH else None
