"""Logical-axis sharding rules → NamedShardings.

The reference leaves intra-model parallelism to torch FSDP/DeepSpeed inside
the training loop (reference: python/ray/train/torch/train_loop_utils.py
prepare_model); here sharding is a first-class framework layer: model code
annotates parameters/activations with *logical* axis names, and a rule table
maps logical axes to mesh axes per parallelism plan (flax linen
logical-partitioning idiom, re-implemented standalone so models and the
train step share one vocabulary).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.mesh import (AXIS_DATA, AXIS_EXPERT, AXIS_FSDP,
                                   AXIS_SEQ, AXIS_TENSOR)

# Default rule table: logical axis -> mesh axis (or None = replicated).
# Embeddings/MLP widths shard over tensor; the long "model dim" rows shard
# over fsdp (ZeRO-3 resharding, all-gathered per layer by XLA).
DEFAULT_RULES: Dict[str, Optional[object]] = {
    "batch": (AXIS_DATA, AXIS_FSDP),   # global batch over both DP axes
    "seq": AXIS_SEQ,                   # sequence/context parallel
    # vocab tables shard over BOTH model axes on the vocab dim, keeping
    # their d dim replicated: sharding the table's d over fsdp (like the
    # weight matrices) would make the embedding gather/scatter-add want
    # activations laid out d@fsdp while the batch dim already occupies
    # fsdp — GSPMD bridges that conflict with an involuntary full
    # rematerialization in the backward pass (round-4 verdict weak #5).
    # Footprint is unchanged: 4-way sharded either way on a 2x2 mesh.
    "vocab": (AXIS_TENSOR, AXIS_FSDP),
    "embed_lookup": None,              # d dim of the vocab tables
    "embed": AXIS_FSDP,
    "heads": AXIS_TENSOR,
    "kv_heads": AXIS_TENSOR,
    "head_dim": None,
    "mlp": AXIS_TENSOR,
    "experts": AXIS_EXPERT,            # MoE expert-parallel axis
    "layers": None,                    # scan axis; stays replicated (pp later)
    None: None,
}


def make_sharding_rules(**overrides) -> Dict[str, Optional[object]]:
    rules = dict(DEFAULT_RULES)
    rules.update(overrides)
    return rules


def logical_to_mesh_axes(logical: Sequence[Optional[str]],
                         rules: Optional[Dict] = None) -> P:
    rules = rules or DEFAULT_RULES
    spec = []
    used = set()
    for name in logical:
        axis = rules.get(name)
        # a mesh axis may appear at most once in a PartitionSpec
        if axis is not None:
            key = tuple(axis) if isinstance(axis, (tuple, list)) else (axis,)
            if any(a in used for a in key):
                axis = None
            else:
                used.update(key)
        spec.append(tuple(axis) if isinstance(axis, list) else axis)
    return P(*spec)


def param_shardings(mesh: Mesh, logical_tree,
                    rules: Optional[Dict] = None):
    """Map a pytree of logical-axis tuples to NamedShardings."""
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, logical_to_mesh_axes(axes, rules)),
        logical_tree, is_leaf=lambda x: isinstance(x, tuple))


def batch_sharding(mesh: Mesh, rules: Optional[Dict] = None,
                   with_seq: bool = True) -> NamedSharding:
    axes = ("batch", "seq") if with_seq else ("batch",)
    return NamedSharding(mesh, logical_to_mesh_axes(axes, rules))


def constrain(x, logical: Sequence[Optional[str]],
              rules: Optional[Dict] = None):
    """with_sharding_constraint by logical axes (no-op outside jit/mesh)."""
    try:
        return jax.lax.with_sharding_constraint(
            x, logical_to_mesh_axes(logical, rules))
    except (ValueError, RuntimeError):
        return x
