from ray_tpu.parallel.mesh import (MeshConfig, make_mesh, local_mesh,
                                   AXIS_DATA, AXIS_FSDP, AXIS_TENSOR,
                                   AXIS_SEQ, AXIS_EXPERT)
from ray_tpu.parallel.sharding import (logical_to_mesh_axes, make_sharding_rules,
                                       param_shardings, batch_sharding,
                                       constrain)

__all__ = [
    "MeshConfig", "make_mesh", "local_mesh", "AXIS_DATA", "AXIS_FSDP",
    "AXIS_TENSOR", "AXIS_SEQ", "AXIS_EXPERT", "logical_to_mesh_axes",
    "make_sharding_rules", "param_shardings", "batch_sharding", "constrain",
]
