from ray_tpu.experimental.channel import Channel, ReaderView


def broadcast_object(ref, node_ids):
    """Push `ref`'s object to every node in `node_ids` through the
    binomial broadcast tree (owner-directed; see
    node_manager.h_broadcast_object)."""
    import ray_tpu._private.worker as _w
    if _w.global_worker is None:
        raise RuntimeError("ray_tpu.init() first")
    return _w.global_worker.broadcast(ref, node_ids)


def object_locations(refs):
    """Best-effort node ids for locally-known objects (owned refs carry
    their executor-reported location; store-resident objects are local).
    None entries = unknown. Reference: the cached-location plane
    RefBundle/OutputSplitter locality dealing reads."""
    import ray_tpu._private.worker as _w
    if _w.global_worker is None:
        raise RuntimeError("ray_tpu.init() first")
    return _w.global_worker.core.object_locations(refs)


__all__ = ["Channel", "ReaderView", "broadcast_object",
           "object_locations"]
