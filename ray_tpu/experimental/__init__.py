from ray_tpu.experimental.channel import Channel, ReaderView

__all__ = ["Channel", "ReaderView"]
