"""Mutable shared-memory channels (python side of
ray_tpu/native/mutable_channel.cpp; reference:
python/ray/experimental/channel/shared_memory_channel.py). The compiled-DAG
transport: microsecond-scale single-writer/N-reader handoff with no RPC."""

from __future__ import annotations

import ctypes
import os
import pickle
from typing import Any, Optional

from ray_tpu.native.build import build


class _Lib:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            lib = ctypes.CDLL(build("mutable_channel"))
            lib.rtc_create.restype = ctypes.c_void_p
            lib.rtc_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                       ctypes.c_uint32]
            lib.rtc_open.restype = ctypes.c_void_p
            lib.rtc_open.argtypes = [ctypes.c_char_p]
            lib.rtc_close.argtypes = [ctypes.c_void_p]
            lib.rtc_payload.restype = ctypes.c_void_p
            lib.rtc_payload.argtypes = [ctypes.c_void_p]
            lib.rtc_max_size.restype = ctypes.c_uint64
            lib.rtc_max_size.argtypes = [ctypes.c_void_p]
            lib.rtc_write_acquire.restype = ctypes.c_int
            lib.rtc_write_acquire.argtypes = [ctypes.c_void_p,
                                              ctypes.c_int64]
            lib.rtc_write_publish.restype = ctypes.c_int
            lib.rtc_write_publish.argtypes = [ctypes.c_void_p,
                                              ctypes.c_uint64]
            lib.rtc_read_acquire.restype = ctypes.c_int64
            lib.rtc_read_acquire.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint64)]
            lib.rtc_read_release.restype = ctypes.c_int
            lib.rtc_read_release.argtypes = [ctypes.c_void_p,
                                             ctypes.c_uint64]
            lib.rtc_set_closed.restype = ctypes.c_int
            lib.rtc_set_closed.argtypes = [ctypes.c_void_p]
            cls._instance = super().__new__(cls)
            cls._instance.lib = lib
        return cls._instance


class ChannelClosed(Exception):
    pass


class ReaderView:
    """Zero-copy view of the current version; release() acks it."""

    __slots__ = ("data", "version", "_chan", "_released")

    def __init__(self, chan: "Channel", data: memoryview, version: int):
        self._chan = chan
        self.data = data
        self.version = version
        self._released = False

    def release(self):
        if not self._released:
            self._released = True
            self.data = None
            self._chan._lib.rtc_read_release(self._chan._h, self.version)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class Channel:
    """Single-writer / num_readers-reader mutable object."""

    def __init__(self, path: str, max_size: int = 1 << 20,
                 num_readers: int = 1, create: bool = False):
        self._lib = _Lib().lib
        self.path = path
        if create:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            self._h = self._lib.rtc_create(path.encode(), max_size,
                                           num_readers)
        else:
            self._h = self._lib.rtc_open(path.encode())
        if not self._h:
            raise OSError(f"cannot {'create' if create else 'open'} "
                          f"channel {path}")
        base = self._lib.rtc_payload(self._h)
        size = self._lib.rtc_max_size(self._h)
        self._mem = (ctypes.c_uint8 * size).from_address(base)
        self._view = memoryview(self._mem).cast("B")
        self._last_read = 0

    # ------------------------------------------------------------- raw bytes
    def write_bytes(self, payload, timeout_s: float = 10.0):
        mv = memoryview(payload).cast("B")
        if mv.nbytes > len(self._view):
            raise ValueError(f"payload {mv.nbytes} > channel capacity")
        rc = self._lib.rtc_write_acquire(self._h, int(timeout_s * 1000))
        if rc == -1:
            raise TimeoutError("writer blocked: readers have not consumed")
        if rc == -2:
            raise ChannelClosed(self.path)
        self._view[:mv.nbytes] = mv
        self._lib.rtc_write_publish(self._h, mv.nbytes)

    def read_bytes(self, timeout_s: float = 10.0) -> ReaderView:
        size = ctypes.c_uint64()
        v = self._lib.rtc_read_acquire(self._h, self._last_read,
                                       int(timeout_s * 1000),
                                       ctypes.byref(size))
        if v == 0:
            raise TimeoutError("no new version")
        if v == -2:
            raise ChannelClosed(self.path)
        self._last_read = v
        return ReaderView(self, self._view[:size.value], v)

    # -------------------------------------------------------- python objects
    def write(self, value: Any, timeout_s: float = 10.0):
        self.write_bytes(pickle.dumps(value, protocol=5), timeout_s)

    def read(self, timeout_s: float = 10.0) -> Any:
        with self.read_bytes(timeout_s) as view:
            return pickle.loads(view.data)

    def close(self):
        if self._h:
            self._lib.rtc_set_closed(self._h)

    def destroy(self):
        self.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def __reduce__(self):
        return (Channel, (self.path,))


def node_local_path(path: str, node_id: str) -> str:
    """Physical file for a logical channel on one node. Logical channel
    ids are cluster-wide; each node materializes its own local file (on
    real clusters paths never meet, but single-machine test clusters
    share /tmp — without the suffix a producer's channel and its pushed
    mirror would collide on one file)."""
    return f"{path}.{node_id[:12]}"


def open_wait(path: str, timeout_s: float = 30.0) -> Channel:
    """Open a channel that a remote producer (or the node manager, for
    pushed mirrors) may not have created yet."""
    import time
    deadline = time.monotonic() + timeout_s
    while True:
        if os.path.exists(path):
            try:
                return Channel(path)
            except OSError:
                pass   # mid-creation
        if time.monotonic() > deadline:
            raise TimeoutError(f"channel {path} never appeared")
        time.sleep(0.005)


class ChannelWriter:
    """Writer side of a (possibly cross-node) compiled-DAG edge.

    Local readers share the node-local mutable channel (zero-copy);
    remote reader nodes receive each published version through the node
    managers (reference: PushMutableObject fan-out,
    experimental_mutable_object_provider.h:30). spec:
    {"path", "max_size", "local_readers": int,
     "remote": {node_id: reader_count}}.
    """

    def __init__(self, spec: dict, node_call=None):
        self.spec = spec
        self.path = spec["path"]
        self._node_call = node_call
        self.local: Optional[Channel] = None
        if spec.get("local_readers", 0) > 0:
            local_path = node_local_path(self.path, spec["producer_node"])
            os.makedirs(os.path.dirname(local_path), exist_ok=True)
            self.local = Channel(local_path, max_size=spec["max_size"],
                                 num_readers=spec["local_readers"],
                                 create=True)
        self._remote = dict(spec.get("remote") or {})

    def write(self, value: Any, timeout_s: float = 60.0):
        payload = pickle.dumps(value, protocol=5)
        if self.local is not None:
            self.local.write_bytes(payload, timeout_s=timeout_s)
        if self._remote:
            if self._node_call is None:
                from ray_tpu import _get_worker
                self._node_call = _get_worker().node_call
            self._node_call(
                "channel_publish", path=self.path, payload=payload,
                targets=dict(self._remote),
                max_size=self.spec["max_size"],
                write_timeout_s=timeout_s)

    def close(self):
        if self.local is not None:
            self.local.close()
            self.local.destroy()
        if self._remote:
            try:
                if self._node_call is None:
                    from ray_tpu import _get_worker
                    self._node_call = _get_worker().node_call
                self._node_call("channel_close", path=self.path,
                                targets=list(self._remote))
            except Exception:
                pass
