"""OpenTelemetry interop for the built-in tracing plane (reference:
python/ray/util/tracing/tracing_helper.py:34 — the reference hooks
opentelemetry-sdk exporters; here the span store is the GCS task-event
table and this module renders/ships it in the OTLP JSON wire format, so
any OTLP/HTTP collector (Jaeger, Tempo, Grafana) ingests it without an
opentelemetry dependency in the runtime).

Span mapping: one span per task execution; trace_id/span_id come from
the propagated trace context (worker.py spec fields), state transitions
become the span window, task metadata becomes attributes.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional


def _hex_id(value: Optional[str], nbytes: int) -> str:
    """Normalize an internal id to OTLP's fixed-width lowercase hex
    (16-byte trace ids, 8-byte span ids)."""
    h = (value or "").replace("-", "").lower()
    h = "".join(c for c in h if c in "0123456789abcdef")
    want = nbytes * 2
    return (h[:want]).rjust(want, "0") if h else "0" * want


def _attr(key: str, value) -> Dict:
    if isinstance(value, bool):
        return {"key": key, "value": {"boolValue": value}}
    if isinstance(value, int):
        return {"key": key, "value": {"intValue": str(value)}}
    if isinstance(value, float):
        return {"key": key, "value": {"doubleValue": value}}
    return {"key": key, "value": {"stringValue": str(value)}}


def task_events_to_otlp(rows: List[Dict],
                        service_name: str = "ray_tpu") -> Dict:
    """GCS task-event rows -> one OTLP/JSON ExportTraceServiceRequest.

    Both row kinds export: task rows become one span per execution;
    flight-recorder rows (``kind == "runtime_event"``) become child
    spans with their recorded parent links intact, so an engine-slot
    span nests under its Serve request span in Jaeger/Tempo. Runtime
    attrs ride as ``ray_tpu.attr.*`` attributes."""
    spans = []
    for row in rows:
        times = row.get("state_times", {})
        start = times.get("RUNNING")
        if start is None:
            continue
        end = times.get("FINISHED") or times.get("FAILED") or start
        end = max(end, start)
        failed = "FAILED" in times
        runtime = row.get("kind") == "runtime_event"
        attributes = [
            _attr("ray_tpu.task_id", row.get("task_id")),
            _attr("ray_tpu.type", row.get("type")),
            _attr("ray_tpu.node_id", row.get("node_id")),
            _attr("ray_tpu.worker_id", row.get("worker_id")),
            _attr("ray_tpu.state", row.get("state")),
        ]
        if runtime:
            attributes.append(_attr("ray_tpu.category",
                                    row.get("category")))
            attributes.append(_attr("ray_tpu.event_kind",
                                    row.get("event_kind")))
            for k, v in sorted((row.get("attrs") or {}).items()):
                attributes.append(_attr(f"ray_tpu.attr.{k}", v))
        span = {
            "traceId": _hex_id(row.get("trace_id") or row.get("task_id"),
                               16),
            "spanId": _hex_id(row.get("span_id") or row.get("task_id"), 8),
            "name": row.get("name") or "task",
            "kind": 1,   # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(int(start * 1e9)),
            "endTimeUnixNano": str(int(end * 1e9)),
            "attributes": attributes,
            "status": {"code": 2 if failed else 1},
        }
        parent = row.get("parent_span_id")
        if parent:
            span["parentSpanId"] = _hex_id(parent, 8)
        spans.append(span)
    return {
        "resourceSpans": [{
            "resource": {"attributes": [_attr("service.name",
                                              service_name)]},
            "scopeSpans": [{
                "scope": {"name": "ray_tpu.tracing"},
                "spans": spans,
            }],
        }],
    }


def task_events_to_chrome(rows: List[Dict],
                          gauge_series: Optional[List[Dict]] = None
                          ) -> List[Dict]:
    """GCS task-event rows -> chrome://tracing / Perfetto event list.

    Task rows keep the classic layout (pid = node, tid = worker).
    Flight-recorder rows render as per-subsystem tracks (pid =
    ``runtime:<category>``) so engine/store/data/serve phases line up
    under the tasks that caused them; instants emit as ``ph: "i"``.
    Events are sorted by ts and every duration event has dur >= 1us —
    the output loads in either viewer without sanitizing.

    gauge_series: raw time-series rows from the GCS metrics plane
    (``dump_metric_series``: {name, tags, worker_id, samples: [[ts,
    v], ...]}); each renders as a counter track (``ph: "C"``) on the
    ``metrics`` pid, so slot-occupancy / queue-depth curves draw
    alongside the spans that explain them."""
    events: List[Dict] = []
    for s in gauge_series or []:
        label = s.get("name", "metric")
        tags = s.get("tags") or {}
        if tags:
            label += "{" + ",".join(f"{k}={v}"
                                    for k, v in sorted(tags.items())) + "}"
        for ts, value in s.get("samples", []):
            events.append({
                "name": label, "cat": "metrics", "ph": "C",
                "ts": ts * 1e6, "pid": "metrics",
                "args": {"value": value},
            })
    for row in rows:
        times = row.get("state_times", {})
        start = times.get("RUNNING")
        if start is None:
            continue
        end = times.get("FINISHED") or times.get("FAILED")
        end = end if end and end >= start else start
        runtime = row.get("kind") == "runtime_event"
        args = {"task_id": row.get("task_id"), "state": row.get("state"),
                "trace_id": row.get("trace_id"),
                "span_id": row.get("span_id"),
                "parent_span_id": row.get("parent_span_id")}
        if runtime:
            args.update(row.get("attrs") or {})
            ev = {
                "name": row.get("name", "event"),
                "cat": row.get("category", "runtime"),
                "pid": f"runtime:{row.get('category', 'runtime')}",
                "tid": (row.get("worker_id") or "worker")[:8],
                "ts": start * 1e6,
                "args": args,
            }
            if row.get("event_kind") == "instant":
                ev["ph"] = "i"
                ev["s"] = "p"       # process-scoped instant marker
            else:
                ev["ph"] = "X"
                ev["dur"] = max(1.0, (end - start) * 1e6)
        else:
            ev = {
                "name": row.get("name", "task"),
                "cat": row.get("type", "task"),
                "ph": "X",
                "ts": start * 1e6,
                "dur": max(1.0, (end - start) * 1e6),
                "pid": (row.get("node_id") or "node")[:8],
                "tid": (row.get("worker_id") or "worker")[:8],
                "args": args,
            }
        events.append(ev)
    events.sort(key=lambda e: e["ts"])
    return events


def export_otlp(filename: Optional[str] = None,
                endpoint: Optional[str] = None,
                limit: int = 10000,
                service_name: str = "ray_tpu") -> Dict:
    """Export the cluster's spans. filename: write OTLP JSON; endpoint:
    POST to `<endpoint>/v1/traces` (the OTLP/HTTP convention). Returns
    the payload either way."""
    from ray_tpu import _get_worker
    rows = _get_worker().gcs_call("list_task_events", limit=limit)
    payload = task_events_to_otlp(rows, service_name=service_name)
    if filename:
        with open(filename, "w") as f:
            json.dump(payload, f)
    if endpoint:
        import urllib.request
        req = urllib.request.Request(
            endpoint.rstrip("/") + "/v1/traces",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            resp.read()
    return payload


def cluster_stacks() -> Dict:
    """Live Python stacks of every process in the cluster (`ray_tpu
    stack`; reference: `ray stack`)."""
    import asyncio

    from ray_tpu import _get_worker
    core = _get_worker().core
    return asyncio.run_coroutine_threadsafe(
        core.dump_cluster_stacks_async(), core.loop).result(60)


def format_cluster_stacks(dump: Dict) -> str:
    lines = []
    for node_id, node in dump.items():
        lines.append(f"=== node {node_id[:12]} ===")
        if "error" in node:
            lines.append(f"  <{node['error']}>")
            continue
        nm = node.get("node_manager", {})
        lines.append(f"-- node_manager (pid {nm.get('pid')}) --")
        for tname, stack in (nm.get("stacks") or {}).items():
            lines.append(f"thread {tname}:\n{stack}")
        for wid, w in (node.get("workers") or {}).items():
            if "error" in w:
                lines.append(f"-- worker {wid[:12]}: <{w['error']}> --")
                continue
            lines.append(f"-- worker {wid[:12]} (pid {w.get('pid')}, "
                         f"{w.get('mode')}) --")
            for tname, stack in (w.get("stacks") or {}).items():
                lines.append(f"thread {tname}:\n{stack}")
    return "\n".join(lines)
