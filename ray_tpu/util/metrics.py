"""User-defined metrics: Counter / Gauge / Histogram
(reference: python/ray/util/metrics.py feeding the per-node agent's
MetricsAgent, python/ray/_private/metrics_agent.py:483, re-exported to
Prometheus). Here every process pushes its registry to the GCS on a 2s
cadence and the dashboard renders the aggregate at /metrics in
Prometheus text format.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

_registry: Dict[str, "Metric"] = {}
_registry_lock = threading.Lock()
_pusher_started = False
_pusher_stop = threading.Event()
_push_failures = 0
# The snapshot of the most recent FAILED push, kept as (capture_ts,
# payload). Counters are cumulative so a dropped push loses nothing
# locally — but a GCS restart wipes the time-series delta baselines,
# and the first post-restart push would then land the entire cumulative
# history as one giant delta in the current window. Replaying the
# buffered pre-outage snapshot (at its original capture time) first
# re-establishes the baseline, so the current push's delta collapses to
# just the activity since the failure. metrics_ts reset-detection
# tolerates the replay even when the "failed" push actually landed.
_failed_push: Optional[Tuple[float, List[Dict]]] = None


def _ensure_pusher():
    global _pusher_started
    with _registry_lock:
        if _pusher_started:
            return
        _pusher_started = True
        _pusher_stop.clear()
    t = threading.Thread(target=_push_loop, name="metrics-push", daemon=True)
    t.start()


def resume_pusher():
    """Restart the pusher after a stop_pusher() (ray_tpu re-init in the
    same process): metrics registered before the shutdown would
    otherwise never push again. No-op with an empty registry — a
    metric-less process doesn't deserve a thread."""
    with _registry_lock:
        if not _registry:
            return
    _ensure_pusher()


def stop_pusher():
    """Worker shutdown: wake the pusher and let it exit instead of
    spinning forever on is_initialized(). The final snapshot flush is
    the worker's own stop path (worker.py stop_async) — this only
    retires the thread."""
    global _pusher_started
    _pusher_stop.set()
    with _registry_lock:
        _pusher_started = False


def registry_snapshot() -> List[Dict]:
    """Snapshot every registered metric (the push payload). Shared by
    the 2s pusher and the worker's shutdown flush."""
    with _registry_lock:
        return [m._snapshot() for m in _registry.values()]


def _push_interval() -> float:
    """Base cadence jittered +/-25% so a fleet of workers spreads its
    pushes over the control plane instead of synchronizing on it."""
    try:
        from ray_tpu._private.config import cfg
        base = float(cfg.metrics_push_interval_s)
    except Exception:
        base = 2.0
    return base * random.uniform(0.75, 1.25)


def push_once() -> bool:
    """One registry push through the connected worker. Returns True on
    success; the FIRST failure per process logs (at most one line — a
    dead GCS must not spam), later ones stay silent. A failed push
    buffers its snapshot and re-merges it (original capture time) ahead
    of the next successful push — see _failed_push."""
    global _push_failures, _failed_push
    payload: Optional[List[Dict]] = None
    capture_ts = time.time()
    try:
        import ray_tpu
        if not ray_tpu.is_initialized():
            return False
        payload = registry_snapshot()
        if not payload:
            return True
        w = ray_tpu._get_worker()
        core = w.core
        node_id = getattr(core, "node_id", None)
        if _failed_push is not None:
            buf_ts, buf_payload = _failed_push
            w.gcs_call("report_metrics", worker_id=core.worker_id,
                       node_id=node_id, metrics=buf_payload, ts=buf_ts)
            _failed_push = None
        w.gcs_call("report_metrics", worker_id=core.worker_id,
                   node_id=node_id, metrics=payload)
        _push_failures = 0
        return True
    except Exception as e:
        if payload is not None:
            # keep only the newest failed snapshot: it is cumulative, so
            # it subsumes every earlier one (bounded buffer by design)
            _failed_push = (capture_ts, payload)
        if _push_failures == 0:
            logger.warning(
                "metrics push to GCS failed (%s: %s); snapshot buffered "
                "for replay, further failures suppressed until one "
                "succeeds", type(e).__name__, e)
        _push_failures += 1
        return False


def _push_loop():
    while True:
        if _pusher_stop.wait(timeout=_push_interval()):
            return      # clean exit on worker shutdown (stop_pusher)
        try:
            push_once()
        except Exception:
            pass


class Metric:
    _type = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Tuple[str, ...]] = None):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._values: Dict[Tuple, float] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry[name] = self
        _ensure_pusher()

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]):
        merged = {**self._default_tags, **(tags or {})}
        return tuple(sorted(merged.items()))

    def _snapshot(self) -> Dict:
        with self._lock:
            return {"name": self._name, "type": self._type,
                    "help": self._description,
                    "samples": [[list(k), v]
                                for k, v in self._values.items()]}


class Counter(Metric):
    _type = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict] = None):
        if value < 0:
            raise ValueError("counters only increase")
        k = self._key(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value


class Gauge(Metric):
    _type = "gauge"

    def set(self, value: float, tags: Optional[Dict] = None):
        with self._lock:
            self._values[self._key(tags)] = float(value)


class Histogram(Metric):
    _type = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Optional[Tuple[str, ...]] = None):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries or
                                 [0.01, 0.1, 1.0, 10.0, 100.0])
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}

    def observe(self, value: float, tags: Optional[Dict] = None):
        k = self._key(tags)
        with self._lock:
            counts = self._counts.setdefault(
                k, [0] * (len(self.boundaries) + 1))
            i = 0
            while i < len(self.boundaries) and value > self.boundaries[i]:
                i += 1
            counts[i] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value

    def _snapshot(self) -> Dict:
        with self._lock:
            return {"name": self._name, "type": self._type,
                    "help": self._description,
                    "boundaries": self.boundaries,
                    "samples": [[list(k), self._counts[k],
                                 self._sums.get(k, 0.0)]
                                for k in self._counts]}


def counter_snapshot(name: str, value: float, help: str = "",
                     tags: Optional[Dict[str, str]] = None) -> Dict:
    """A registry-shaped counter snapshot built from an externally-held
    cumulative value (daemons like the node manager own their counters
    as plain ints and push them directly — no Metric object needed).
    Compatible with render_prometheus and the GCS time-series ingest."""
    return {"name": name, "type": "counter", "help": help,
            "samples": [[sorted((tags or {}).items()), float(value)]]}


def gauge_snapshot(name: str, value: float, help: str = "",
                   tags: Optional[Dict[str, str]] = None) -> Dict:
    return {"name": name, "type": "gauge", "help": help,
            "samples": [[sorted((tags or {}).items()), float(value)]]}


def _escape_label_value(value) -> str:
    """Prometheus text-format label escaping: inside double quotes,
    backslash, the quote itself and newlines must be escaped — a raw
    tag value like 'us-central1\\n' would otherwise emit unparsable
    exposition text (reference: prometheus text_format spec; the
    reference escapes in its OpenCensus->Prometheus exporter)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    """HELP lines escape backslash and newline (no quotes involved)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _format_tags(key: Tuple) -> str:
    """(k, v) pairs -> escaped label body. The formatter variable is
    deliberately NOT named `v` — earlier revisions shadowed the
    enclosing sample-value loop variable here, emitting the tag value
    where the sample value belonged."""
    return ",".join(f'{k}="{_escape_label_value(tv)}"' for k, tv in key)


def render_prometheus(all_metrics: Dict[str, List[Dict]]) -> str:
    """GCS-aggregated {worker_id: [snapshots]} -> Prometheus text."""
    by_name: Dict[str, List[Dict]] = {}
    for snaps in all_metrics.values():
        for m in snaps:
            by_name.setdefault(m["name"], []).append(m)
    out = []
    for name, ms in sorted(by_name.items()):
        m0 = ms[0]
        if m0.get("help"):
            out.append(f"# HELP {name} {_escape_help(m0['help'])}")
        out.append(f"# TYPE {name} {m0['type']}")
        if m0["type"] == "histogram":
            agg: Dict[Tuple, List] = {}
            for m in ms:
                for tags, counts, total in m["samples"]:
                    key = tuple(map(tuple, tags))
                    if key in agg:
                        agg[key][0] = [a + b for a, b in
                                       zip(agg[key][0], counts)]
                        agg[key][1] += total
                    else:
                        agg[key] = [list(counts), total]
            for key, (counts, total) in agg.items():
                tag_s = _format_tags(key)
                cum = 0
                for b, c in zip(m0["boundaries"], counts):
                    cum += c
                    le = (tag_s + "," if tag_s else "") + f'le="{b}"'
                    out.append(f"{name}_bucket{{{le}}} {cum}")
                cum += counts[-1]
                le = (tag_s + "," if tag_s else "") + 'le="+Inf"'
                out.append(f"{name}_bucket{{{le}}} {cum}")
                brace = f"{{{tag_s}}}" if tag_s else ""
                out.append(f"{name}_count{brace} {cum}")
                out.append(f"{name}_sum{brace} {total}")
        else:
            agg2: Dict[Tuple, float] = {}
            for m in ms:
                for tags, v in m["samples"]:
                    key = tuple(map(tuple, tags))
                    agg2[key] = agg2.get(key, 0.0) + v \
                        if m["type"] == "counter" else v
            for key, v in agg2.items():
                tag_s = _format_tags(key)
                brace = f"{{{tag_s}}}" if tag_s else ""
                out.append(f"{name}{brace} {v}")
    return "\n".join(out) + "\n"
