"""State API: cluster introspection (reference: python/ray/util/state/api.py
— ray list tasks/actors/nodes/objects/jobs, summaries; backed by the GCS
tables and task-event sink instead of a dashboard aggregator)."""

from __future__ import annotations

import collections
from typing import Dict, List, Optional


def _w():
    from ray_tpu import _get_worker
    return _get_worker()


def list_nodes() -> List[Dict]:
    return _w().gcs_call("get_all_nodes")


def list_actors() -> List[Dict]:
    return _w().gcs_call("get_all_actors")


def list_jobs() -> List[Dict]:
    return _w().gcs_call("get_all_jobs")


def list_placement_groups() -> List[Dict]:
    return _w().gcs_call("get_all_placement_groups")


def list_tasks(limit: int = 1000, job_id: Optional[int] = None) -> List[Dict]:
    # task rows only: the flight recorder's runtime events share the GCS
    # sink but are not tasks (see list_runtime_events)
    return _w().gcs_call("list_task_events", limit=limit, job_id=job_id,
                         kind="task")


def list_runtime_events(limit: int = 1000,
                        category: Optional[str] = None) -> List[Dict]:
    """Flight-recorder rows (`ray_tpu/_private/events.py`): spans and
    instants recorded inside tasks/daemons — engine step phases, object
    store spill/restore/transfer, data stage/shuffle windows, serve
    request phases. category filters by subsystem ("engine", "store",
    "data", "serve")."""
    return _w().gcs_call("list_task_events", limit=limit,
                         kind="runtime_event", category=category)


def summarize_runtime_events(limit: int = 10000) -> Dict[str, Dict]:
    """{event_name: {count, total_ms}} over the retained window."""
    out: Dict[str, Dict] = {}
    for r in list_runtime_events(limit=limit):
        times = r.get("state_times", {})
        start = times.get("RUNNING")
        end = times.get("FINISHED", start)
        agg = out.setdefault(r.get("name", "?"),
                             {"count": 0, "total_ms": 0.0})
        agg["count"] += 1
        if start is not None and end is not None:
            agg["total_ms"] += max(0.0, (end - start) * 1e3)
    for agg in out.values():
        agg["total_ms"] = round(agg["total_ms"], 3)
    return out


def query_metrics(name: str, window: float = 60.0, agg: str = "avg",
                  tags: Optional[Dict[str, str]] = None,
                  threshold: Optional[float] = None) -> Dict:
    """Windowed aggregate over the GCS time-series metrics plane (fed by
    every process's 2s registry pushes). agg: "rate"/"sum"/"avg"/"max"/
    "min"/"latest" for counters and gauges; "p50"/"p90"/"p95"/"p99"
    (reconstructed from histogram bucket deltas), "frac_over" (with
    `threshold` — the SLO bad-event fraction) and "buckets" for
    histograms; "series" returns the raw samples. Returns {"value": ...,
    "n_samples": ...}; value is None when nothing matched the window.

    Example::

        state.query_metrics("serve_llm_ttft_ms", window=30, agg="p95")
    """
    return _w().gcs_call("query_metrics", name=name, window=window,
                         agg=agg, tags=tags, threshold=threshold)


def list_metric_series() -> List[Dict]:
    """Per-metric inventory of the time-series plane: name, kind,
    series count, retained samples, staleness."""
    return _w().gcs_call("list_metric_series")


def list_named_actors(namespace: Optional[str] = None) -> List[Dict]:
    return _w().gcs_call("list_named_actors", namespace=namespace)


def list_objects(limit: int = 1000) -> List[Dict]:
    """Objects in this node's shared-memory store plus this process's
    ownership entries (reference: `ray memory` merges the store view with
    per-worker refcount tables).

    Merge order: the shm-store scan runs first, then this process's
    owned table folds INTO it — an object present in both yields ONE
    row (kind="owned+shm", carrying both the store's size_bytes and the
    ownership fields) rather than two. At most `limit` rows return;
    shm rows win the budget because they represent real arena bytes."""
    core = _w().core
    rows: Dict[bytes, Dict] = {}
    if core.store is not None:
        for oid in core.store.list_objects(max_n=limit):
            size = 0
            buf = core.store.get(oid)
            if buf is not None:
                size = len(buf.data) + len(buf.metadata or b"")
                buf.close()
            rows[oid] = {"object_id": oid.hex(), "node_id": core.node_id,
                         "size_bytes": size, "kind": "shm"}
    for oid, entry in list(core.owned.items()):
        owned_fields = {
            "complete": bool(entry.get("complete")),
            "location": entry.get("location"),
            "borrowers": len(entry.get("borrowers") or ()),
            "task_pins": entry.get("submitted", 0),
        }
        row = rows.get(oid)
        if row is not None:
            row.update(owned_fields)
            row["kind"] = "owned+shm"
        elif len(rows) < limit:
            rows[oid] = {"object_id": oid.hex(), "node_id": core.node_id,
                         "kind": "owned", **owned_fields}
    return list(rows.values())[:limit]


def summarize_tasks() -> Dict[str, Dict[str, int]]:
    """{task_name: {state: count}} (reference: ray summary tasks)."""
    summary: Dict[str, Dict[str, int]] = collections.defaultdict(
        lambda: collections.defaultdict(int))
    for t in list_tasks(limit=10000):
        summary[t.get("name", "?")][t.get("state", "?")] += 1
    return {k: dict(v) for k, v in summary.items()}


def summarize_actors() -> Dict[str, int]:
    summary: Dict[str, int] = collections.defaultdict(int)
    for a in list_actors():
        summary[a["state"]] += 1
    return dict(summary)


def cluster_summary() -> Dict:
    import ray_tpu
    nodes = list_nodes()
    return {
        "nodes_alive": sum(1 for n in nodes if n["alive"]),
        "nodes_dead": sum(1 for n in nodes if not n["alive"]),
        "total_resources": ray_tpu.cluster_resources(),
        "available_resources": ray_tpu.available_resources(),
        "actors": summarize_actors(),
    }
