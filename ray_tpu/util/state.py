"""State API: cluster introspection (reference: python/ray/util/state/api.py
— ray list tasks/actors/nodes/objects/jobs, summaries; backed by the GCS
tables and task-event sink instead of a dashboard aggregator)."""

from __future__ import annotations

import collections
from typing import Dict, List, Optional


def _w():
    from ray_tpu import _get_worker
    return _get_worker()


def list_nodes() -> List[Dict]:
    return _w().gcs_call("get_all_nodes")


def list_actors() -> List[Dict]:
    return _w().gcs_call("get_all_actors")


def list_jobs() -> List[Dict]:
    return _w().gcs_call("get_all_jobs")


def list_placement_groups() -> List[Dict]:
    return _w().gcs_call("get_all_placement_groups")


def list_tasks(limit: int = 1000, job_id: Optional[int] = None) -> List[Dict]:
    # task rows only: the flight recorder's runtime events share the GCS
    # sink but are not tasks (see list_runtime_events)
    return _w().gcs_call("list_task_events", limit=limit, job_id=job_id,
                         kind="task")


def list_runtime_events(limit: int = 1000,
                        category: Optional[str] = None) -> List[Dict]:
    """Flight-recorder rows (`ray_tpu/_private/events.py`): spans and
    instants recorded inside tasks/daemons — engine step phases, object
    store spill/restore/transfer, data stage/shuffle windows, serve
    request phases. category filters by subsystem ("engine", "store",
    "data", "serve")."""
    return _w().gcs_call("list_task_events", limit=limit,
                         kind="runtime_event", category=category)


def summarize_runtime_events(limit: int = 10000) -> Dict[str, Dict]:
    """{event_name: {count, total_ms}} over the retained window."""
    out: Dict[str, Dict] = {}
    for r in list_runtime_events(limit=limit):
        times = r.get("state_times", {})
        start = times.get("RUNNING")
        end = times.get("FINISHED", start)
        agg = out.setdefault(r.get("name", "?"),
                             {"count": 0, "total_ms": 0.0})
        agg["count"] += 1
        if start is not None and end is not None:
            agg["total_ms"] += max(0.0, (end - start) * 1e3)
    for agg in out.values():
        agg["total_ms"] = round(agg["total_ms"], 3)
    return out


def query_metrics(name: str, window: float = 60.0, agg: str = "avg",
                  tags: Optional[Dict[str, str]] = None,
                  threshold: Optional[float] = None) -> Dict:
    """Windowed aggregate over the GCS time-series metrics plane (fed by
    every process's 2s registry pushes). agg: "rate"/"sum"/"avg"/"max"/
    "min"/"latest" for counters and gauges; "p50"/"p90"/"p95"/"p99"
    (reconstructed from histogram bucket deltas), "frac_over" (with
    `threshold` — the SLO bad-event fraction) and "buckets" for
    histograms; "series" returns the raw samples. Returns {"value": ...,
    "n_samples": ...}; value is None when nothing matched the window.

    Example::

        state.query_metrics("serve_llm_ttft_ms", window=30, agg="p95")
    """
    return _w().gcs_call("query_metrics", name=name, window=window,
                         agg=agg, tags=tags, threshold=threshold)


def list_metric_series() -> List[Dict]:
    """Per-metric inventory of the time-series plane: name, kind,
    series count, retained samples, staleness."""
    return _w().gcs_call("list_metric_series")


def control_plane_stats(top_n: int = 3) -> Dict:
    """GCS control-plane health: per-handler RPC latency quantiles
    (top_n slowest by p99), global in-flight RPCs, pubsub backlog /
    delivery counters, in-flight actor launches with their current
    phase, and the count of crash black boxes on this session's disk."""
    return _w().gcs_call("control_plane_stats", top_n=top_n)


def list_named_actors(namespace: Optional[str] = None) -> List[Dict]:
    return _w().gcs_call("list_named_actors", namespace=namespace)


def list_objects(limit: int = 1000,
                 include_ledger: bool = True) -> List[Dict]:
    """Objects in this node's shared-memory store, this process's
    ownership entries, and the GCS object-ledger provenance rows joined
    into one table (reference: `ray memory` merges the store view with
    per-worker refcount tables; the ledger adds the cluster-wide and
    historical dimension).

    Merge order (deterministic — same inputs, same rows, same order):

    1. The local shm-store scan runs FIRST — per-object info probes give
       live arena truth (size, ``pins``, ``is_span``, ``stripe``,
       ``age_s``) without pinning or touching LRU.
    2. This process's owned table folds INTO those rows — an object in
       both yields ONE row (kind="owned+shm" carrying store truth AND
       ownership fields); owner-only entries append as kind="owned"
       while the limit budget remains.
    3. GCS object-ledger rows fold in LAST and never override live
       arena truth: a matched row keeps its kind and live size/pins/
       placement, gaining only provenance (``owner``, ``creator_task``,
       ``created_ts``, ``locations``, ``leaked``) and filling
       is_span/pins/age_s when the live scan could not. Unmatched
       ledger rows (objects resident on OTHER nodes) append as
       kind="ledger" within the remaining budget.

    At most `limit` rows return; shm rows win the budget because they
    represent real local arena bytes."""
    core = _w().core
    shm_rows: List[Dict] = []
    if core.store is not None:
        now_sec = core.store.now_sec()
        for oid in core.store.list_objects(max_n=limit):
            info = core.store.object_info(oid)
            if info is None:
                continue
            shm_rows.append({
                "object_id": oid.hex(), "node_id": core.node_id,
                "size_bytes": info["data_size"] + info["meta_size"],
                "kind": "shm", "pins": info["pins"],
                "is_span": info["is_span"], "stripe": info["stripe"],
                "age_s": max(0, now_sec - info["ctime_sec"]),
                "sealed": info["sealed"]})
    ledger_rows: List[Dict] = []
    if include_ledger:
        try:
            ledger_rows = _w().gcs_call("list_object_ledger", limit=limit)
        except Exception:
            ledger_rows = []
    return _merge_object_rows(shm_rows, dict(core.owned), ledger_rows,
                              limit, node_id=core.node_id)


def _merge_object_rows(shm_rows: List[Dict], owned: Dict,
                       ledger_rows: List[Dict], limit: int,
                       node_id: Optional[str] = None,
                       now: Optional[float] = None) -> List[Dict]:
    """Pure merge implementing the order documented on list_objects
    (factored out so the join is testable without a cluster; `now` pins
    the age clock for deterministic tests)."""
    import time as _time
    rows: Dict[str, Dict] = {}
    for r in shm_rows[:limit]:
        rows[r["object_id"]] = dict(r)
    for oid, entry in owned.items():
        hexid = oid.hex() if isinstance(oid, bytes) else oid
        owned_fields = {
            "complete": bool(entry.get("complete")),
            "location": entry.get("location"),
            "borrowers": len(entry.get("borrowers") or ()),
            "task_pins": entry.get("submitted", 0),
        }
        row = rows.get(hexid)
        if row is not None:
            row.update(owned_fields)
            row["kind"] = "owned+shm"
        elif len(rows) < limit:
            rows[hexid] = {"object_id": hexid, "node_id": node_id,
                           "kind": "owned", "pins": None,
                           "is_span": None, "age_s": None,
                           **owned_fields}
    now = _time.time() if now is None else now
    for lr in ledger_rows:
        hexid = lr.get("object_id")
        if not hexid:
            continue
        locations = lr.get("locations") or {}
        prov = {"owner": lr.get("owner"),
                "creator_task": lr.get("creator_task"),
                "created_ts": lr.get("created_ts"),
                "locations": sorted(locations),
                "leaked": bool(lr.get("leaked"))}
        ref_ts = lr.get("sealed_ts") or lr.get("created_ts")
        row = rows.get(hexid)
        if row is not None:
            row.update(prov)   # provenance keys never carry live truth
            if row.get("is_span") is None:
                row["is_span"] = bool(lr.get("is_span"))
            if row.get("pins") is None:
                row["pins"] = sum(int(l.get("pins") or 0)
                                  for l in locations.values())
            if row.get("age_s") is None and ref_ts:
                row["age_s"] = round(max(0.0, now - ref_ts), 3)
        elif len(rows) < limit:
            rows[hexid] = {
                "object_id": hexid,
                "node_id": next(iter(sorted(locations)), None),
                "size_bytes": (lr.get("size") or 0)
                + (lr.get("meta_size") or 0),
                "kind": "ledger", "is_span": bool(lr.get("is_span")),
                "stripe": lr.get("stripe"),
                "pins": sum(int(l.get("pins") or 0)
                            for l in locations.values()),
                "age_s": round(max(0.0, now - ref_ts), 3)
                if ref_ts else None,
                **prov}
    return list(rows.values())[:limit]


def list_object_ledger(limit: int = 1000, node_id: Optional[str] = None,
                       leaked: Optional[bool] = None,
                       live_only: bool = False) -> List[Dict]:
    """Raw provenance rows from the GCS object_ledger table (newest
    first): creator worker/task, owner, size, stripe/span placement,
    lifecycle timestamps (created/sealed/spilled/restored/evicted/
    freed), per-node pins, and the leak flag."""
    return _w().gcs_call("list_object_ledger", limit=limit,
                         node_id=node_id, leaked=leaked,
                         live_only=live_only)


def ledger_stats() -> Dict:
    """{entries, exited_workers, leaked_objects, leaked_bytes}."""
    return _w().gcs_call("ledger_stats")


def ledger_sweep() -> Dict:
    """Run one GCS leak-detector pass NOW (the loop runs it every
    cfg.ledger_sweep_interval_s). Returns {leaked_objects,
    leaked_bytes, newly_flagged}."""
    return _w().gcs_call("ledger_sweep")


def _node_call(address: str, method: str, timeout: float = 10.0, **kw):
    import asyncio
    core = _w().core

    async def call():
        return await core.pool.call(address, method, **kw)
    return asyncio.run_coroutine_threadsafe(call(), core.loop) \
        .result(timeout)


def memory_summary() -> Dict:
    """Cluster memory overview: ledger totals plus each alive node's
    arena occupancy/fragmentation and data-plane counters (from the
    node managers' get_node_info)."""
    out: Dict = {"nodes": []}
    try:
        out["ledger"] = ledger_stats()
    except Exception:
        out["ledger"] = None
    for n in list_nodes():
        if not n.get("alive"):
            continue
        row = {"node_id": n["node_id"]}
        try:
            info = _node_call(n["address"], "get_node_info")
            row["store"] = info.get("store")
            row["data_plane"] = info.get("data_plane")
        except Exception as e:
            row["error"] = str(e)
        out["nodes"].append(row)
    return out


def summarize_tasks() -> Dict[str, Dict[str, int]]:
    """{task_name: {state: count}} (reference: ray summary tasks)."""
    summary: Dict[str, Dict[str, int]] = collections.defaultdict(
        lambda: collections.defaultdict(int))
    for t in list_tasks(limit=10000):
        summary[t.get("name", "?")][t.get("state", "?")] += 1
    return {k: dict(v) for k, v in summary.items()}


def summarize_actors() -> Dict[str, int]:
    summary: Dict[str, int] = collections.defaultdict(int)
    for a in list_actors():
        summary[a["state"]] += 1
    return dict(summary)


def cluster_summary() -> Dict:
    import ray_tpu
    nodes = list_nodes()
    return {
        "nodes_alive": sum(1 for n in nodes if n["alive"]),
        "nodes_dead": sum(1 for n in nodes if not n["alive"]),
        "total_resources": ray_tpu.cluster_resources(),
        "available_resources": ray_tpu.available_resources(),
        "actors": summarize_actors(),
    }
