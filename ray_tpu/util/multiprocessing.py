"""multiprocessing.Pool drop-in backed by cluster tasks (reference:
python/ray/util/multiprocessing/pool.py — Pool API running on actors;
here map work fans out as tasks, imap streams in order, apply_async
returns AsyncResult-compatible futures)."""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional


class AsyncResult:
    def __init__(self, refs, single: bool):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        import ray_tpu
        vals = ray_tpu.get(self._refs, timeout=timeout)
        return vals[0] if self._single else vals

    def wait(self, timeout: Optional[float] = None):
        import ray_tpu
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        import ray_tpu
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        import ray_tpu
        if not self.ready():
            raise ValueError("result not ready")
        try:
            ray_tpu.get(self._refs)
            return True
        except Exception:
            return False


class Pool:
    """Process pool over the cluster. processes bounds in-flight tasks."""

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = ()):
        import ray_tpu
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._n = processes or int(
            ray_tpu.cluster_resources().get("CPU", 2))
        self._initializer = initializer
        self._initargs = initargs
        self._closed = False

    def _wrap(self, func):
        init, initargs = self._initializer, self._initargs
        if init is None:
            return func

        def run(*a, **kw):
            init(*initargs)
            return func(*a, **kw)
        return run

    def _submit(self, func, argslist) -> List:
        import ray_tpu
        rf = ray_tpu.remote(self._wrap(func))
        window: List = []
        out: List = []
        for args in argslist:
            if len(window) >= self._n * 2:
                _, window = ray_tpu.wait(window, num_returns=1)
            ref = rf.remote(*args)
            window.append(ref)
            out.append(ref)
        return out

    def apply(self, func, args=(), kwds=None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func, args=(), kwds=None, callback=None,
                    error_callback=None) -> AsyncResult:
        import ray_tpu
        rf = ray_tpu.remote(self._wrap(func))
        ar = AsyncResult([rf.remote(*args, **(kwds or {}))], single=True)
        if callback is not None or error_callback is not None:
            import threading

            def watch():
                try:
                    value = ar.get()
                except Exception as e:
                    if error_callback is not None:
                        error_callback(e)
                    return
                if callback is not None:
                    callback(value)
            threading.Thread(target=watch, daemon=True).start()
        return ar

    def map(self, func, iterable, chunksize=None) -> List:
        return AsyncResult(self._submit(func, ((x,) for x in iterable)),
                           single=False).get()

    def map_async(self, func, iterable, chunksize=None) -> AsyncResult:
        return AsyncResult(self._submit(func, ((x,) for x in iterable)),
                           single=False)

    def starmap(self, func, iterable, chunksize=None) -> List:
        return AsyncResult(self._submit(func, iterable), single=False).get()

    def imap(self, func, iterable, chunksize=None):
        import ray_tpu
        refs = self._submit(func, ((x,) for x in iterable))
        for r in refs:
            yield ray_tpu.get(r)

    def imap_unordered(self, func, iterable, chunksize=None):
        import ray_tpu
        refs = self._submit(func, ((x,) for x in iterable))
        pending = list(refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            yield ray_tpu.get(ready[0])

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True

    def join(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
