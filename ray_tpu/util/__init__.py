from ray_tpu.util.placement_group import (placement_group,
                                          placement_group_table,
                                          remove_placement_group,
                                          PlacementGroup)
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy, PlacementGroupSchedulingStrategy,
    SpreadSchedulingStrategy)

__all__ = [
    "placement_group", "placement_group_table", "remove_placement_group",
    "PlacementGroup", "NodeAffinitySchedulingStrategy",
    "PlacementGroupSchedulingStrategy", "SpreadSchedulingStrategy",
]
