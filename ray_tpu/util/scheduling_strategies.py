"""Scheduling strategies (reference:
python/ray/util/scheduling_strategies.py:15,41)."""

from __future__ import annotations

from typing import Optional


class PlacementGroupSchedulingStrategy:
    def __init__(self, placement_group,
                 placement_group_bundle_index: int = 0,
                 placement_group_capture_child_tasks: bool = False):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = \
            placement_group_capture_child_tasks


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id: str, soft: bool = False):
        self.node_id = node_id
        self.soft = soft


class SpreadSchedulingStrategy:
    def __str__(self):
        return "SPREAD"
