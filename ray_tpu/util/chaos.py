"""Chaos-testing utilities (reference: ResourceKillerActor / RayletKiller
python/ray/_private/test_utils.py:1433,1536 used by the chaos suites —
kill random nodes during workloads and assert completion; RPC-level
failure injection lives in _private/rpc.py behind
RAY_TPU_TESTING_RPC_FAILURE).

The ``push_chunk`` spec key covers BOTH object-transfer transports: the
legacy msgpack chunk RPCs and the binary data plane (data_plane.py runs
the same injection hook before every raw chunk send, so
``RAY_TPU_TESTING_RPC_FAILURE="push_chunk=0.05"`` keeps exercising
mid-stream transfer aborts after the zero-copy path landed).

Shared-memory chaos lives in its own spec because the failure mode is a
process DEATH, not an exception: ``RAY_TPU_TESTING_SHM_FAILURE=
"shm_create=N"`` makes the armed process SIGKILL itself inside its Nth
``rt_create`` while it HOLDS a stripe mutex mid-mutation (the hook is in
shm_store.cpp) — the worst-case death the robust-mutex recovery path
must repair from. Arm child client processes via ``ShmCreateKiller``."""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, List, Optional


class NodeKiller:
    """Kills random worker nodes of a cluster_utils.Cluster at an
    interval; never touches protected nodes (e.g. the head)."""

    def __init__(self, cluster, interval_s: float = 2.0,
                 protected_node_ids: Optional[List[str]] = None,
                 max_kills: int = 1, seed: int = 0):
        self.cluster = cluster
        self.interval_s = interval_s
        self.protected = set(protected_node_ids or [])
        self.max_kills = max_kills
        self.killed: List[str] = []
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _loop(self):
        while not self._stop.is_set() and len(self.killed) < self.max_kills:
            if self._stop.wait(self.interval_s):
                return
            victims = [n for n in self.cluster.nodes
                       if n.node_id not in self.protected
                       and n.node_id not in self.killed]
            if not victims:
                continue
            v = self._rng.choice(victims)
            try:
                v.kill()
                self.killed.append(v.node_id)
            except Exception:
                pass

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


class ShmCreateKiller:
    """Arms a (child) process to SIGKILL itself mid-``rt_create`` while
    holding a shared-arena stripe mutex — the object-store analog of
    NodeKiller. The kill happens INSIDE the native create, after the
    stripe's heap has been mutated but before the entry is published, so
    survivors must hit ``EOWNERDEAD`` on that stripe's robust mutex,
    repair it, and keep serving puts.

    Usage::

        killer = ShmCreateKiller(nth_create=3)
        proc = ctx.Process(target=..., env-injected via killer.env())
        # or: subprocess.Popen(..., env=killer.env())
        killer.assert_killed(proc)   # died by SIGKILL, not cleanly
    """

    SPEC_ENV = "RAY_TPU_TESTING_SHM_FAILURE"

    def __init__(self, nth_create: int = 1):
        if nth_create < 1:
            raise ValueError("nth_create must be >= 1")
        self.nth_create = nth_create

    def spec(self) -> str:
        return f"shm_create={self.nth_create}"

    def env(self, base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
        """Environment for the victim process (a copy; the arming env var
        must never leak into the parent — the spec is parsed once per
        process at first create)."""
        e = dict(base if base is not None else os.environ)
        e[self.SPEC_ENV] = self.spec()
        return e

    @staticmethod
    def assert_killed(proc, timeout_s: float = 30.0) -> None:
        """Join a multiprocessing.Process victim and assert it died by
        SIGKILL (exitcode -9), i.e. the injection actually fired."""
        proc.join(timeout_s)
        if proc.exitcode != -9:
            raise AssertionError(
                f"victim exitcode {proc.exitcode!r}; expected -9 (SIGKILL "
                "from the shm_create injection)")


class ShmSpanCreateKiller(ShmCreateKiller):
    """Arms a (child) process to SIGKILL itself mid-SPANNING-create —
    inside the native span claim loop, while it holds BOTH the arena's
    span mutex and a member stripe's mutex, with the descriptor still
    CLAIMING and at least one stripe already marked span-owned. The
    worst-case death of the weight-distribution plane: survivors must
    repair on two levels (stripe EOWNERDEAD marks the span broken; span
    EOWNERDEAD frees every claimed member stripe) and the half-claimed
    span must be freed or invalidated WHOLE — never half.

    Spec: ``RAY_TPU_TESTING_SHM_FAILURE="shm_span_create=N"`` (the Nth
    spanning create of the armed process dies). Same ``env()`` /
    ``assert_killed`` usage as :class:`ShmCreateKiller`."""

    def spec(self) -> str:
        return f"shm_span_create={self.nth_create}"


class BroadcastRelayKiller:
    """Injects relay-node failure into tree broadcasts: every
    ``h_request_push`` that carries a non-empty relay list (i.e. an
    interior node of the binomial broadcast tree) fails with the given
    probability, so the root's await observes a dead subtree and must
    retry through the surviving holders. Leaf pushes (empty relay) are
    untouched — exactly the partial-delivery shape a mid-broadcast relay
    death leaves behind.

    Spec: ``RAY_TPU_TESTING_RPC_FAILURE="relay_push=p"``; the env must
    be set BEFORE the daemon tree spawns (the spec is parsed once per
    process)."""

    SPEC_ENV = "RAY_TPU_TESTING_RPC_FAILURE"

    def __init__(self, probability: float = 1.0):
        if not 0.0 < probability <= 1.0:
            raise ValueError("probability must be in (0, 1]")
        self.probability = probability

    def spec(self) -> str:
        return f"relay_push={self.probability}"

    def env(self, base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
        e = dict(base if base is not None else os.environ)
        prior = e.get(self.SPEC_ENV)
        e[self.SPEC_ENV] = f"{prior},{self.spec()}" if prior else self.spec()
        return e


class PrefillExportKiller:
    """Injects failure into the disaggregated-serving KV hand-off: the
    prefill tier's ``prefill_export`` runs the injection hook at entry
    AND right before returning (``serve/disagg.py``), so with
    probability ``p`` an export dies either before any prefill work or
    AFTER the payload object exists but before the hand-off completes —
    the two halves of "prefill replica killed mid-export". The decode
    tier must fall back to LOCAL prefill with exactly-once token
    delivery preserved (nothing has streamed when the rung fails).

    Spec: ``RAY_TPU_TESTING_RPC_FAILURE="prefill_export=p"``; like the
    other RPC-chaos specs it must be in the environment BEFORE the
    victim process parses it (first injection check caches the spec).
    Compose with :class:`ServeReplicaKiller` on the prefill deployment
    to exercise the actor-death (rather than exception) variant."""

    SPEC_ENV = "RAY_TPU_TESTING_RPC_FAILURE"

    def __init__(self, probability: float = 1.0):
        if not 0.0 < probability <= 1.0:
            raise ValueError("probability must be in (0, 1]")
        self.probability = probability

    def spec(self) -> str:
        return f"prefill_export={self.probability}"

    def env(self, base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
        e = dict(base if base is not None else os.environ)
        prior = e.get(self.SPEC_ENV)
        e[self.SPEC_ENV] = f"{prior},{self.spec()}" if prior else self.spec()
        return e

    def arm_local(self):
        """Arm the CURRENT process (direct-instantiation tests): sets
        the env var and resets rpc.py's parsed-spec cache so the next
        injection check re-reads it. Pair with :meth:`disarm_local`."""
        from ray_tpu._private import rpc
        os.environ[self.SPEC_ENV] = self.spec()
        rpc._CHAOS_SPEC = None

    @staticmethod
    def disarm_local():
        from ray_tpu._private import rpc
        os.environ.pop(PrefillExportKiller.SPEC_ENV, None)
        rpc._CHAOS_SPEC = None


class PeerExportKiller:
    """Injects failure into the decode→decode KV fabric: a decode
    replica's ``peer_export`` (serve/disagg.py) runs the injection hook
    at entry AND right before returning, so with probability ``p`` an
    export dies either before the live-trie fingerprint check or AFTER
    the payload exists but before the peer receives it — the two halves
    of "peer replica killed mid-export". The importing replica must
    fall down its ladder (prefill hand-off, then LOCAL prefill) with
    exactly-once token delivery preserved.

    Spec: ``RAY_TPU_TESTING_RPC_FAILURE="peer_export=p"``; like the
    other RPC-chaos specs it must be in the environment BEFORE the
    victim process parses it. Compose with :class:`ServeReplicaKiller`
    on the decode deployment for the actor-death variant."""

    SPEC_ENV = "RAY_TPU_TESTING_RPC_FAILURE"

    def __init__(self, probability: float = 1.0):
        if not 0.0 < probability <= 1.0:
            raise ValueError("probability must be in (0, 1]")
        self.probability = probability

    def spec(self) -> str:
        return f"peer_export={self.probability}"

    def env(self, base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
        e = dict(base if base is not None else os.environ)
        prior = e.get(self.SPEC_ENV)
        e[self.SPEC_ENV] = f"{prior},{self.spec()}" if prior else self.spec()
        return e

    def arm_local(self):
        """Arm the CURRENT process (direct-instantiation tests): sets
        the env var and resets rpc.py's parsed-spec cache so the next
        injection check re-reads it. Pair with :meth:`disarm_local`."""
        from ray_tpu._private import rpc
        os.environ[self.SPEC_ENV] = self.spec()
        rpc._CHAOS_SPEC = None

    @staticmethod
    def disarm_local():
        from ray_tpu._private import rpc
        os.environ.pop(PeerExportKiller.SPEC_ENV, None)
        rpc._CHAOS_SPEC = None


class ShellAttachKiller:
    """Injects failure into the fleet plane's cold-start path: a
    pre-warmed replica shell's ``attach`` (serve/fleet.py ReplicaShell)
    runs the injection hook at entry AND after the callable is
    constructed but before the shell reports ready — the two halves of
    "shell killed mid-weight-attach". The fleet manager must discard
    the poisoned shell and serve the revival through a FRESH shell or a
    cold replica build; requests held at the router (they are parked
    un-submitted until a replica is published) are therefore delivered
    exactly once either way.

    Spec: ``RAY_TPU_TESTING_RPC_FAILURE="shell_attach=p"``; like the
    other RPC-chaos specs the env must be set before the victim process
    parses it (first injection check caches the spec). ``arm_local`` /
    ``disarm_local`` reset the cache for in-process tests."""

    SPEC_ENV = "RAY_TPU_TESTING_RPC_FAILURE"

    def __init__(self, probability: float = 1.0):
        if not 0.0 < probability <= 1.0:
            raise ValueError("probability must be in (0, 1]")
        self.probability = probability

    def spec(self) -> str:
        return f"shell_attach={self.probability}"

    def env(self, base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
        e = dict(base if base is not None else os.environ)
        prior = e.get(self.SPEC_ENV)
        e[self.SPEC_ENV] = f"{prior},{self.spec()}" if prior else self.spec()
        return e

    def arm_local(self):
        """Arm the CURRENT process (direct-instantiation tests): sets
        the env var and resets rpc.py's parsed-spec cache so the next
        injection check re-reads it. Pair with :meth:`disarm_local`."""
        from ray_tpu._private import rpc
        os.environ[self.SPEC_ENV] = self.spec()
        rpc._CHAOS_SPEC = None

    @staticmethod
    def disarm_local():
        from ray_tpu._private import rpc
        os.environ.pop(ShellAttachKiller.SPEC_ENV, None)
        rpc._CHAOS_SPEC = None


class GangRankKiller:
    """Kills one NON-ZERO rank of a sharded serving replica gang
    mid-decode: :class:`~ray_tpu.serve.sharded.ShardedEngineReplica`
    runs the ``gang_rank`` injection hook before every engine step on
    ranks != 0, and when it fires the rank SIGKILLs its own process —
    the crash shape (no exception crosses the actor boundary; the peer
    simply stops answering while rank 0 is mid-stream).

    What the recovery path must then deliver, in order:

    1. rank 0's bounded peer-drain wait times out → the gang WEDGES
       (``_wedged``) — a half-dead SPMD world is never reused;
    2. ``check_health`` raises → the controller retires every member +
       the placement group as one unit (whole-gang drain);
    3. the fleet manager revives through ``checkout_many`` +
       ``attach_shard`` (gang-aware pre-warm) or a cold gang build;
    4. the severed stream re-routes with ``resume_tokens`` — delivered
       tokens ride the prompt, so the client sees each token exactly
       once and a greedy stream continues bit-identically.

    Spec: ``RAY_TPU_TESTING_RPC_FAILURE="gang_rank=p"``; like the other
    RPC-chaos specs the env must reach the victim actor before its
    first injection check caches the parsed spec. ``arm_local`` /
    ``disarm_local`` reset the cache for in-process tests (rank 0 never
    checks the hook, so arming a single-process gang is inert — the
    unit tier patches ``os.kill`` to observe the would-be death)."""

    SPEC_ENV = "RAY_TPU_TESTING_RPC_FAILURE"

    def __init__(self, probability: float = 1.0):
        if not 0.0 < probability <= 1.0:
            raise ValueError("probability must be in (0, 1]")
        self.probability = probability

    def spec(self) -> str:
        return f"gang_rank={self.probability}"

    def env(self, base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
        e = dict(base if base is not None else os.environ)
        prior = e.get(self.SPEC_ENV)
        e[self.SPEC_ENV] = f"{prior},{self.spec()}" if prior else self.spec()
        return e

    def arm_local(self):
        """Arm the CURRENT process (direct-instantiation tests): sets
        the env var and resets rpc.py's parsed-spec cache so the next
        injection check re-reads it. Pair with :meth:`disarm_local`."""
        from ray_tpu._private import rpc
        os.environ[self.SPEC_ENV] = self.spec()
        rpc._CHAOS_SPEC = None

    @staticmethod
    def disarm_local():
        from ray_tpu._private import rpc
        os.environ.pop(GangRankKiller.SPEC_ENV, None)
        rpc._CHAOS_SPEC = None


class StageKiller:
    """Injects stage loss into the elastic MPMD pipeline trainer
    (train/mpmd.py) through BOTH failure channels the recovery path must
    handle:

    * ``stage_step=p`` — the armed stage runs the injection hook at
      forward/backward entry; when it fires, an ACTOR stage SIGKILLs its
      own process mid-step (the crash shape: no exception reaches the
      controller, the actor just dies holding in-flight microbatches),
      while a LOCAL stage handle marks itself dead and raises — the
      in-process stand-in for the same loss. Surviving stages must park
      at the recovery barrier, the controller re-provisions the stage
      from its shard checkpoint, and replay rejoins the pipeline.
    * :meth:`preempt_stage` — writes the stage's preemption-notice
      marker file (the ``tpu.check_preemption_notice`` test channel,
      same file the PR 9 serving lifecycle uses); the stage's watch
      thread reports ``preempting`` and the controller migrates it at
      the NEXT step boundary — the graceful notice → drain → replace
      path, zero replayed steps.

    Spec: ``RAY_TPU_TESTING_RPC_FAILURE="stage_step=p"``; like the other
    RPC-chaos specs the env must be set before the victim process parses
    it (first injection check caches the spec). ``arm_local`` /
    ``disarm_local`` reset the cache for in-process tests."""

    SPEC_ENV = "RAY_TPU_TESTING_RPC_FAILURE"

    def __init__(self, probability: float = 1.0):
        if not 0.0 < probability <= 1.0:
            raise ValueError("probability must be in (0, 1]")
        self.probability = probability

    def spec(self) -> str:
        return f"stage_step={self.probability}"

    def env(self, base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
        e = dict(base if base is not None else os.environ)
        prior = e.get(self.SPEC_ENV)
        e[self.SPEC_ENV] = f"{prior},{self.spec()}" if prior else self.spec()
        return e

    def arm_local(self):
        """Arm the CURRENT process (LocalStageHandle tests): sets the
        env var and resets rpc.py's parsed-spec cache so the next
        injection check re-reads it. Pair with :meth:`disarm_local`."""
        from ray_tpu._private import rpc
        os.environ[self.SPEC_ENV] = self.spec()
        rpc._CHAOS_SPEC = None

    @staticmethod
    def disarm_local():
        from ray_tpu._private import rpc
        os.environ.pop(StageKiller.SPEC_ENV, None)
        rpc._CHAOS_SPEC = None

    # ------------------------------------------- graceful notice channel
    @staticmethod
    def preempt_stage(marker_path: str) -> None:
        """Flip a LIVE stage's preemption notice by creating its marker
        file (the path passed to the stage as ``preempt_marker``; the
        watch thread polls it at ``mpmd_health_poll_s``)."""
        with open(marker_path, "w") as f:
            f.write("preempt\n")

    @staticmethod
    def clear_notice(marker_path: str) -> None:
        try:
            os.remove(marker_path)
        except FileNotFoundError:
            pass


class GcsRpcDelayer:
    """Injects latency into ONE named GCS handler: the observability
    wrapper (``_private/gcs_obs.py``) checks the spec before dispatching
    each ``h_*`` RPC and sleeps the armed handler on the event loop
    (``asyncio.sleep`` — other handlers keep flowing, exactly the shape
    of one slow table scan wedging a single RPC family). Used to drive
    the slow-handler span path (``gcs.rpc`` runtime events over
    ``gcs_slow_rpc_ms``) and the p99 histogram tail deterministically.

    Spec: ``RAY_TPU_TESTING_GCS_RPC_DELAY="gcs_rpc=handler:ms"`` where
    ``handler`` is the RPC method name without the ``h_`` prefix (e.g.
    ``gcs_rpc=kv_get:75``); comma-compose entries to delay several
    handlers. The env must reach the GCS process before its first RPC
    (the spec is parsed once and cached); ``arm_local`` /
    ``disarm_local`` reset the cache for in-process GcsServer tests."""

    SPEC_ENV = "RAY_TPU_TESTING_GCS_RPC_DELAY"

    def __init__(self, handler: str, delay_ms: float):
        if delay_ms < 0:
            raise ValueError("delay_ms must be >= 0")
        self.handler = handler
        self.delay_ms = delay_ms

    def spec(self) -> str:
        return f"gcs_rpc={self.handler}:{self.delay_ms}"

    def env(self, base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
        e = dict(base if base is not None else os.environ)
        prior = e.get(self.SPEC_ENV)
        e[self.SPEC_ENV] = f"{prior},{self.spec()}" if prior else self.spec()
        return e

    def arm_local(self):
        """Arm the CURRENT process (in-process GcsServer tests): sets
        the env var and resets gcs_obs's parsed-spec cache so the next
        dispatch re-reads it. Pair with :meth:`disarm_local`."""
        from ray_tpu._private import gcs_obs
        os.environ[self.SPEC_ENV] = self.spec()
        gcs_obs._DELAY_SPEC = None

    @staticmethod
    def disarm_local():
        from ray_tpu._private import gcs_obs
        os.environ.pop(GcsRpcDelayer.SPEC_ENV, None)
        gcs_obs._DELAY_SPEC = None


class ServeReplicaKiller:
    """Kill serve replica actors mid-request (streaming included) and
    let the controller's reconcile loop replace them — the serving
    analog of NodeKiller. Used by the kill-replica-mid-stream tests to
    assert that per-replica resources (inference-engine slots, queue
    gauges) come back clean on the replacement replica."""

    def __init__(self, app_name: str, deployment_name: str, seed: int = 0):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self.killed = 0
        self.preempted = 0
        self._rng = random.Random(seed)

    def _controller(self):
        from ray_tpu.serve.api import _get_controller
        return _get_controller()

    def _info(self):
        import ray_tpu
        return ray_tpu.get(self._controller().get_deployment_info.remote(
            self.app_name, self.deployment_name), timeout=30)

    def replicas(self) -> List:
        return list(self._info().get("replicas") or [])

    def kill_one(self, prefer_busy: bool = False) -> bool:
        """Kill one (random) replica actor; returns False when none are
        up. The controller detects the death on its next reconcile and
        builds a replacement. prefer_busy=True targets a replica with a
        non-empty queue when one exists — the interesting victim for
        stream-resume tests (killing an idle replica severs nothing)."""
        import ray_tpu
        reps = self.replicas()
        if not reps:
            return False
        victim = None
        if prefer_busy:
            for r in reps:
                try:
                    if ray_tpu.get(r.get_queue_len.remote(),
                                   timeout=10) > 0:
                        victim = r
                        break
                except Exception:
                    continue
        if victim is None:
            victim = self._rng.choice(reps)
        try:
            ray_tpu.kill(victim)
        except Exception:
            return False
        self.killed += 1
        return True

    def preempt_one(self, grace_s: Optional[float] = None) -> bool:
        """Graceful-notice preemption: the controller delivers a drain
        notice to one (random) replica, drops it from the routing table,
        and pre-starts a replacement — exercising the notice -> drain ->
        replace path instead of the crash path. The drained replica is
        force-killed at the grace deadline if its queue never empties."""
        import ray_tpu
        n = len(self.replicas())
        if not n:
            return False
        ok = ray_tpu.get(self._controller().preempt_replica.remote(
            self.app_name, self.deployment_name,
            self._rng.randrange(n), grace_s), timeout=30)
        if ok:
            self.preempted += 1
        return bool(ok)

    def wait_for_replacement(self, timeout_s: float = 60.0,
                             min_running: int = 1, handle=None) -> bool:
        """Block until the deployment again reports >= min_running
        replicas under a NEW version set (the controller bumps the
        router view when the replica set changes). Pass the test's
        DeploymentHandle as `handle` to ALSO wait for router-view
        propagation — the handle's router must have applied the new
        replica set, otherwise the next `handle.remote(...)` races the
        stale routing table and lands on the corpse."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                info = self._info()
                reps = list(info.get("replicas") or [])
                if len(reps) >= min_running:
                    import ray_tpu
                    # replacement must actually answer, not just exist
                    ray_tpu.get([r.get_queue_len.remote() for r in reps],
                                timeout=10)
                    if handle is None:
                        return True
                    router = handle._router
                    router.refresh(force=True)
                    with router.lock:
                        if (router.version >= info["version"]
                                and len(router.replicas) >= min_running):
                            return True
            except Exception:
                pass
            time.sleep(0.5)
        return False


class QuotaLeaseRevoker:
    """Revoke a proxy's tenant-quota lease at the GCS mid-traffic and
    assert the no-over-admission invariant of the lease protocol
    (serve/fleet.py QuotaLeaseClient + _private/gcs.py quota_lease_*):

      * the GCS ESCROWS the revoked share — the lease row stays in the
        denominator of the per-proxy split, so surviving proxies' shares
        do NOT grow while the revoked proxy may still be admitting;
      * the revoked proxy learns of the revocation on its next renew and
        degrades every local bucket to ``quota_lease_conservative_frac``
        of its last share (strictly below the escrowed share), so the
        cluster-wide admitted rate can only FALL during the window;
      * the proxy re-acquires on a later renew tick and is restored to a
        full (re-split) share — degradation is transient, not sticky.

    Unlike the RPC-failure killers this is not env-spec injection: the
    action is a real ``quota_lease_revoke`` control call against a live
    GCS, so the revoker holds a ``gcs_call``-style callable (e.g.
    ``worker.gcs_call`` or a test's fake-GCS shim)."""

    def __init__(self, gcs_call, seed: int = 0):
        self._call = gcs_call
        self.revoked: List[str] = []
        self._rng = random.Random(seed)

    def status(self) -> Dict:
        """Raw ``quota_lease_status`` row: epoch, lease table (with
        per-row ``revoked`` flags), cluster tenant burn totals."""
        return self._call("quota_lease_status") or {}

    def lease_ids(self, live_only: bool = True) -> List[str]:
        rows = self.status().get("leases") or []
        return [r["proxy_id"] for r in rows
                if not (live_only and r.get("revoked"))]

    def revoke(self, proxy_id: str) -> bool:
        """Revoke one proxy's lease. Returns False when the GCS has no
        such lease (already expired/released)."""
        ok = bool(self._call("quota_lease_revoke", proxy_id=proxy_id))
        if ok:
            self.revoked.append(proxy_id)
        return ok

    def revoke_one(self) -> Optional[str]:
        """Revoke a random live lease; returns its proxy_id or None when
        no live lease exists."""
        ids = self.lease_ids(live_only=True)
        if not ids:
            return None
        pid = self._rng.choice(sorted(ids))
        return pid if self.revoke(pid) else None

    def wait_for_degraded(self, lease_client, timeout_s: float = 15.0,
                          poke=None) -> bool:
        """Block until ``lease_client`` (the victim proxy's
        QuotaLeaseClient) has observed the revocation and entered
        conservative mode. The client only learns on a renew, and
        renews ride the request path — pass ``poke`` (a zero-arg
        callable, e.g. ``lambda: client.maybe_renew(now)``) to drive
        ticks when no traffic is flowing."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if poke is not None:
                try:
                    poke()
                except Exception:
                    pass
            if lease_client.revoked:
                return True
            time.sleep(0.05)
        return False

    def wait_for_release(self, lease_client, timeout_s: float = 15.0,
                         poke=None) -> bool:
        """Block until the victim has re-acquired a live lease (revoked
        flag cleared) — the restore half of the chaos round-trip."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if poke is not None:
                try:
                    poke()
                except Exception:
                    pass
            if not lease_client.revoked and lease_client.stats()["epoch"]:
                return True
            time.sleep(0.05)
        return False
