"""Chaos-testing utilities (reference: ResourceKillerActor / RayletKiller
python/ray/_private/test_utils.py:1433,1536 used by the chaos suites —
kill random nodes during workloads and assert completion; RPC-level
failure injection lives in _private/rpc.py behind
RAY_TPU_TESTING_RPC_FAILURE)."""

from __future__ import annotations

import random
import threading
import time
from typing import List, Optional


class NodeKiller:
    """Kills random worker nodes of a cluster_utils.Cluster at an
    interval; never touches protected nodes (e.g. the head)."""

    def __init__(self, cluster, interval_s: float = 2.0,
                 protected_node_ids: Optional[List[str]] = None,
                 max_kills: int = 1, seed: int = 0):
        self.cluster = cluster
        self.interval_s = interval_s
        self.protected = set(protected_node_ids or [])
        self.max_kills = max_kills
        self.killed: List[str] = []
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _loop(self):
        while not self._stop.is_set() and len(self.killed) < self.max_kills:
            if self._stop.wait(self.interval_s):
                return
            victims = [n for n in self.cluster.nodes
                       if n.node_id not in self.protected
                       and n.node_id not in self.killed]
            if not victims:
                continue
            v = self._rng.choice(victims)
            try:
                v.kill()
                self.killed.append(v.node_id)
            except Exception:
                pass

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
