"""Collective communication between actors/tasks.

API parity with the reference's ray.util.collective (reference:
python/ray/util/collective/collective.py:40-655 — init_collective_group,
allreduce/allgather/reducescatter/broadcast/barrier/send/recv), with the
backends re-based for TPU:

- "xla": device-tensor collectives. Rendezvous through GCS KV (replaces the
  NCCL TCP store), then `jax.distributed.initialize`; the actual collectives
  are XLA ICI/DCN ops inside jit (psum/all_gather) over the processes'
  global devices — NCCL/cupy is replaced entirely.
- "store": host-array collectives through the object store + GCS KV
  (replaces pygloo). Works anywhere, used for small host payloads and in
  CPU-only tests.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu._private.config import cfg

_GROUPS: Dict[str, "CollectiveGroup"] = {}


class CollectiveGroup:
    def __init__(self, world_size: int, rank: int, backend: str,
                 group_name: str):
        self.world_size = world_size
        self.rank = rank
        self.backend = backend
        self.group_name = group_name
        self._seq = 0


def _kv():
    from ray_tpu import _get_worker
    return _get_worker()


def _kv_put(key: str, value: bytes):
    _kv().gcs_call("kv_put", ns="collective", key=key.encode(), value=value)


def _kv_get(key: str, timeout: float = 60.0) -> bytes:
    deadline = time.monotonic() + timeout
    while True:
        v = _kv().gcs_call("kv_get", ns="collective", key=key.encode())
        if v is not None:
            return v
        if time.monotonic() > deadline:
            raise TimeoutError(f"collective rendezvous timed out on {key}")
        time.sleep(cfg.wait_poll_floor_s)


def init_collective_group(world_size: int, rank: int,
                          backend: str = "store",
                          group_name: str = "default") -> CollectiveGroup:
    if backend == "xla":
        _init_jax_distributed(world_size, rank, group_name)
    group = CollectiveGroup(world_size, rank, backend, group_name)
    _GROUPS[group_name] = group
    return group


def _init_jax_distributed(world_size: int, rank: int, group_name: str):
    """jax.distributed.initialize with GCS-KV coordinator rendezvous
    (our KV replaces NCCL's TCP store; reference rendezvous:
    util/collective master address through named actors)."""
    import jax

    key = f"{group_name}:coordinator"
    if rank == 0:
        import socket
        from ray_tpu._private.rpc import node_ip_address
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        addr = f"{node_ip_address()}:{port}"
        _kv_put(key, addr.encode())
    else:
        addr = _kv_get(key).decode()
    jax.distributed.initialize(coordinator_address=addr,
                               num_processes=world_size,
                               process_id=rank)


def destroy_collective_group(group_name: str = "default"):
    _GROUPS.pop(group_name, None)


def get_rank(group_name: str = "default") -> int:
    return _GROUPS[group_name].rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _GROUPS[group_name].world_size


def _store_exchange(group: CollectiveGroup, payload: np.ndarray,
                    tag: str) -> List[np.ndarray]:
    """All ranks publish, all ranks read all (store backend primitive).
    The trailing ack round keeps every rank's ObjectRef alive until all
    ranks have fetched it (otherwise the owner GCs the object under a
    slower reader)."""
    import cloudpickle as cp
    import ray_tpu
    seq = group._seq
    group._seq += 1
    key = f"{group.group_name}:{tag}:{seq}"
    ref = ray_tpu.put(payload)
    _kv_put(f"{key}:{group.rank}", cp.dumps(ref))
    outs: List[Optional[np.ndarray]] = []
    for r in range(group.world_size):
        if r == group.rank:
            outs.append(payload)
            continue
        blob = _kv_get(f"{key}:{r}")
        outs.append(ray_tpu.get(cp.loads(blob)))
    _kv_put(f"{key}:ack:{group.rank}", b"1")
    for r in range(group.world_size):
        _kv_get(f"{key}:ack:{r}")
    del ref
    return outs


_REDUCERS = {"sum": np.add, "product": np.multiply,
             "min": np.minimum, "max": np.maximum}


def allreduce(tensor, group_name: str = "default", op: str = "sum"):
    group = _GROUPS[group_name]
    if group.backend == "xla":
        return _xla_allreduce(tensor, op)
    arr = np.asarray(tensor)
    parts = _store_exchange(group, arr, "ar")
    reducer = _REDUCERS[op]
    out = parts[0].copy()
    for p in parts[1:]:
        out = reducer(out, p)
    return out


# jit cache for the device-collective closures: jax.jit keys on function
# identity, so a fresh shard_map per call would retrace + recompile every
# invocation. Keyed by (kind, op/src, ndev) — shapes/dtypes are handled by
# jit's own cache once the callable is stable.
_XLA_FNS: Dict[tuple, Any] = {}


def _xla_mesh():
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices())
    return Mesh(devs, ("all",)), jax.local_device_count()


def _xla_allreduce(tensor, op: str):
    """Cross-process device allreduce: under jax.distributed all processes'
    devices form one global mesh; psum over it."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    if op not in ("sum", "max", "min", "product"):
        raise ValueError(f"unsupported allreduce op {op!r}")
    mesh, n_local = _xla_mesh()
    key = ("ar", op, mesh.size)
    fn = _XLA_FNS.get(key)
    if fn is None:
        def f(x):
            import jax.lax as lax
            if op == "product":
                # pprod via psum of logs is lossy — use all_gather+reduce;
                # P() replicates per process onto its local devices: take
                # one representative per process (homogeneous hosts)
                g = lax.all_gather(x, "all")
                return jnp.prod(g[::n_local], axis=0)
            out = getattr(lax, {"sum": "psum", "max": "pmax",
                                "min": "pmin"}[op])(x, "all")
            if op == "sum":
                # P() replicates each process's tensor onto all of its
                # local devices; psum then counts every local copy —
                # divide the multiplicity back out. Integer dtypes use
                # integer floordiv (exact: value is k*n_local) so large
                # sums never round through float32.
                if jnp.issubdtype(x.dtype, jnp.integer):
                    out = out // n_local
                else:
                    out = (out / n_local).astype(x.dtype)
            return out

        fn = jax.jit(shard_map(f, mesh=mesh, in_specs=P(), out_specs=P(),
                               check_rep=False))
        _XLA_FNS[key] = fn
    return fn(tensor)


def allgather(tensor, group_name: str = "default") -> List[np.ndarray]:
    group = _GROUPS[group_name]
    if group.backend == "xla":
        return _xla_allgather(tensor)
    arr = np.asarray(tensor)
    return _store_exchange(group, arr, "ag")


def _xla_allgather(tensor) -> List:
    """Device all_gather across all processes' devices; returns one entry
    per process (mirrors the store backend's per-rank list)."""
    import jax
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    mesh, n_local = _xla_mesh()
    key = ("ag", mesh.size)
    fn = _XLA_FNS.get(key)
    if fn is None:
        def f(x):
            # every shard computes the identical [n_dev, ...] stack, so the
            # result is replicated — out_specs=P() returns it once
            return jax.lax.all_gather(x, "all")

        fn = jax.jit(shard_map(f, mesh=mesh, in_specs=P(), out_specs=P(),
                               check_rep=False))
        _XLA_FNS[key] = fn
    out = fn(tensor)
    # one representative copy per process (each process's tensor was
    # replicated over its local devices)
    return [out[i] for i in range(0, out.shape[0], n_local)]


def reducescatter(tensor, group_name: str = "default", op: str = "sum"):
    group = _GROUPS[group_name]
    out = allreduce(tensor, group_name, op)
    chunks = np.array_split(out, group.world_size)
    return chunks[group.rank]


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    group = _GROUPS[group_name]
    if group.backend == "xla":
        return _xla_broadcast(tensor, src_rank, group)
    import ray_tpu
    import cloudpickle as cp
    seq = group._seq
    group._seq += 1
    key = f"{group.group_name}:bc:{seq}"
    if group.rank == src_rank:
        ref = ray_tpu.put(np.asarray(tensor))
        _kv_put(key, cp.dumps(ref))
        # hold the ref until every rank has fetched
        for r in range(group.world_size):
            if r != src_rank:
                _kv_get(f"{key}:ack:{r}")
        del ref
        return np.asarray(tensor)
    out = ray_tpu.get(cp.loads(_kv_get(key)))
    _kv_put(f"{key}:ack:{group.rank}", b"1")
    return out


def barrier(group_name: str = "default"):
    group = _GROUPS[group_name]
    seq = group._seq
    group._seq += 1
    _kv_put(f"{group.group_name}:bar:{seq}:{group.rank}", b"1")
    for r in range(group.world_size):
        _kv_get(f"{group.group_name}:bar:{seq}:{r}")


def _xla_broadcast(tensor, src_rank: int, group: CollectiveGroup):
    """Device broadcast as a psum where non-source processes contribute
    zeros (every process passes a same-shaped buffer, like the reference
    API). Stays entirely on-device over ICI/DCN."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    mesh, n_local = _xla_mesh()
    contrib = (jnp.asarray(tensor) if group.rank == src_rank
               else jnp.zeros_like(jnp.asarray(tensor)))
    key = ("bc", mesh.size)
    fn = _XLA_FNS.get(key)
    if fn is None:
        def f(x):
            # divide the per-process local-device multiplicity back out;
            # integer floordiv keeps large integer payloads exact
            s = jax.lax.psum(x, "all")
            if jnp.issubdtype(x.dtype, jnp.integer):
                return s // n_local
            return (s / n_local).astype(x.dtype)

        fn = jax.jit(shard_map(f, mesh=mesh, in_specs=P(), out_specs=P(),
                               check_rep=False))
        _XLA_FNS[key] = fn
    return fn(contrib)


# NOTE: send/recv are host-mediated (object store + GCS KV) on every
# backend: XLA has no true point-to-point primitive outside compiled
# collectives (ppermute needs all devices in the program); device-to-device
# P2P belongs to compiled-DAG channels (experimental/channel.py), not this
# eager API.
_P2P_SEQ: Dict[tuple, int] = {}


def send(tensor, dst_rank: int, group_name: str = "default"):
    group = _GROUPS[group_name]
    import ray_tpu
    import cloudpickle as cp
    key = (group_name, group.rank, dst_rank)
    seq = _P2P_SEQ.get(key, 0)
    _P2P_SEQ[key] = seq + 1
    ref = ray_tpu.put(np.asarray(tensor))
    tag = f"{group.group_name}:p2p:{seq}:{group.rank}:{dst_rank}"
    _kv_put(tag, cp.dumps(ref))
    _kv_get(f"{tag}:ack")       # hold ref until the receiver has fetched
    del ref


def recv(src_rank: int, group_name: str = "default"):
    group = _GROUPS[group_name]
    import ray_tpu
    import cloudpickle as cp
    key = (group_name, src_rank, group.rank)
    seq = _P2P_SEQ.get(key, 0)
    _P2P_SEQ[key] = seq + 1
    tag = f"{group.group_name}:p2p:{seq}:{src_rank}:{group.rank}"
    blob = _kv_get(tag)
    out = ray_tpu.get(cp.loads(blob))
    _kv_put(f"{tag}:ack", b"1")
    return out
