"""ActorPool: load-balance tasks over a fixed set of actors (reference:
python/ray/util/actor_pool.py)."""

from __future__ import annotations

from typing import Any, Callable, Iterable, List


class ActorPool:
    def __init__(self, actors: List):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._pending = []          # (fn, value) waiting for an actor
        self._results_order = []    # submission-ordered futures

    def submit(self, fn: Callable, value: Any):
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = actor
            self._results_order.append(ref)
        else:
            self._pending.append((fn, value))

    def _dispatch_pending(self):
        while self._pending and self._idle:
            fn, value = self._pending.pop(0)
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = actor
            self._results_order.append(ref)

    def has_next(self) -> bool:
        return bool(self._results_order or self._pending)

    def get_next(self, timeout=None):
        import ray_tpu
        if not self.has_next():
            raise StopIteration("no pending results")
        self._dispatch_pending()
        ref = self._results_order[0]
        # a timeout must leave the future retrievable and the actor busy
        # (reference behavior: ray.util.ActorPool keeps the future on
        # TimeoutError); a task error consumes the future like a result
        try:
            value = ray_tpu.get(ref, timeout=timeout)
        except TimeoutError:
            raise
        except Exception:
            self._consume(ref)
            raise
        self._consume(ref)
        return value

    def _consume(self, ref):
        if ref in self._results_order:
            self._results_order.remove(ref)
        actor = self._future_to_actor.pop(ref, None)
        if actor is not None:
            self._idle.append(actor)
        self._dispatch_pending()

    def get_next_unordered(self, timeout=None):
        import ray_tpu
        if not self.has_next():
            raise StopIteration("no pending results")
        self._dispatch_pending()
        ready, _ = ray_tpu.wait(list(self._results_order), num_returns=1,
                                timeout=timeout)
        if not ready:
            raise TimeoutError("no result ready within timeout")
        ref = ready[0]
        try:
            value = ray_tpu.get(ref)
        except Exception:
            self._consume(ref)
            raise
        self._consume(ref)
        return value

    def map(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def has_free(self) -> bool:
        return bool(self._idle)

    def push(self, actor):
        self._idle.append(actor)
        self._dispatch_pending()

    def pop_idle(self):
        return self._idle.pop() if self._idle else None
