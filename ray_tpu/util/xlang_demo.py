"""Cross-language demo/test targets: importable callables and classes
that non-Python frontends (C++ API) reference by "module:attr"
(reference: cross-language function/actor descriptors in the cpp/java
frontends)."""

from __future__ import annotations


class Accumulator:
    """Stateful target for cross-language actor calls."""

    def __init__(self, start=0):
        self.total = int(start)

    def add(self, x):
        self.total += int(x)
        return self.total

    def get(self):
        return self.total


def scale(x, k):
    return x * k
