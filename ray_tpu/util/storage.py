"""External storage plane: one URI-addressed filesystem abstraction for
object spilling and Train checkpoints (reference:
python/ray/_private/external_storage.py:72 — filesystem-or-cloud spill
targets; python/ray/train/_internal/storage.py StorageContext — pyarrow
filesystems behind RunConfig.storage_path).

URIs: plain paths and file:// map to the local filesystem via the
standard library (no import cost on hot paths); any other scheme
(gs://, s3://, memory://, ...) resolves through fsspec. memory:// is
fsspec's in-process filesystem and doubles as the fake-remote backend in
tests — the code path is byte-for-byte the one gs:// takes."""

from __future__ import annotations

import os
from typing import List, Tuple


def _split(uri: str) -> Tuple[str, str]:
    """-> (scheme, path); plain paths get scheme ''.

    file: URIs normalize to plain absolute paths in BOTH RFC-8089
    forms — file:///x and the single-slash file:/x. Without the
    second case, file:/x has no "://" and used to be treated as a
    cwd-RELATIVE path, silently creating a literal 'file:' directory
    (round-4 verdict weak #4)."""
    if "://" in uri:
        scheme, rest = uri.split("://", 1)
        if scheme == "file":
            return "", "/" + rest.lstrip("/")
        return scheme, uri
    if uri.startswith("file:"):
        return "", "/" + uri[len("file:"):].lstrip("/")
    return "", uri


def validate_root(uri: str, what: str = "storage") -> str:
    """Validate a spill/checkpoint/persist root URI: local paths must be
    absolute (a relative root silently writes into whatever CWD the
    daemon happens to have). Returns the URI unchanged."""
    scheme, path = _split(uri)
    if not scheme and not os.path.isabs(path):
        raise ValueError(
            f"{what} root {uri!r} resolves to the relative local path "
            f"{path!r}; use an absolute path or a scheme:// URI")
    return uri


def is_remote(uri: str) -> bool:
    return _split(uri)[0] != ""


def _fs(uri: str):
    import fsspec
    return fsspec.core.url_to_fs(uri)   # (fs, path)


def join(uri: str, *parts: str) -> str:
    if is_remote(uri):
        return "/".join([uri.rstrip("/")] + [p.strip("/") for p in parts])
    # file:// normalizes to a plain local path
    return os.path.join(_split(uri)[1], *parts)


def makedirs(uri: str) -> None:
    scheme, path = _split(uri)
    if not scheme:
        os.makedirs(path, exist_ok=True)
        return
    fs, p = _fs(uri)
    fs.makedirs(p, exist_ok=True)


def write_bytes(uri: str, data: bytes) -> None:
    scheme, path = _split(uri)
    if not scheme:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        return
    fs, p = _fs(uri)
    parent = p.rsplit("/", 1)[0]
    if parent:
        fs.makedirs(parent, exist_ok=True)
    with fs.open(p, "wb") as f:
        f.write(data)


def read_bytes(uri: str) -> bytes:
    scheme, path = _split(uri)
    if not scheme:
        with open(path, "rb") as f:
            return f.read()
    fs, p = _fs(uri)
    with fs.open(p, "rb") as f:
        return f.read()


def exists(uri: str) -> bool:
    scheme, path = _split(uri)
    if not scheme:
        return os.path.exists(path)
    fs, p = _fs(uri)
    return fs.exists(p)


def delete(uri: str) -> bool:
    scheme, path = _split(uri)
    try:
        if not scheme:
            os.unlink(path)
        else:
            fs, p = _fs(uri)
            fs.rm(p)
        return True
    except (OSError, FileNotFoundError):
        return False


def delete_dir(uri: str) -> bool:
    scheme, path = _split(uri)
    try:
        if not scheme:
            import shutil
            shutil.rmtree(path, ignore_errors=True)
        else:
            fs, p = _fs(uri)
            fs.rm(p, recursive=True)
        return True
    except (OSError, FileNotFoundError):
        return False


def listdir(uri: str) -> List[str]:
    """Child names (not full paths); empty list if missing."""
    scheme, path = _split(uri)
    try:
        if not scheme:
            return sorted(os.listdir(path))
        fs, p = _fs(uri)
        return sorted(x.rstrip("/").rsplit("/", 1)[-1]
                      for x in fs.ls(p, detail=False))
    except (OSError, FileNotFoundError):
        return []


def upload_dir(local_dir: str, uri: str) -> None:
    """Recursively copy a local directory to the URI."""
    for root, _dirs, files in os.walk(local_dir):
        rel = os.path.relpath(root, local_dir)
        for fname in files:
            dst = join(uri, fname) if rel == "." \
                else join(uri, rel.replace(os.sep, "/"), fname)
            with open(os.path.join(root, fname), "rb") as f:
                write_bytes(dst, f.read())


def download_dir(uri: str, local_dir: str) -> None:
    """Recursively copy a URI directory tree to a local directory."""
    scheme, path = _split(uri)
    os.makedirs(local_dir, exist_ok=True)
    if not scheme:
        import shutil
        for name in os.listdir(path):
            src = os.path.join(path, name)
            dst = os.path.join(local_dir, name)
            if os.path.isdir(src):
                shutil.copytree(src, dst, dirs_exist_ok=True)
            else:
                shutil.copy2(src, dst)
        return
    fs, p = _fs(uri)
    base = p.rstrip("/")
    for info in fs.find(base):
        rel = info[len(base):].lstrip("/")
        dst = os.path.join(local_dir, *rel.split("/"))
        os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
        with fs.open(info, "rb") as f:
            data = f.read()
        with open(dst, "wb") as f:
            f.write(data)
