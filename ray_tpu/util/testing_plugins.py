"""Test-support runtime-env plugin (the xlang_demo pattern: a tiny
importable module so worker processes can load cross-process test
targets). Exercised by tests/test_runtime_env_plugins.py via
RAY_TPU_RUNTIME_ENV_PLUGINS=ray_tpu.util.testing_plugins:TokenPlugin."""

from __future__ import annotations

from ray_tpu._private.runtime_env_plugins import RuntimeEnvPlugin


class TokenPlugin(RuntimeEnvPlugin):
    """Owns the runtime_env key "token": exports its value (plus proof
    it saw the full env dict) into the task's environment."""

    name = "token"
    priority = 5     # before env_vars: explicit env_vars must win

    def setup(self, value, renv, ctx, worker):
        ctx.env_vars["TOKEN_PLUGIN_VALUE"] = str(value)
        ctx.env_vars["TOKEN_PLUGIN_SAW_KEYS"] = ",".join(sorted(renv))
