"""Distributed FIFO queue backed by an actor (reference:
python/ray/util/queue.py)."""

from __future__ import annotations

import time
from typing import Any, List, Optional


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        import collections
        self.maxsize = maxsize
        self.items = collections.deque()

    def put(self, item) -> bool:
        if self.maxsize > 0 and len(self.items) >= self.maxsize:
            return False
        self.items.append(item)
        return True

    def get(self):
        if not self.items:
            return False, None
        return True, self.items.popleft()

    def qsize(self) -> int:
        return len(self.items)

    def empty(self) -> bool:
        return not self.items

    def full(self) -> bool:
        return self.maxsize > 0 and len(self.items) >= self.maxsize


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        import ray_tpu
        cls = ray_tpu.remote(_QueueActor)
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0.1)
        opts.setdefault("max_concurrency", 4)
        self.actor = cls.options(**opts).remote(maxsize)

    def put(self, item, block: bool = True, timeout: Optional[float] = None):
        import ray_tpu
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if ray_tpu.get(self.actor.put.remote(item), timeout=30):
                return
            if not block or (deadline and time.monotonic() > deadline):
                raise Full()
            time.sleep(0.02)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        import ray_tpu
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = ray_tpu.get(self.actor.get.remote(), timeout=30)
            if ok:
                return item
            if not block or (deadline and time.monotonic() > deadline):
                raise Empty()
            time.sleep(0.02)

    def put_nowait(self, item):
        self.put(item, block=False)

    def get_nowait(self):
        return self.get(block=False)

    def qsize(self) -> int:
        import ray_tpu
        return ray_tpu.get(self.actor.qsize.remote(), timeout=30)

    def empty(self) -> bool:
        return self.qsize() == 0

    def __reduce__(self):
        q = object.__new__(Queue)
        return (_rebuild_queue, (self.actor,))


def _rebuild_queue(actor):
    q = object.__new__(Queue)
    q.actor = actor
    return q
