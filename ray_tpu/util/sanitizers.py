"""Runtime sanitizers for the asyncio-native control plane.

The reference structures its concurrency with per-component
`instrumented_io_context` event loops (src/ray/common/asio/ — per-handler
event stats + lag probes, event_stats.cc), single-thread assertions
(src/ray/util/thread_checker.h) and tsan/asan CI builds (.bazelrc
build:tsan/build:asan). This package's runtime is asyncio, so the analogs
are loop-shaped rather than thread-shaped:

- **Loop sanitizer** (`maybe_install`): times EVERY callback/handle the
  loop runs (one process-wide patch of `asyncio.events.Handle._run`),
  aggregates per-callback event stats (count / total / max — the
  event_stats.cc surface), and records a ring of "slow callback" events
  whose duration exceeded the threshold. A callback that blocks the loop
  is this runtime's data race: every daemon on that loop stalls.
- **Lag probe**: a background task that sleeps a fixed interval and
  measures scheduling drift — the loop-lag metric the reference derives
  from instrumented contexts.
- **SingleLoopChecker**: thread_checker.h analog — pins the first loop
  that touches a component and asserts every later touch happens on the
  same loop.

Native code gets the real thing: `native/build.py:build_selftest`
compiles standalone harnesses (native/shm_store_selftest.cpp) with
`-fsanitize=address,undefined`, and the suite runs them as
subprocesses (tests/test_sanitizers.py).

Enable with ``RAY_TPU_LOOP_SANITIZER=1`` (threshold via
``RAY_TPU_SLOW_CALLBACK_S``, default 0.1s). Daemons call
`maybe_install()` at startup; stats ride the existing `dump_stacks`
debug RPC so `ray_tpu stack` shows them cluster-wide.
"""

from __future__ import annotations

import asyncio
import collections
import os
import threading
import time
from typing import Dict, Optional

_LOCK = threading.Lock()
_INSTALLED = False
_SLOW_RING_MAX = 64


class _Stats:
    """Per-callback-name event stats + slow-event ring (event_stats.cc
    shape: count, cumulative time, max time). Locked: the Handle._run
    patch is process-wide, so executor-thread loops record concurrently
    with the main loop's snapshot()."""

    def __init__(self) -> None:
        self.events: Dict[str, list] = {}  # name -> [count, total_s, max_s]
        self.slow = collections.deque(maxlen=_SLOW_RING_MAX)
        self.lag_max_s = 0.0
        self.lag_avg_s = 0.0
        self._lag_n = 0
        self._mu = threading.Lock()

    def record(self, name: str, dt: float, threshold: float) -> None:
        with self._mu:
            e = self.events.get(name)
            if e is None:
                e = self.events[name] = [0, 0.0, 0.0]
            e[0] += 1
            e[1] += dt
            if dt > e[2]:
                e[2] = dt
            if dt >= threshold:
                self.slow.append({"callback": name,
                                  "duration_s": round(dt, 4),
                                  "ts": time.time()})

    def record_lag(self, lag: float) -> None:
        with self._mu:
            self._lag_n += 1
            self.lag_avg_s += (lag - self.lag_avg_s) / self._lag_n
            if lag > self.lag_max_s:
                self.lag_max_s = lag

    def snapshot(self, top: int = 20) -> Dict:
        with self._mu:
            ranked = sorted(self.events.items(),
                            key=lambda kv: -kv[1][1])[:top]
            return {
                "handlers": {n: {"count": c, "total_s": round(t, 4),
                                 "max_s": round(m, 4)}
                             for n, (c, t, m) in ranked},
                "slow_callbacks": list(self.slow),
                "loop_lag": {"max_s": round(self.lag_max_s, 4),
                             "avg_s": round(self.lag_avg_s, 5)},
            }


_STATS = _Stats()


def enabled() -> bool:
    return os.environ.get("RAY_TPU_LOOP_SANITIZER", "") not in ("", "0")


def threshold_s() -> float:
    return float(os.environ.get("RAY_TPU_SLOW_CALLBACK_S", "0.1"))


def _callback_name(cb) -> str:
    # unwrap the functools/bound-method layers asyncio hands us
    for attr in ("__func__", "func"):
        inner = getattr(cb, attr, None)
        if inner is not None:
            cb = inner
    name = getattr(cb, "__qualname__", None) or repr(cb)
    mod = getattr(cb, "__module__", "") or ""
    if mod.startswith("asyncio"):
        # Task.__step etc. — attribute to the coroutine being driven
        return name
    return f"{mod}.{name}" if mod else name


def _patch_handle_run() -> None:
    orig = asyncio.events.Handle._run
    thr = threshold_s()

    def timed_run(self):
        t0 = time.perf_counter()
        try:
            return orig(self)
        finally:
            dt = time.perf_counter() - t0
            if dt >= 1e-4:  # skip no-op wakeups; keep the dict small
                cb = getattr(self, "_callback", None)
                # a Task step is more useful named after its coroutine
                task = getattr(cb, "__self__", None)
                if isinstance(task, asyncio.Task):
                    coro = task.get_coro()
                    name = getattr(coro, "__qualname__", repr(coro))
                else:
                    name = _callback_name(cb)
                _STATS.record(name, dt, thr)

    asyncio.events.Handle._run = timed_run


async def _lag_probe(interval: float = 0.05) -> None:
    """Measure event-loop scheduling drift: how much later than asked
    the loop wakes us. Runs forever; daemons fire-and-forget it."""
    loop = asyncio.get_running_loop()
    while True:
        t0 = loop.time()
        await asyncio.sleep(interval)
        _STATS.record_lag(max(0.0, loop.time() - t0 - interval))


def maybe_install(start_lag_probe: bool = True) -> bool:
    """Install the loop sanitizer if RAY_TPU_LOOP_SANITIZER is set.
    Idempotent; safe to call from every daemon main. Returns True when
    active. Must be called with a running loop for the lag probe to
    start (otherwise stats-only)."""
    global _INSTALLED
    if not enabled():
        return False
    with _LOCK:
        if not _INSTALLED:
            _patch_handle_run()
            _INSTALLED = True
    if start_lag_probe:
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        if loop is not None and not getattr(loop, "_rt_lag_probe", None):
            loop._rt_lag_probe = loop.create_task(_lag_probe())
    return True


def stats_snapshot() -> Optional[Dict]:
    """Current sanitizer stats, or None when inactive (the dump_stacks
    RPC attaches this so `ray_tpu stack` surfaces loop health)."""
    if not _INSTALLED:
        return None
    return _STATS.snapshot()


class SingleLoopChecker:
    """thread_checker.h analog: asserts a component is only touched from
    the event loop that first touched it.

    Usage: ``self._checker = SingleLoopChecker("NodeManager")`` then
    ``self._checker.check()`` at hot entry points. check() is a no-op
    unless the sanitizer is enabled, so production pays one attribute
    load + one truthiness test."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._loop = None
        self._active = enabled()

    def check(self) -> None:
        if not self._active:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        if self._loop is None:
            self._loop = loop
        elif loop is not self._loop:
            raise AssertionError(
                f"{self.name}: touched from loop {loop!r}, owned by "
                f"{self._loop!r} — single-loop discipline violated")
