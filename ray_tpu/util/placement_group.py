"""Placement groups (reference: python/ray/util/placement_group.py:41,145;
GCS-side 2-phase reservation in _private/gcs.py)."""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional


class PlacementGroup:
    def __init__(self, pg_id: str, bundles: List[Dict[str, float]],
                 strategy: str):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy

    def ready(self) -> bool:
        return self.wait(timeout=0)

    def wait(self, timeout: Optional[float] = 30.0) -> bool:
        from ray_tpu import _get_worker
        w = _get_worker()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            info = w.gcs_call("get_placement_group", pg_id=self.id)
            if info is not None and info["state"] == "CREATED":
                return True
            if info is not None and info["state"] == "REMOVED":
                return False
            if deadline is not None and time.monotonic() > deadline:
                return False
            # infeasible at creation time: ask GCS to try again
            w.gcs_call("create_placement_group", pg_id=self.id,
                       bundles=self.bundle_specs, strategy=self.strategy)
            time.sleep(0.2)

    def node_ids(self) -> Optional[List[str]]:
        from ray_tpu import _get_worker
        info = _get_worker().gcs_call("get_placement_group", pg_id=self.id)
        return info["node_ids"] if info else None

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs, self.strategy))


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    from ray_tpu import _get_worker
    w = _get_worker()
    pg_id = os.urandom(8).hex()
    w.gcs_call("create_placement_group", pg_id=pg_id, bundles=bundles,
               strategy=strategy, name=name)
    return PlacementGroup(pg_id, bundles, strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    from ray_tpu import _get_worker
    _get_worker().gcs_call("remove_placement_group", pg_id=pg.id)


def placement_group_table() -> List[Dict]:
    from ray_tpu import _get_worker
    return _get_worker().gcs_call("get_all_placement_groups")
