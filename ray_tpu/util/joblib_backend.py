"""Joblib backend running parallel work as cluster tasks (reference:
python/ray/util/joblib/ — register_ray + RayBackend over the Pool API).
Usage:

    from ray_tpu.util.joblib_backend import register_ray_tpu
    register_ray_tpu()
    with joblib.parallel_backend("ray_tpu"):
        Parallel(n_jobs=8)(delayed(f)(i) for i in range(100))
"""

from __future__ import annotations


def register_ray_tpu():
    from joblib import register_parallel_backend
    from joblib._parallel_backends import MultiprocessingBackend

    class RayTpuBackend(MultiprocessingBackend):
        """Joblib backend whose pool is the cluster-task Pool."""

        supports_timeout = True

        def effective_n_jobs(self, n_jobs):
            import ray_tpu
            if n_jobs == 1:
                return 1
            cpus = int(ray_tpu.cluster_resources().get("CPU", 1)) \
                if ray_tpu.is_initialized() else 4
            return cpus if n_jobs in (-1, None) else min(n_jobs, cpus)

        def configure(self, n_jobs=1, parallel=None, prefer=None,
                      require=None, **kwargs):
            n_jobs = self.effective_n_jobs(n_jobs)
            if n_jobs == 1:
                raise FallbackToBackend(None)
            from ray_tpu.util.multiprocessing import Pool
            self._pool = Pool(processes=n_jobs)
            self.parallel = parallel
            return n_jobs

        def terminate(self):
            if getattr(self, "_pool", None) is not None:
                self._pool.terminate()
                self._pool = None

    try:
        from joblib._parallel_backends import FallbackToBackend
    except ImportError:  # pragma: no cover
        class FallbackToBackend(Exception):
            pass

    register_parallel_backend("ray_tpu", RayTpuBackend)
