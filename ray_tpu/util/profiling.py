"""Per-step time/FLOP attribution: where does a train/decode step go?

ROADMAP item 5 has `train_step_mfu` stuck at 0.564 with zero in-runtime
visibility into where step time is spent; the offline harness
(reports/mfu_ablate.py) answers it once per ablation run, not live. The
step-level attribution that both the Gemma-on-TPU serving study (arXiv
2605.25645) and the MPMD pipeline work (arXiv 2412.14374) lean on before
optimizing is exactly: FLOPs from the compiled program
(``compiled.cost_analysis()``) divided over measured wall phases.

``StepProfiler`` combines three marks per step with a FLOP/byte cost:

- **host gap**  — time between the previous step's end and this step's
  begin (logging, checkpointing, scheduler bookkeeping);
- **data wait** — begin → ``data_ready()`` (input pipeline);
- **compute**   — ``data_ready()`` → end (dispatch + device, the caller
  blocks on the step's output before ending).

and emits, per step (through the existing metrics registry, so the
values land in /metrics AND the GCS time-series plane):

  runtime_<name>_mfu             gauge   FLOPs / (wall * peak)
  runtime_<name>_mfu_compute     gauge   FLOPs / (compute * peak) — the
                                         hardware-bound ceiling
  runtime_<name>_phase_ms        gauge   tags: phase=compute|host_gap|
                                         data_wait
  runtime_<name>_roofline_bound  gauge   min(1, intensity / machine
                                         balance): the MFU an ideal
                                         schedule of this program could
                                         reach on this chip
  runtime_<name>_tokens_per_s    gauge   when step_begin(tokens=) given

plus (``emit_span=True``) a flight-recorder span per step carrying the
same attribution, so the stuck-MFU question is readable off the
timeline instead of requiring the offline harness.

Cost sources, in order of preference: ``wrap_jit`` (AOT lower+compile
once per input shape — cost_analysis comes free and the compiled
executable is reused, no double compile), ``observe_compiled`` (caller
already has an AOT executable), ``set_cost`` (analytic formulas — the
inference engine's decode step uses ``decode_step_flops`` because
re-lowering its decode program would trip the compile-once invariant).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)

# Peak dense-matmul FLOP/s per chip by accelerator kind (bf16). The CPU
# entry is a NOMINAL figure — CPU MFU is a relative utilization signal
# for tests/dev boxes, not a hardware claim. RAY_TPU_PEAK_FLOPS
# overrides everything.
_PEAK_FLOPS_BY_KIND = {
    "tpu v4": 275e12,
    "tpu v5 lite": 197e12,
    "tpu v5e": 197e12,
    "tpu v5p": 459e12,
    "tpu v6 lite": 918e12,
    "tpu v6e": 918e12,
    "cpu": 1e11,
}
# HBM bandwidth (bytes/s) per chip for the roofline machine balance.
_PEAK_BYTES_BY_KIND = {
    "tpu v4": 1.2e12,
    "tpu v5 lite": 8.2e11,
    "tpu v5e": 8.2e11,
    "tpu v5p": 2.77e12,
    "tpu v6 lite": 1.64e12,
    "tpu v6e": 1.64e12,
    "cpu": 5e10,
}


def _device_kind() -> str:
    try:
        import jax
        return jax.devices()[0].device_kind.lower()
    except Exception:
        return "cpu"


def _lookup(table: Dict[str, float], kind: str, default: float) -> float:
    for key, v in table.items():
        if key in kind:
            return v
    return default


def detect_peak_flops() -> float:
    env = os.environ.get("RAY_TPU_PEAK_FLOPS")
    if env:
        return float(env)
    return _lookup(_PEAK_FLOPS_BY_KIND, _device_kind(), 1e11)


def detect_peak_bytes_per_s() -> float:
    env = os.environ.get("RAY_TPU_PEAK_BYTES_PER_S")
    if env:
        return float(env)
    return _lookup(_PEAK_BYTES_BY_KIND, _device_kind(), 5e10)


def cost_of_compiled(compiled) -> Dict[str, float]:
    """FLOPs / bytes-accessed from an AOT ``Compiled``'s cost analysis
    (jax returns one dict per partition; sum them)."""
    ca = compiled.cost_analysis()
    if isinstance(ca, dict):
        ca = [ca]
    flops = sum(float(d.get("flops", 0.0) or 0.0) for d in ca or [])
    nbytes = sum(float(d.get("bytes accessed", 0.0) or 0.0)
                 for d in ca or [])
    return {"flops": flops, "bytes_accessed": nbytes}


def decode_step_flops(n_params: int, n_layers: int, n_heads: int,
                      head_dim: int, kv_lens) -> float:
    """Analytic per-decode-step FLOPs for a transformer slot batch:
    2 FLOPs/param/token for the dense path plus QK^T and AV against each
    slot's live KV length (the engine can't re-lower its decode program
    for cost_analysis without tripping the compile-once invariant)."""
    total = 0.0
    for kv in kv_lens:
        total += 2.0 * n_params \
            + 4.0 * n_layers * float(kv) * n_heads * head_dim
    return total


def decode_step_bytes(param_bytes: float, n_layers: int, n_kv_heads: int,
                      head_dim: int, kv_lens, elt_bytes: float) -> float:
    """Decode is memory-bound: every step re-reads the params plus each
    slot's K and V history."""
    kv_read = sum(2.0 * n_layers * float(kv) * n_kv_heads * head_dim
                  * elt_bytes for kv in kv_lens)
    return float(param_bytes) + kv_read


def _shape_key(tree) -> tuple:
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return (treedef,
            tuple((getattr(x, "shape", ()), str(getattr(x, "dtype", type(x))))
                  for x in leaves))


class _StepScope:
    """Context manager for one profiled step — see StepProfiler.step()."""

    __slots__ = ("_prof", "_tokens", "_t0", "_t_data")

    def __init__(self, prof: "StepProfiler", tokens: Optional[int]):
        self._prof = prof
        self._tokens = tokens
        self._t0 = time.perf_counter()
        self._t_data: Optional[float] = None

    def data_ready(self):
        """Input pipeline done; compute starts now."""
        self._t_data = time.perf_counter()

    def block(self, out) -> None:
        """Block on the step's output so the compute phase includes
        device time, not just dispatch."""
        try:
            import jax
            jax.block_until_ready(out)
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        end = time.perf_counter()
        data_t = self._t_data or self._t0
        self._prof.observe(
            compute_s=end - data_t, data_s=data_t - self._t0,
            begin_t=self._t0, end_t=end, tokens=self._tokens,
            failed=exc_type is not None)
        return False


class StepProfiler:
    """Thread-compatible (one step in flight per profiler instance);
    creating one registers its gauges, which starts the metrics pusher
    lazily like any other metric."""

    def __init__(self, name: str = "train_step",
                 peak_flops: Optional[float] = None,
                 peak_bytes_per_s: Optional[float] = None,
                 emit_span: bool = True, emit_every: int = 1,
                 category: str = "profile"):
        from ray_tpu.util.metrics import Gauge
        self.name = name
        self.category = category
        self.emit_span = emit_span
        self.emit_every = max(1, int(emit_every))
        self.peak_flops = peak_flops or detect_peak_flops()
        self.peak_bytes_per_s = peak_bytes_per_s or detect_peak_bytes_per_s()
        self.flops: float = 0.0
        self.bytes_accessed: float = 0.0
        self.steps = 0
        self.last: Dict[str, Any] = {}
        self._prev_end: Optional[float] = None
        self._lock = threading.Lock()
        self._g_mfu = Gauge(f"runtime_{name}_mfu",
                            f"model FLOPs utilization of the {name} loop "
                            "(wall clock incl. host gap + data wait)")
        self._g_mfu_c = Gauge(f"runtime_{name}_mfu_compute",
                              f"{name} MFU over the compute phase only "
                              "(the hardware-bound ceiling)")
        self._g_phase = Gauge(f"runtime_{name}_phase_ms",
                              f"per-step {name} phase attribution (ms)",
                              tag_keys=("phase",))
        self._g_roof = Gauge(f"runtime_{name}_roofline_bound",
                             f"roofline MFU bound of the {name} program "
                             "(arithmetic intensity / machine balance)")
        self._g_tps = Gauge(f"runtime_{name}_tokens_per_s",
                            f"{name} tokens per wall second")

    # --------------------------------------------------------------- cost
    def set_cost(self, flops: float, bytes_accessed: float = 0.0):
        self.flops = float(flops)
        self.bytes_accessed = float(bytes_accessed)
        return self

    def observe_compiled(self, compiled) -> bool:
        """Read FLOPs/bytes off an AOT-compiled executable. Returns
        False (cost left untouched) when the backend exposes none."""
        try:
            cost = cost_of_compiled(compiled)
        except Exception:
            return False
        if cost["flops"] <= 0 and cost["bytes_accessed"] <= 0:
            return False
        self.set_cost(cost["flops"], cost["bytes_accessed"])
        return True

    def wrap_jit(self, jit_fn):
        """Wrap a ``jax.jit`` function so each input shape is AOT
        lowered+compiled exactly once, its cost analysis feeds this
        profiler, and subsequent calls reuse the compiled executable.
        Any failure (backend without AOT, sharding-strict executables
        rejecting an input) falls back to the plain jitted call for that
        shape — the profiler then just has no FLOP count."""
        cache: Dict[tuple, tuple] = {}

        def call(*args):
            try:
                key = _shape_key(args)
            except Exception:
                return jit_fn(*args)
            entry = cache.get(key)
            if entry is None:
                fn, cost = jit_fn, None
                try:
                    compiled = jit_fn.lower(*args).compile()
                    cost = cost_of_compiled(compiled)
                    fn = compiled
                except Exception as e:
                    logger.debug("AOT cost analysis unavailable for %s: %s",
                                 self.name, e)
                entry = cache[key] = (fn, cost)
            fn, cost = entry
            if cost is not None:
                self.set_cost(cost["flops"], cost["bytes_accessed"])
            try:
                return fn(*args)
            except Exception:
                if fn is jit_fn:
                    raise
                # a strict AOT executable rejected this input (e.g. an
                # uncommitted sharding): pin the fallback for this shape
                cache[key] = (jit_fn, cost)
                return jit_fn(*args)

        return call

    # -------------------------------------------------------------- steps
    def step(self, tokens: Optional[int] = None) -> _StepScope:
        """``with prof.step(tokens=B*L) as s: batch=...; s.data_ready();
        out = step_fn(batch); s.block(out)``"""
        return _StepScope(self, tokens)

    def observe(self, compute_s: float, data_s: float = 0.0,
                begin_t: Optional[float] = None,
                end_t: Optional[float] = None,
                tokens: Optional[int] = None,
                flops: Optional[float] = None,
                bytes_accessed: Optional[float] = None,
                failed: bool = False) -> Dict[str, Any]:
        """Low-level entry (the engine calls this directly with its own
        phase timings). Returns the attribution dict for this step."""
        now = time.perf_counter()
        end_t = now if end_t is None else end_t
        begin_t = (end_t - compute_s - data_s) if begin_t is None \
            else begin_t
        with self._lock:
            gap_s = max(0.0, begin_t - self._prev_end) \
                if self._prev_end is not None else 0.0
            self._prev_end = end_t
            self.steps += 1
            step_n = self.steps
        if flops is not None:
            self.flops = float(flops)
        if bytes_accessed is not None:
            self.bytes_accessed = float(bytes_accessed)
        compute_s = max(0.0, compute_s)
        data_s = max(0.0, data_s)
        wall_s = compute_s + data_s + gap_s
        rec: Dict[str, Any] = {
            "step": step_n,
            "compute_ms": round(compute_s * 1e3, 4),
            "data_wait_ms": round(data_s * 1e3, 4),
            "host_gap_ms": round(gap_s * 1e3, 4),
            "wall_ms": round(wall_s * 1e3, 4),
        }
        if self.flops > 0 and wall_s > 0:
            rec["mfu"] = round(self.flops / wall_s / self.peak_flops, 6)
            if compute_s > 0:
                rec["mfu_compute"] = round(
                    self.flops / compute_s / self.peak_flops, 6)
        if self.flops > 0 and self.bytes_accessed > 0:
            intensity = self.flops / self.bytes_accessed
            balance = self.peak_flops / self.peak_bytes_per_s
            rec["roofline_bound"] = round(min(1.0, intensity / balance), 6)
        if tokens is not None and wall_s > 0:
            rec["tokens_per_s"] = round(tokens / wall_s, 2)
        if failed:
            rec["failed"] = True
        self.last = rec
        if step_n % self.emit_every == 0:
            self._emit(rec, begin_t, end_t)
        return rec

    def _emit(self, rec: Dict[str, Any], begin_t: float, end_t: float):
        try:
            self._g_phase.set(rec["compute_ms"], tags={"phase": "compute"})
            self._g_phase.set(rec["data_wait_ms"],
                              tags={"phase": "data_wait"})
            self._g_phase.set(rec["host_gap_ms"],
                              tags={"phase": "host_gap"})
            if "mfu" in rec:
                self._g_mfu.set(rec["mfu"])
            if "mfu_compute" in rec:
                self._g_mfu_c.set(rec["mfu_compute"])
            if "roofline_bound" in rec:
                self._g_roof.set(rec["roofline_bound"])
            if "tokens_per_s" in rec:
                self._g_tps.set(rec["tokens_per_s"])
        except Exception:
            pass
        if self.emit_span:
            from ray_tpu._private import events
            # wall-clock reconstruction: perf_counter deltas applied to
            # time.time() so the span lines up with the rest of the
            # timeline
            t_end = time.time() - (time.perf_counter() - end_t)
            t_begin = t_end - (end_t - begin_t)
            events.record_complete(
                f"{self.name}.step", t_begin, t_end,
                category=self.category,
                **{k: v for k, v in rec.items() if k != "step"})
