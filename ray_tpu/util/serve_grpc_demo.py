"""Serve test targets importable by worker processes (xlang_demo
pattern): a hand-written equivalent of protoc-generated registration
code for a tiny Echo service, plus an app builder for `serve deploy`
configs (tests/test_serve_cli_grpc.py).

`add_EchoServicer_to_server` has exactly the shape protoc emits — a
method-handlers dict with per-method (de)serializers registered through
`grpc.method_handlers_generic_handler`. A UTF-8 codec stands in for the
protobuf message classes; the proxy treats messages as opaque objects
either way (reference: python/ray/serve/_private/proxy.py:558 gRPCProxy
consumes generated add_*_to_server functions the same way)."""

from __future__ import annotations

SERVICE_NAME = "raytpu.demo.Echo"


def add_EchoServicer_to_server(servicer, server):   # noqa: N802
    import grpc
    rpc_method_handlers = {
        "Echo": grpc.unary_unary_rpc_method_handler(
            servicer.Echo,
            request_deserializer=lambda b: b.decode("utf-8"),
            response_serializer=lambda s: s.encode("utf-8")),
        "Reverse": grpc.unary_unary_rpc_method_handler(
            servicer.Reverse,
            request_deserializer=lambda b: b.decode("utf-8"),
            response_serializer=lambda s: s.encode("utf-8")),
    }
    server.add_generic_rpc_handlers((
        grpc.method_handlers_generic_handler(SERVICE_NAME,
                                             rpc_method_handlers),))


def echo_client(address: str, method: str, payload: str,
                application: str = "default", timeout: float = 60.0) -> str:
    """Typed-service client (the shape a generated stub produces)."""
    import grpc
    with grpc.insecure_channel(address) as channel:
        fn = channel.unary_unary(
            f"/{SERVICE_NAME}/{method}",
            request_serializer=lambda s: s.encode("utf-8"),
            response_deserializer=lambda b: b.decode("utf-8"))
        return fn(payload, metadata=[("application", application)],
                  timeout=timeout)


def build_echo_app(prefix: str = "echo"):
    """App builder for declarative configs (import_path target)."""
    from ray_tpu import serve

    @serve.deployment
    class EchoDeployment:
        def __init__(self, prefix: str):
            self.prefix = prefix

        def __call__(self, payload):
            return {"echo": payload, "prefix": self.prefix}

        def Echo(self, request: str) -> str:        # noqa: N802
            return f"{self.prefix}:{request}"

        def Reverse(self, request: str) -> str:     # noqa: N802
            return request[::-1]

    return EchoDeployment.bind(prefix)
