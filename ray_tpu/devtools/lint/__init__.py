"""rtlint — runtime-aware static analysis for the ray_tpu codebase.

The runtime has three load-bearing invariants that nothing checked
*statically* until this package existed (the reference covers the same
ground with tsan/asan CI builds and `thread_checker.h` compile-time
assertions, SURVEY §5.2):

- owner-loop handlers must never block (the asyncio analog of a data
  race: one blocking callback stalls every daemon on that loop),
- jitted hot paths must never retrace (`decode_compile_count == 1`,
  the "exactly 3 XLA programs" guarantee the serving stack builds on),
- off-loop threads must mutate shared state only under their declared
  locks (`@off_loop(lock=...)` markers on the PR 1/PR 6 thread-entry
  methods).

rtlint walks the package ASTs with a rule registry (RT001..RT005),
honors inline suppressions (``# rtlint: disable=RT001``, with an
optional justification after the rule list; a disable comment on a
``def`` line covers the whole function), subtracts a committed baseline
of justified legacy findings, and renders human or JSON output. Run it
as ``ray_tpu lint`` or ``python -m ray_tpu.devtools.lint``.
"""

from ray_tpu.devtools.lint.config import LintConfig, load_config
from ray_tpu.devtools.lint.engine import LintResult, run_lint
from ray_tpu.devtools.lint.finding import Finding
from ray_tpu.devtools.lint.registry import Rule, all_rules, register

__all__ = ["Finding", "LintConfig", "LintResult", "Rule", "all_rules",
           "load_config", "register", "run_lint"]
