"""Inline suppressions: ``# rtlint: disable=RT001[,RT002|all][ — why]``.

Parsed from the token stream (not the AST — comments don't survive
parsing). A trailing disable comment applies to the findings on its own
line; a standalone comment (or comment block) applies to the next code
line; a disable comment on a ``def`` line covers the whole function body
(the engine matches finding ``scope_lines`` against the map). Optional
justification text after the rule list is kept for the report but never
interpreted. ``disable-file=`` anywhere in a file suppresses those
rules for the entire file.
"""

from __future__ import annotations

import io
import re
import tokenize
from typing import Dict, Set, Tuple

_PRAGMA = re.compile(
    r"#\s*rtlint:\s*(disable(?:-file)?)\s*=\s*"
    r"(all|RT\d{3}(?:\s*,\s*RT\d{3})*)",
    re.IGNORECASE)

ALL = "all"


def parse_suppressions(source: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """-> ({lineno: {"RT001", ...} or {"all"}}, file-wide rule set)."""
    per_line: Dict[int, Set[str]] = {}
    file_wide: Set[str] = set()
    lines = source.splitlines()

    def _is_code_line(idx0: int) -> bool:
        stripped = lines[idx0].strip() if idx0 < len(lines) else ""
        return bool(stripped) and not stripped.startswith("#")

    def _bind_line(lineno: int) -> int:
        """A standalone pragma comment binds to the next code line (so a
        justification block sits ABOVE the store it exempts); a trailing
        pragma binds to its own line."""
        if _is_code_line(lineno - 1):
            return lineno
        nxt = lineno
        while nxt <= len(lines) and not _is_code_line(nxt - 1):
            nxt += 1
        return nxt if nxt <= len(lines) else lineno

    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA.search(tok.string)
            if not m:
                continue
            kind, rules_raw = m.group(1).lower(), m.group(2)
            rules = ({ALL} if rules_raw.lower() == ALL
                     else {r.strip().upper()
                           for r in rules_raw.split(",")})
            if kind == "disable-file":
                file_wide |= rules
            else:
                per_line.setdefault(_bind_line(tok.start[0]),
                                    set()).update(rules)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass   # unparseable file: the engine reports that separately
    return per_line, file_wide


def is_suppressed(rule: str, line: int, scope_lines,
                  per_line: Dict[int, Set[str]],
                  file_wide: Set[str]) -> bool:
    if ALL in file_wide or rule in file_wide:
        return True
    for ln in [line, *scope_lines]:
        rules = per_line.get(ln)
        if rules and (ALL in rules or rule in rules):
            return True
    return False
