"""``python -m ray_tpu.devtools.lint`` — same surface as ``ray_tpu
lint`` (scripts/cli.py delegates here)."""

import sys

from ray_tpu.devtools.lint.cli import main

if __name__ == "__main__":
    sys.exit(main())
