"""Rule modules register themselves on import; importing this package
is what makes ``all_rules()`` complete. Add a rule = add a module here
with a ``@register``-ed Rule subclass and import it below."""

from ray_tpu.devtools.lint.rules import (  # noqa: F401
    rt001_loop_blocking,
    rt002_jit_retrace,
    rt003_cross_thread,
    rt004_swallowed,
    rt005_msgpack,
)
