"""RT005: msgpack-unsafe values returned from RPC handlers.

``h_*`` handler return values ride the msgpack control plane
(``rpc.py``: ``packb(use_bin_type=True)`` / ``unpackb(raw=False)``).
Three shapes fail or corrupt silently:

- sets / frozensets: msgpack has no set type — ``packb`` raises
  TypeError at call time, on the REMOTE caller's request;
- numpy scalars (``np.int64(...)`` & friends): not packable without a
  custom default hook, which this control plane deliberately does not
  install (payload bytes belong on the data plane);
- bytes-keyed dict literals: they round-trip msgpack itself, but every
  state/dashboard surface re-exports handler payloads as JSON
  (``json.dumps`` rejects bytes keys) and older peers unpack with
  ``strict_map_key=True`` — hex-encode ids at the boundary instead.

The analysis is decidable-shapes-only: literals and direct
constructor calls in ``return`` expressions of ``h_*`` methods
(including values nested in dict/list/tuple literals). Dynamic values
are out of scope — the RPC layer's error path covers those at runtime.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ray_tpu.devtools.lint.finding import Finding
from ray_tpu.devtools.lint.registry import (FileContext, Rule, call_name,
                                            register)

_SET_CTORS = {"set", "frozenset"}
_NP_SCALARS = {"int8", "int16", "int32", "int64", "uint8", "uint16",
               "uint32", "uint64", "float16", "float32", "float64",
               "bool_", "intp", "longlong"}


@register
class MsgpackUnsafeReturnRule(Rule):
    code = "RT005"
    name = "msgpack-unsafe-return"
    description = "msgpack-unsafe value returned from an h_* RPC handler"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name.startswith("h_"):
                yield from self._check_handler(node, ctx)

    def _check_handler(self, fn, ctx) -> Iterator[Finding]:
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                yield from self._check_value(node.value, fn, ctx)

    _COERCERS = {"int", "float", "str", "bool", "bytes", "list", "sorted",
                 "tuple"}

    def _iter_payload(self, expr) -> Iterator[ast.AST]:
        """Walk a return expression, pruning subtrees already coerced to
        a packable type (`int(np.int64(x))` is fine at the boundary)."""
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Call) and \
                    call_name(node) in self._COERCERS:
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _check_value(self, expr, fn, ctx) -> Iterator[Finding]:
        for node in self._iter_payload(expr):
            if isinstance(node, (ast.Set, ast.SetComp)):
                yield ctx.finding(
                    self.code, node,
                    f"handler `{fn.name}` returns a set — msgpack has no "
                    "set type; the remote caller's request fails at "
                    "pack time (return a sorted list)")
            elif isinstance(node, ast.Call):
                name = call_name(node)
                if name in _SET_CTORS:
                    yield ctx.finding(
                        self.code, node,
                        f"handler `{fn.name}` returns `{name}(...)` — "
                        "msgpack has no set type (return a sorted list)")
                elif self._np_scalar(name):
                    yield ctx.finding(
                        self.code, node,
                        f"handler `{fn.name}` returns numpy scalar "
                        f"`{name}` — not msgpack-packable on this "
                        "control plane (coerce with int()/float())")
            elif isinstance(node, ast.Dict):
                for key in node.keys:
                    if isinstance(key, ast.Constant) and \
                            isinstance(key.value, bytes):
                        yield ctx.finding(
                            self.code, key,
                            f"handler `{fn.name}` returns a bytes-keyed "
                            "dict — breaks JSON re-export and "
                            "strict_map_key peers (hex-encode the key)")

    @staticmethod
    def _np_scalar(name: str) -> bool:
        parts = name.split(".")
        return len(parts) == 2 and parts[0] in ("np", "numpy") \
            and parts[1] in _NP_SCALARS
