"""RT004: exceptions swallowed inside daemon loops (`_private/` scope).

``except Exception: pass`` directly inside a ``for``/``while`` body is a
repeating silent failure: a daemon loop that hits the same error every
tick looks healthy forever (no log line, no counter) while e.g. task
events or spill requests silently stop flowing. The rule is scoped to
``_private/`` — that's where the runtime daemons live; best-effort
swallows elsewhere (user-facing conveniences) are a different
conversation.

Only fully-silent handlers are flagged: type Exception/BaseException/
bare, body exactly ``pass`` (or ``...``). A handler that logs, counts,
narrows the type, or even ``continue``s with a comment is out of scope.
Intentional best-effort swallows stay, but carry an inline
``# rtlint: disable=RT004 — <why>`` or a baseline justification.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ray_tpu.devtools.lint.finding import Finding
from ray_tpu.devtools.lint.registry import FileContext, Rule, register

_BROAD = {"Exception", "BaseException"}


def _is_silent(handler: ast.ExceptHandler) -> bool:
    if len(handler.body) != 1:
        return False
    stmt = handler.body[0]
    if isinstance(stmt, ast.Pass):
        return True
    return isinstance(stmt, ast.Expr) and \
        isinstance(stmt.value, ast.Constant) and stmt.value.value is ...


def _is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    if isinstance(handler.type, ast.Name):
        return handler.type.id in _BROAD
    if isinstance(handler.type, ast.Attribute):
        return handler.type.attr in _BROAD
    return False


@register
class SwallowedExceptionRule(Rule):
    code = "RT004"
    name = "swallowed-exception"
    description = ("`except Exception: pass` inside a daemon loop "
                   "(_private/)")
    path_filter = ("_private/",)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        yield from self._walk(ctx.tree, ctx, in_loop=False)

    def _walk(self, node, ctx, in_loop: bool) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(node):
            child_in_loop = in_loop
            if isinstance(child, (ast.For, ast.AsyncFor, ast.While)):
                child_in_loop = True
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                # a nested def starts a fresh (non-loop) scope
                child_in_loop = False
            if isinstance(child, ast.ExceptHandler) and in_loop and \
                    _is_broad(child) and _is_silent(child):
                tname = "bare except" if child.type is None else \
                    f"except {ast.unparse(child.type)}"
                yield ctx.finding(
                    self.code, child,
                    f"`{tname}: pass` inside a loop swallows every "
                    "iteration's failure silently — log it, count it, "
                    "or narrow the type")
            yield from self._walk(child, ctx, child_in_loop)
