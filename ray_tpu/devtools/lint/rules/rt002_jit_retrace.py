"""RT002: retrace hazards in jit-compiled functions.

The engine's whole performance story rests on compile-once contracts
(`decode_compile_count == 1`, "exactly 3 XLA programs"); one Python
coercion of a traced value, one data-dependent branch, or one
unhashable static arg silently turns a cached dispatch into a
recompile per call. This rule finds the function objects handed to
``jax.jit`` / ``jit`` / ``wrap_jit`` (decorator form, ``partial(jax.jit,
...)`` form, and the ``name = jax.jit(fn, ...)`` assignment form used by
``make_train_fns`` and the inference engine) and flags, inside them:

- host coercion of traced arguments: ``int(x)`` / ``float(x)`` /
  ``bool(x)`` / ``x.item()`` where ``x`` involves a non-static
  parameter.  Shape arithmetic (``x.shape``, ``len(x)``, ``x.ndim``,
  ``x.size``) is static under tracing and is NOT flagged;
- Python branching on traced arguments (``if``/``while`` tests naming a
  non-static parameter — ``is``/``is not`` comparisons excluded: they
  resolve at trace time without concretizing);
- static args that cannot hash: a ``static_argnums``/``static_argnames``
  target whose default is a list/dict/set literal;
- donated-buffer reuse: a later read of a plain-name argument passed in
  a ``donate_argnums`` position of a known-jitted callable (straight-line
  analysis within one function body; rebinding clears the taint).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ray_tpu.devtools.lint.finding import Finding
from ray_tpu.devtools.lint.registry import (FileContext, Rule, call_name,
                                            dotted_name, register)

_JIT_NAMES = {"jax.jit", "jit", "wrap_jit", "pjit", "jax.pjit"}
_SHAPEY = {"shape", "ndim", "size", "dtype", "itemsize", "nbytes"}
_COERCIONS = {"int", "float", "bool", "complex"}


def _is_jit_call(node: ast.AST) -> Optional[ast.Call]:
    """The Call node if `node` is jax.jit(...)/jit(...)/wrap_jit(...),
    or partial(jax.jit, ...); else None."""
    if not isinstance(node, ast.Call):
        return None
    name = call_name(node)
    if name in _JIT_NAMES or name.endswith(".wrap_jit"):
        return node
    if name in ("partial", "functools.partial") and node.args:
        inner = dotted_name(node.args[0])
        if inner in _JIT_NAMES:
            return node
    return None


def _static_params(fn, jit_call: Optional[ast.Call]) -> Set[str]:
    """Parameter names excluded from tracing via static_argnums/names."""
    static: Set[str] = set()
    if jit_call is None:
        return static
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for kw in jit_call.keywords:
        if kw.arg == "static_argnums":
            for idx in _int_elts(kw.value):
                if 0 <= idx < len(params):
                    static.add(params[idx])
        elif kw.arg == "static_argnames":
            for name in _str_elts(kw.value):
                static.add(name)
    return static


def _int_elts(node) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)]
    return []


def _str_elts(node) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def _traced_mentions(node: ast.AST, traced: Set[str]) -> bool:
    """True when `node` references a traced param OUTSIDE a static
    accessor chain (x.shape / x.ndim / len(x) / x.dtype)."""
    def visit(n) -> bool:
        if isinstance(n, ast.Attribute) and n.attr in _SHAPEY:
            return False                     # x.shape... — static
        if isinstance(n, ast.Call):
            fname = call_name(n)
            if fname in ("len", "isinstance", "getattr", "hasattr"):
                return False                 # len(x) etc. — static/meta
        if isinstance(n, ast.Name):
            return n.id in traced
        return any(visit(c) for c in ast.iter_child_nodes(n))
    return visit(node)


@register
class JitRetraceRule(Rule):
    code = "RT002"
    name = "jit-retrace"
    description = "retrace hazard inside a jit-compiled function"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # pass 1: map locally defined functions and jitted callables
        defs: Dict[str, ast.AST] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[node.name] = node
        # jitted_fns: function-def node -> jit call (or None for bare @jit)
        jitted: List[Tuple[ast.AST, Optional[ast.Call]]] = []
        # donating callables visible by name: name -> donated positions
        donors: Dict[str, Set[int]] = {}

        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    jc = _is_jit_call(dec)
                    if jc is not None:
                        jitted.append((node, jc))
                    elif dotted_name(dec) in _JIT_NAMES:
                        jitted.append((node, None))
            if isinstance(node, ast.Call):
                # jit(fn, ...) anywhere — assignment, return, argument
                jc = _is_jit_call(node)
                if jc is not None and jc.args:
                    fname = dotted_name(jc.args[0])
                    if fname in defs:
                        jitted.append((defs[fname], jc))
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                jc = _is_jit_call(node.value)
                if jc is not None:
                    target = node.targets[0]
                    tname = None
                    if isinstance(target, ast.Name):
                        tname = target.id
                    elif isinstance(target, ast.Attribute):
                        tname = dotted_name(target)
                    donated = set()
                    for kw in jc.keywords:
                        if kw.arg == "donate_argnums":
                            donated = set(_int_elts(kw.value))
                    if tname and donated:
                        donors[tname] = donated

        seen = set()
        for fn, jc in jitted:
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            yield from self._check_jitted(fn, jc, ctx)

        # donated-buffer reuse sites: every function body + module body
        bodies = [ctx.tree] + [n for n in ast.walk(ctx.tree)
                               if isinstance(n, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef))]
        for body_owner in bodies:
            yield from self._check_donation_reuse(body_owner, donors, ctx)

    # ------------------------------------------------------ jitted bodies
    def _check_jitted(self, fn, jit_call, ctx) -> Iterator[Finding]:
        params = {a.arg for a in fn.args.posonlyargs + fn.args.args
                  + fn.args.kwonlyargs}
        static = _static_params(fn, jit_call)
        traced = params - static

        # unhashable / mutable static defaults
        defaults = fn.args.defaults
        pos = fn.args.posonlyargs + fn.args.args
        for arg, default in zip(pos[len(pos) - len(defaults):], defaults):
            if arg.arg in static and isinstance(
                    default, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                              ast.DictComp, ast.SetComp)):
                yield ctx.finding(
                    self.code, default,
                    f"static arg `{arg.arg}` of jitted `{fn.name}` has a "
                    "mutable (unhashable) default — every call misses the "
                    "jit cache or raises")

        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name in _COERCIONS and node.args and \
                        _traced_mentions(node.args[0], traced):
                    yield ctx.finding(
                        self.code, node,
                        f"`{name}()` concretizes a traced value inside "
                        f"jitted `{fn.name}` — retraces (or errors) every "
                        "distinct value")
                elif name.endswith(".item") and _traced_mentions(
                        node.func, traced):
                    yield ctx.finding(
                        self.code, node,
                        f"`.item()` forces a host sync inside jitted "
                        f"`{fn.name}` — breaks tracing / retraces per value")
            elif isinstance(node, (ast.If, ast.While)):
                test = node.test
                if self._branch_on_traced(test, traced):
                    yield ctx.finding(
                        self.code, test,
                        f"Python branch on traced value in jitted "
                        f"`{fn.name}` — use lax.cond/lax.select or mark "
                        "the arg static")

    def _branch_on_traced(self, test: ast.AST, traced: Set[str]) -> bool:
        if isinstance(test, ast.Compare) and all(
                op.__class__ in (ast.Is, ast.IsNot)
                for op in test.ops):
            return False       # `x is None` resolves at trace time
        return _traced_mentions(test, traced)

    # ------------------------------------------------- donated-arg reuse
    def _check_donation_reuse(self, owner, donors: Dict[str, Set[int]],
                              ctx) -> Iterator[Finding]:
        """Linear pass over one body: after `r = g(buf, ...)` with g
        donating that position, a later plain read of `buf` (without
        rebinding) is a use of a freed buffer. Compound statements
        (if/for/try/with bodies) are analyzed as isolated scopes with a
        COPY of the live taint — a donation inside one branch never
        taints code after the branch point, so mutually-exclusive
        early-return paths (`if fast: return g(state); slow(state)`)
        don't false-positive."""
        if not donors:
            return
        body = owner.body if hasattr(owner, "body") else []
        yield from self._linear(body, dict(), donors, ctx)

    _BLOCK_ATTRS = ("body", "orelse", "finalbody")

    def _linear(self, stmts, tainted: Dict[str, ast.Call],
                donors: Dict[str, Set[int]], ctx) -> Iterator[Finding]:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue      # nested defs get their own pass
            compound = any(getattr(stmt, a, None)
                           for a in self._BLOCK_ATTRS) or \
                getattr(stmt, "handlers", None)
            # reads in this statement's own expressions (for a compound,
            # that's the test/iter/items — its blocks recurse below)
            check_nodes = [stmt] if not compound else \
                [n for n in (getattr(stmt, "test", None),
                             getattr(stmt, "iter", None),
                             *(i.context_expr for i in
                               getattr(stmt, "items", []) or []))
                 if n is not None]
            for top in check_nodes:
                for n in ast.walk(top):
                    if isinstance(n, ast.Name) and \
                            isinstance(n.ctx, ast.Load) and n.id in tainted:
                        call = tainted.pop(n.id)  # one report per taint
                        yield ctx.finding(
                            self.code, n,
                            f"`{n.id}` was donated to "
                            f"`{call_name(call)}` (donate_argnums) and "
                            "is read afterwards — donated buffers are "
                            "invalid after the call")
            if compound:
                for attr in self._BLOCK_ATTRS:
                    block = getattr(stmt, attr, None) or []
                    yield from self._linear(block, dict(tainted),
                                            donors, ctx)
                for handler in getattr(stmt, "handlers", []) or []:
                    yield from self._linear(handler.body, dict(tainted),
                                            donors, ctx)
                continue
            # taint donated plain-name args of calls in this statement
            for n in ast.walk(stmt):
                if isinstance(n, ast.Call):
                    cname = call_name(n)
                    if cname in donors:
                        for pos in donors[cname]:
                            if pos < len(n.args) and isinstance(
                                    n.args[pos], ast.Name):
                                tainted[n.args[pos].id] = n
            # assignments rebind (clear taint) after the statement runs
            if isinstance(stmt, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            tainted.pop(n.id, None)
