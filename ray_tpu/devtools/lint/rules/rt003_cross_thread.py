"""RT003: unlocked shared-state mutation in off-loop methods.

Methods that run on caller threads (the PR 1 put path, the PR 6 striped
arena clients) are marked ``@off_loop(lock="_ref_lock")``
(``ray_tpu/_private/markers.py``). Inside a marked method, every store
to ``self`` state — attribute assigns, augmented assigns, subscript
assigns on a self attribute, and ``del`` — must happen inside a
``with self.<declared-lock>:`` block.

Single-bytecode dict publishes (``self.d[k] = fully_built_value``) are
GIL-atomic and sometimes intentional; those sites carry an inline
``# rtlint: disable=RT003 — <why>`` (or a baseline entry) so the
atomicity argument is written down next to the code instead of lost in
a reviewer's head. The read-modify-write shapes this rule exists for
(``self.n += 1``, ``self.d[k] = self.d.get(k, 0) + 1``) are never safe
unlocked, GIL or not.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ray_tpu.devtools.lint.finding import Finding
from ray_tpu.devtools.lint.registry import (FileContext, Rule,
                                            const_str_kwarg, dotted_name,
                                            register)

_MARKER = "off_loop"


def _off_loop_lock(fn) -> Optional[tuple]:
    """(lock_name or None,) when fn carries @off_loop; None when not
    marked. lock may legitimately be None (marker without a declared
    lock: every store is flagged and the message asks for one)."""
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted_name(target)
        if name == _MARKER or name.endswith("." + _MARKER):
            lock = const_str_kwarg(dec, "lock") if isinstance(
                dec, ast.Call) else None
            return (lock,)
    return None


def _self_store_target(node: ast.AST) -> Optional[str]:
    """'attr' when node stores to self.attr or self.attr[...]; else
    None."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _is_lock_ctx(expr: ast.AST, lock: Optional[str]) -> bool:
    """`with self.<lock>:` (or getattr(self, lock)) for the declared
    lock; with no declared lock, any `with self.*lock*:` counts so the
    finding message can focus on declaring one."""
    name = dotted_name(expr)
    if lock is not None:
        return name == f"self.{lock}"
    return name.startswith("self.") and "lock" in name.lower()


@register
class CrossThreadMutationRule(Rule):
    code = "RT003"
    name = "cross-thread-mutation"
    description = ("self.* store outside the declared lock in an "
                   "@off_loop method")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                marker = _off_loop_lock(node)
                if marker is not None:
                    yield from self._check_method(node, marker[0], ctx)

    def _check_method(self, fn, lock: Optional[str],
                      ctx) -> Iterator[Finding]:
        yield from self._walk(fn.body, fn, lock, ctx, locked=False)

    def _walk(self, stmts, fn, lock, ctx, locked: bool) -> Iterator[Finding]:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue      # nested defs have their own markers
            now_locked = locked
            if isinstance(stmt, ast.With):
                if any(_is_lock_ctx(item.context_expr, lock)
                       for item in stmt.items):
                    now_locked = True
            if not locked:
                yield from self._check_stmt(stmt, fn, lock, ctx)
            for attr in ("body", "orelse", "finalbody"):
                yield from self._walk(getattr(stmt, attr, []) or [],
                                      fn, lock, ctx, now_locked)
            for handler in getattr(stmt, "handlers", []) or []:
                yield from self._walk(handler.body, fn, lock, ctx,
                                      now_locked)

    def _check_stmt(self, stmt, fn, lock, ctx) -> Iterator[Finding]:
        """Direct (non-nested-block) stores in one statement."""
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Delete):
            targets = stmt.targets
        for t in targets:
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for e in elts:
                attr = _self_store_target(e)
                if attr is None:
                    continue
                need = (f"`with self.{lock}:`" if lock
                        else "a declared lock (@off_loop(lock=...))")
                kind = ("read-modify-write"
                        if isinstance(stmt, ast.AugAssign) else "store")
                yield ctx.finding(
                    self.code, stmt,
                    f"{kind} to self.{attr} in off-loop method "
                    f"`{fn.name}` outside {need}")
