"""RT001: blocking calls on owner-loop code paths.

A blocking call inside an ``async def`` body or a registered ``h_*``
handler (sync handlers run inline on the daemon's event loop) stalls
every coroutine sharing that loop — in this runtime that means missed
heartbeats, delayed lease grants, false node-death. This is the static
complement to ``util/sanitizers.py``'s dynamic loop sanitizer, which
only catches the block after it already happened in a tagged run.

Flagged inside loop-owned scopes (nested ``def``s are skipped — they
are routinely shipped to executor threads, where blocking is fine):

- ``time.sleep`` (use ``await asyncio.sleep``)
- blocking subprocess waits: ``subprocess.run/call/check_call/
  check_output``, ``os.system``, ``.communicate()``/``.wait()`` on
  process-ish receivers
- blocking socket ops on socket-ish receivers (``*sock*.connect`` etc.;
  use ``loop.sock_*`` / streams)
- ``socket.create_connection``, ``urllib.request.urlopen``
- blocking file IO: builtin ``open`` (use ``run_in_executor``)
- thread-lock acquisition: ``<lock-ish>.acquire()`` without
  ``blocking=False`` and ``with <lock-ish>:`` — a held peer thread
  turns the critical section into a loop stall
"""

from __future__ import annotations

import ast
from typing import Iterator

from ray_tpu.devtools.lint.finding import Finding
from ray_tpu.devtools.lint.registry import (FileContext, Rule, call_name,
                                            dotted_name, register)

_CALL_BLOCKLIST = {
    "time.sleep": "time.sleep blocks the event loop (await asyncio.sleep)",
    "subprocess.run": "subprocess.run blocks the event loop "
                      "(use asyncio.create_subprocess_exec)",
    "subprocess.call": "subprocess.call blocks the event loop",
    "subprocess.check_call": "subprocess.check_call blocks the event loop",
    "subprocess.check_output": "subprocess.check_output blocks the "
                               "event loop",
    "os.system": "os.system blocks the event loop",
    "os.waitpid": "os.waitpid blocks the event loop",
    "socket.create_connection": "socket.create_connection blocks the "
                                "event loop (use loop.sock_connect)",
    "urllib.request.urlopen": "urlopen blocks the event loop",
}

_SOCKET_METHODS = {"accept", "connect", "recv", "recv_into", "recvfrom",
                   "send", "sendall", "sendto"}
_PROC_METHODS = {"communicate", "wait"}
_LOCKISH = ("lock", "mutex", "_mu", "sem", "cond")


def _lockish(name: str) -> bool:
    low = name.lower()
    return any(part in low for part in _LOCKISH)


def _receiver(node: ast.AST) -> str:
    """Base identifier of an attribute chain ('self._sock.recv' ->
    '_sock', 'sock.connect' -> 'sock')."""
    dotted = dotted_name(node)
    parts = [p for p in dotted.split(".") if p not in ("self", "*")]
    return parts[-2] if len(parts) >= 2 else ""


@register
class LoopBlockingRule(Rule):
    code = "RT001"
    name = "loop-blocking"
    description = ("blocking call inside an async def body or a "
                   "registered h_* handler")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        yield from self._scan(ctx.tree, ctx, owned=False)

    def _scan(self, node, ctx, owned: bool) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.AsyncFunctionDef):
                yield from self._scan_owned(child, ctx)
            elif isinstance(child, ast.FunctionDef):
                if child.name.startswith("h_"):
                    # sync RPC handlers dispatch inline on the loop
                    yield from self._scan_owned(child, ctx)
                # other sync defs: not loop-owned, skip their bodies
            elif isinstance(child, ast.Lambda):
                continue
            else:
                yield from self._scan(child, ctx, owned)

    def _scan_owned(self, fn, ctx) -> Iterator[Finding]:
        """Walk one loop-owned function body, skipping nested defs."""
        for stmt in fn.body:
            yield from self._walk_stmt(stmt, ctx)

    def _walk_stmt(self, node, ctx) -> Iterator[Finding]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return     # executor thunks / helpers: not loop-owned
        if isinstance(node, ast.With):
            for item in node.items:
                name = dotted_name(item.context_expr)
                base = name.split(".")[-1] if name else ""
                if base and _lockish(base) and not isinstance(
                        item.context_expr, ast.Call):
                    yield ctx.finding(
                        self.code, item.context_expr,
                        f"`with {name}:` acquires a thread lock on the "
                        "event loop — a holder thread stalls every "
                        "coroutine on it")
        if isinstance(node, ast.Call):
            yield from self._check_call(node, ctx)
        for child in ast.iter_child_nodes(node):
            yield from self._walk_stmt(child, ctx)

    def _check_call(self, call: ast.Call, ctx) -> Iterator[Finding]:
        name = call_name(call)
        if name in _CALL_BLOCKLIST:
            yield ctx.finding(self.code, call, _CALL_BLOCKLIST[name])
            return
        if name == "open" or name.endswith(".open") and "os." in name:
            yield ctx.finding(
                self.code, call,
                "blocking file open on the event loop (wrap the read in "
                "loop.run_in_executor)")
            return
        last = name.split(".")[-1] if name else ""
        recv = _receiver(call.func) if isinstance(call.func,
                                                  ast.Attribute) else ""
        if last in _SOCKET_METHODS and "sock" in recv.lower():
            yield ctx.finding(
                self.code, call,
                f"blocking socket op `{name}` on the event loop "
                "(use loop.sock_* or asyncio streams)")
            return
        if last in _PROC_METHODS and ("proc" in recv.lower()
                                      or "popen" in recv.lower()):
            yield ctx.finding(
                self.code, call,
                f"blocking process wait `{name}` on the event loop")
            return
        if last == "acquire" and _lockish(recv):
            if not any(kw.arg == "blocking" and
                       isinstance(kw.value, ast.Constant) and
                       kw.value.value is False for kw in call.keywords):
                yield ctx.finding(
                    self.code, call,
                    f"blocking lock acquire `{name}` on the event loop "
                    "(pass blocking=False or restructure)")
