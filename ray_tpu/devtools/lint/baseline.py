"""Committed baseline: legacy/intentional findings that don't fail the
gate, each with a one-line justification.

Entries match findings by fingerprint (rule + file + enclosing symbol +
normalized source line + occurrence — line numbers excluded so edits
above a finding don't churn the file). ``update`` rewrites the file
from the current findings, preserving the justification of every entry
that still matches and stamping new ones with TODO so review catches
unjustified additions.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List

from ray_tpu.devtools.lint.finding import Finding

TODO_JUSTIFICATION = "TODO: justify this exemption"


class Baseline:
    def __init__(self, path: str = "", entries: Dict[str, dict] = None):
        self.path = path
        self.entries: Dict[str, dict] = entries or {}

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not path or not os.path.exists(path):
            return cls(path)
        with open(path) as f:
            doc = json.load(f)
        entries = {e["fingerprint"]: e for e in doc.get("entries", [])}
        return cls(path, entries)

    def apply(self, findings: List[Finding]) -> List[Finding]:
        """Mark matched findings as baselined; returns the unmatched
        (i.e. NEW) findings."""
        new = []
        for f in findings:
            entry = self.entries.get(f.fingerprint)
            if entry is not None and entry.get("rule", f.rule) == f.rule:
                f.baselined = True
                f.justification = entry.get("justification", "")
            else:
                new.append(f)
        return new

    def stale_fingerprints(self, findings: List[Finding]) -> List[str]:
        live = {f.fingerprint for f in findings}
        return sorted(fp for fp in self.entries if fp not in live)

    def update(self, findings: List[Finding], path: str = "") -> str:
        path = path or self.path
        entries = []
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
            old = self.entries.get(f.fingerprint, {})
            entries.append({
                "fingerprint": f.fingerprint, "rule": f.rule,
                "path": f.path, "symbol": f.symbol, "snippet": f.snippet,
                "justification": old.get("justification",
                                         TODO_JUSTIFICATION),
            })
        doc = {"version": 1,
               "comment": ("rtlint baseline — every entry needs a one-line "
                           "justification; regenerate with "
                           "`ray_tpu lint --update-baseline`"),
               "entries": entries}
        with open(path, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=False)
            f.write("\n")
        return path
