"""Finding: one rule violation at one source location.

The fingerprint deliberately excludes the line number: baselines must
survive unrelated edits above a finding, so identity is (rule, file,
enclosing symbol, normalized source line, occurrence index) — the same
scheme flake8-bugbear-style baselines use to stay stable across rebases.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List


@dataclass
class Finding:
    rule: str                 # "RT001"
    path: str                 # repo-relative, forward slashes
    line: int                 # 1-based
    col: int
    message: str
    symbol: str = ""          # enclosing def/class qualname ("" = module)
    snippet: str = ""         # stripped source line
    occurrence: int = 0       # disambiguates identical lines in one symbol
    # def-line numbers of every enclosing function: a suppression comment
    # on any of these lines silences the finding for the whole scope
    scope_lines: List[int] = field(default_factory=list)
    baselined: bool = False
    justification: str = ""   # carried from the matching baseline entry

    @property
    def fingerprint(self) -> str:
        key = "\x1f".join([self.rule, self.path, self.symbol,
                           self.snippet, str(self.occurrence)])
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        d = {"rule": self.rule, "path": self.path, "line": self.line,
             "col": self.col, "message": self.message,
             "symbol": self.symbol, "snippet": self.snippet,
             "fingerprint": self.fingerprint}
        if self.baselined:
            d["baselined"] = True
            if self.justification:
                d["justification"] = self.justification
        return d

    def format(self) -> str:
        where = f"{self.path}:{self.line}:{self.col}"
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{where}: {self.rule} {self.message}{sym}"
