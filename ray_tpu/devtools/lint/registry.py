"""Rule registry + the per-file context rules run against.

A rule is a class with a ``code`` ("RT001"), a short ``name``, an
optional ``path_filter`` (substring any of which must appear in the
repo-relative path — RT004 is scoped to ``_private/`` daemon code this
way), and ``check(ctx)`` yielding Findings. Registration is import-time
(`@register`); ``ray_tpu.devtools.lint.rules`` imports every rule
module so ``all_rules()`` is complete after one import.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple, Type

from ray_tpu.devtools.lint.finding import Finding

_RULES: Dict[str, Type["Rule"]] = {}


def register(cls: Type["Rule"]) -> Type["Rule"]:
    if not getattr(cls, "code", None):
        raise ValueError(f"rule {cls.__name__} has no code")
    if cls.code in _RULES:
        raise ValueError(f"duplicate rule code {cls.code}")
    _RULES[cls.code] = cls
    return cls


def all_rules() -> Dict[str, Type["Rule"]]:
    # import for side effect: each rule module registers itself
    import ray_tpu.devtools.lint.rules  # noqa: F401
    return dict(_RULES)


class FileContext:
    """Parsed view of one file shared by every rule: source lines, the
    AST, and an interval index of function bodies (for symbol
    attribution and def-line scoped suppressions)."""

    def __init__(self, relpath: str, source: str, tree: ast.Module):
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        # (start, end, def_line, qualname) per def/async def, outermost first
        self.func_spans: List[Tuple[int, int, int, str]] = []
        self._index_functions(tree, [])
        self._occ: Dict[tuple, int] = {}

    def _index_functions(self, node, stack: List[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qual = stack + [child.name]
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.func_spans.append(
                        (child.lineno, child.end_lineno or child.lineno,
                         child.lineno, ".".join(qual)))
                self._index_functions(child, qual)
            else:
                self._index_functions(child, stack)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def enclosing(self, lineno: int) -> Tuple[str, List[int]]:
        """(innermost enclosing qualname, def-lines of every enclosing
        function) for a source line."""
        qual, defs = "", []
        for start, end, def_line, name in self.func_spans:
            if start <= lineno <= end:
                defs.append(def_line)
                qual = name   # spans are outermost-first; keep innermost
        return qual, defs

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        lineno = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        symbol, scope_lines = self.enclosing(lineno)
        snippet = self.line_text(lineno)
        key = (rule, symbol, snippet)
        occ = self._occ.get(key, 0)
        self._occ[key] = occ + 1
        return Finding(rule=rule, path=self.relpath, line=lineno, col=col,
                       message=message, symbol=symbol, snippet=snippet,
                       occurrence=occ, scope_lines=scope_lines)


class Rule:
    code: str = ""
    name: str = ""
    description: str = ""
    # substrings; when non-empty, the rule only runs on files whose
    # repo-relative path contains one of them
    path_filter: Tuple[str, ...] = ()

    def applies_to(self, relpath: str) -> bool:
        if not self.path_filter:
            return True
        return any(part in relpath for part in self.path_filter)

    def check(self, ctx: FileContext) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


# ---------------------------------------------------------------- helpers
# Shared AST utilities the rules lean on.

def call_name(node: ast.Call) -> str:
    """Dotted name of a call target ('time.sleep', 'sock.connect', 'int');
    '' when the target is not a name/attribute chain."""
    return dotted_name(node.func)


def dotted_name(node: ast.AST) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    if parts:
        # e.g. <call>.result — keep the attribute chain with a wildcard base
        return ".".join(["*"] + list(reversed(parts)))
    return ""


def names_in(node: ast.AST) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def decorator_names(fn) -> List[str]:
    """Dotted names of each decorator; calls unwrap to their target
    ('off_loop(lock=...)' -> 'off_loop', '@partial(jax.jit)' -> 'partial')."""
    out = []
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        out.append(dotted_name(target))
    return out


def const_str_kwarg(call: ast.Call, name: str) -> Optional[str]:
    for kw in call.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None
