"""``[tool.rtlint]`` configuration from pyproject.toml.

Discovery walks up from the first lint target (or cwd) to the nearest
pyproject.toml carrying a ``[tool.rtlint]`` table; relative paths in
the config (targets, baseline) resolve against that file's directory,
so ``ray_tpu lint`` behaves the same from any cwd.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import List, Optional

try:
    import tomllib          # 3.11+
except ImportError:         # pragma: no cover — tier-1 box runs 3.10
    tomllib = None

DEFAULT_PATHS = ["ray_tpu"]
DEFAULT_EXCLUDE = ["__pycache__", "native/_build", ".git"]
DEFAULT_BASELINE = "rtlint-baseline.json"


@dataclass
class LintConfig:
    root: str = ""                       # dir holding pyproject.toml ("" = cwd)
    paths: List[str] = field(default_factory=lambda: list(DEFAULT_PATHS))
    exclude: List[str] = field(default_factory=lambda: list(DEFAULT_EXCLUDE))
    enable: List[str] = field(default_factory=list)   # [] = all registered
    baseline: str = DEFAULT_BASELINE

    def resolve(self, path: str) -> str:
        if os.path.isabs(path):
            return path
        return os.path.join(self.root or os.getcwd(), path)

    @property
    def baseline_path(self) -> str:
        return self.resolve(self.baseline) if self.baseline else ""


def _find_pyproject(start: str) -> Optional[str]:
    d = os.path.abspath(start)
    if os.path.isfile(d):
        d = os.path.dirname(d)
    while True:
        cand = os.path.join(d, "pyproject.toml")
        if os.path.exists(cand):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


def _parse_rtlint_table(text: str) -> dict:
    """Minimal [tool.rtlint] reader for interpreters without tomllib
    (<3.11): supports exactly the shapes this config uses — string and
    array-of-string values, one per line."""
    m = re.search(r"^\[tool\.rtlint\]\s*$(.*?)(?:^\[|\Z)", text,
                  re.MULTILINE | re.DOTALL)
    if not m:
        return {}
    table = {}
    for line in m.group(1).splitlines():
        line = line.split("#", 1)[0].strip()
        kv = re.match(r"^(\w+)\s*=\s*(.+)$", line)
        if not kv:
            continue
        key, raw = kv.group(1), kv.group(2).strip()
        if raw.startswith("["):
            table[key] = re.findall(r'"([^"]*)"', raw)
        elif raw.startswith('"') and raw.endswith('"'):
            table[key] = raw[1:-1]
    return table


def load_config(start: str = ".") -> LintConfig:
    """Config from the nearest pyproject.toml above `start`; defaults
    when none (or no [tool.rtlint] table) is found."""
    pyproject = _find_pyproject(start)
    if pyproject is None:
        root = os.path.abspath(start)
        if os.path.isfile(root):
            root = os.path.dirname(root)
        return LintConfig(root=root)
    if tomllib is not None:
        with open(pyproject, "rb") as f:
            try:
                doc = tomllib.load(f)
            except tomllib.TOMLDecodeError:
                return LintConfig(root=os.path.dirname(pyproject))
        table = doc.get("tool", {}).get("rtlint", {})
    else:
        with open(pyproject, encoding="utf-8") as f:
            table = _parse_rtlint_table(f.read())
    cfg = LintConfig(root=os.path.dirname(pyproject))
    if "paths" in table:
        cfg.paths = [str(p) for p in table["paths"]]
    if "exclude" in table:
        cfg.exclude = [str(p) for p in table["exclude"]]
    if "enable" in table:
        cfg.enable = [str(r).upper() for r in table["enable"]]
    if "baseline" in table:
        cfg.baseline = str(table["baseline"])
    return cfg
