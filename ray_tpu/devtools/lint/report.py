"""Human and JSON rendering of a LintResult."""

from __future__ import annotations

import json

from ray_tpu.devtools.lint.engine import LintResult


def render_json(result: LintResult) -> str:
    return json.dumps(result.to_dict(), indent=2)


def render_text(result: LintResult) -> str:
    lines = []
    for f in result.findings:
        lines.append(f.format())
        if f.snippet:
            lines.append(f"    {f.snippet}")
    for e in result.errors:
        lines.append(f"{e['path']}: PARSE-ERROR {e['error']}")
    if result.stale_baseline:
        lines.append(f"note: {len(result.stale_baseline)} baseline "
                     f"entr{'y is' if len(result.stale_baseline) == 1 else 'ies are'} "
                     "stale (finding no longer present) — re-run with "
                     "--update-baseline to prune")
    verdict = "ok" if result.ok else "FAILED"
    lines.append(
        f"rtlint: {verdict} — {len(result.findings)} new finding(s), "
        f"{len(result.baselined)} baselined, {result.suppressed} "
        f"suppressed across {result.files_scanned} files "
        f"({result.duration_s:.2f}s, rules: {', '.join(result.rules_run)})")
    return "\n".join(lines)
