"""Lint driver: discover files, parse once, run every enabled rule,
apply suppressions and the baseline, and time the whole pass (the CI
self-gate asserts the package lints in well under 10 s)."""

from __future__ import annotations

import ast
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ray_tpu.devtools.lint.baseline import Baseline
from ray_tpu.devtools.lint.config import LintConfig, load_config
from ray_tpu.devtools.lint.finding import Finding
from ray_tpu.devtools.lint.registry import FileContext, all_rules
from ray_tpu.devtools.lint import suppress


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)   # NEW (gate fails)
    baselined: List[Finding] = field(default_factory=list)
    suppressed: int = 0
    files_scanned: int = 0
    errors: List[dict] = field(default_factory=list)        # parse failures
    stale_baseline: List[str] = field(default_factory=list)
    duration_s: float = 0.0
    rules_run: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings and not self.errors

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "findings": [f.to_dict() for f in self.findings],
            "baselined": [f.to_dict() for f in self.baselined],
            "suppressed": self.suppressed,
            "files_scanned": self.files_scanned,
            "errors": self.errors,
            "stale_baseline": self.stale_baseline,
            "duration_s": round(self.duration_s, 3),
            "rules": self.rules_run,
        }


def discover_files(paths: Sequence[str], exclude: Sequence[str],
                   root: str) -> List[str]:
    out = []
    for p in paths:
        p = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            rel = os.path.relpath(dirpath, root)
            dirnames[:] = sorted(
                d for d in dirnames
                if not _excluded(os.path.join(rel, d), exclude))
            for fn in sorted(filenames):
                if fn.endswith(".py") and \
                        not _excluded(os.path.join(rel, fn), exclude):
                    out.append(os.path.join(dirpath, fn))
    return out


def _excluded(relpath: str, exclude: Sequence[str]) -> bool:
    rel = relpath.replace(os.sep, "/")
    return any(pat in rel for pat in exclude)


def lint_file(path: str, root: str, rules: Dict[str, object],
              result: LintResult) -> None:
    relpath = os.path.relpath(path, root).replace(os.sep, "/")
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError, ValueError) as e:
        result.errors.append({"path": relpath, "error": str(e)})
        return
    result.files_scanned += 1
    per_line, file_wide = suppress.parse_suppressions(source)
    ctx = FileContext(relpath, source, tree)
    for rule in rules.values():
        if not rule.applies_to(relpath):
            continue
        for f in rule.check(ctx):
            if suppress.is_suppressed(f.rule, f.line, f.scope_lines,
                                      per_line, file_wide):
                result.suppressed += 1
            else:
                result.findings.append(f)


def run_lint(paths: Optional[Sequence[str]] = None,
             config: Optional[LintConfig] = None,
             enable: Optional[Sequence[str]] = None,
             baseline_path: Optional[str] = None,
             use_baseline: bool = True) -> LintResult:
    """Lint `paths` (default: config paths). `baseline_path=None` uses
    the config's baseline; pass use_baseline=False to see everything."""
    t0 = time.perf_counter()
    if config is None:
        start = paths[0] if paths else "."
        config = load_config(start)
    targets = list(paths) if paths else list(config.paths)
    enabled = [r.upper() for r in (enable or config.enable)] or None
    registry = all_rules()
    if enabled is not None:
        unknown = [r for r in enabled if r not in registry]
        if unknown:
            raise ValueError(f"unknown rule(s): {', '.join(unknown)}")
        registry = {k: v for k, v in registry.items() if k in enabled}
    rules = {code: cls() for code, cls in sorted(registry.items())}

    result = LintResult(rules_run=sorted(rules))
    files = discover_files(targets, config.exclude, config.root)
    if not files:
        # an explicitly named target that resolves to nothing is an
        # error, not a quietly green gate
        result.errors.append(
            {"path": ", ".join(targets),
             "error": "no Python files found under the given path(s)"})
    for path in files:
        lint_file(path, config.root, rules, result)
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))

    if use_baseline:
        bpath = baseline_path if baseline_path is not None \
            else config.baseline_path
        bl = Baseline.load(bpath)
        all_findings = result.findings
        result.findings = bl.apply(all_findings)
        result.baselined = [f for f in all_findings if f.baselined]
        result.stale_baseline = bl.stale_fingerprints(all_findings)
    result.duration_s = time.perf_counter() - t0
    return result
