"""Argument handling for the lint gate (shared by `ray_tpu lint` and
`python -m ray_tpu.devtools.lint`).

Exit codes: 0 clean (baselined/suppressed findings don't fail the
gate), 1 new findings or parse errors, 2 usage errors. CI runs
``ray_tpu lint ray_tpu/ --format json`` and treats non-zero as red.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def add_lint_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("paths", nargs="*", default=[],
                        help="files/dirs to lint (default: [tool.rtlint] "
                             "paths from pyproject.toml)")
    parser.add_argument("--format", choices=["text", "json"],
                        default="text", dest="fmt")
    parser.add_argument("--baseline", default=None,
                        help="baseline file (default: [tool.rtlint] "
                             "baseline, rtlint-baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline; show every finding")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from current findings "
                             "(preserves existing justifications)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule subset (e.g. "
                             "RT001,RT004)")


def run_from_args(args) -> int:
    from ray_tpu.devtools.lint import load_config, run_lint
    from ray_tpu.devtools.lint.baseline import Baseline
    from ray_tpu.devtools.lint.report import render_json, render_text

    start = args.paths[0] if args.paths else "."
    config = load_config(start)
    enable = [r.strip().upper() for r in args.rules.split(",")] \
        if args.rules else None
    try:
        result = run_lint(paths=args.paths or None, config=config,
                          enable=enable, baseline_path=args.baseline,
                          use_baseline=not args.no_baseline)
    except ValueError as e:
        print(f"rtlint: {e}", file=sys.stderr)
        return 2

    if args.update_baseline:
        bpath = args.baseline or config.baseline_path
        bl = Baseline.load(bpath)
        kept = bl.update(result.findings + result.baselined, bpath)
        print(f"rtlint: baseline rewritten with "
              f"{len(result.findings) + len(result.baselined)} "
              f"entr(y/ies) at {kept}")
        return 0

    out = render_json(result) if args.fmt == "json" else \
        render_text(result)
    print(out)
    return 0 if result.ok else 1


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="rtlint",
        description="runtime-aware static analysis for ray_tpu")
    add_lint_args(parser)
    return run_from_args(parser.parse_args(argv))
