"""Developer tooling that ships with the package but never imports the
runtime (lint must be runnable on a box that can't even start a node)."""
