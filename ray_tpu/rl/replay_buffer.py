"""Uniform transition replay buffer (reference:
rllib/utils/replay_buffers/ — the new-stack EpisodeReplayBuffer role,
simplified to flat transition storage in preallocated numpy rings)."""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._store: Optional[Dict[str, np.ndarray]] = None
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: Dict[str, np.ndarray]) -> None:
        """Add a batch of transitions {key: [N, ...]}."""
        n = len(next(iter(batch.values())))
        if self._store is None:
            self._store = {
                k: np.zeros((self.capacity,) + v.shape[1:], v.dtype)
                for k, v in batch.items()}
        i = self._idx
        if i + n <= self.capacity:
            for k, v in batch.items():
                self._store[k][i:i + n] = v
        else:
            first = self.capacity - i
            for k, v in batch.items():
                self._store[k][i:] = v[:first]
                self._store[k][:n - first] = v[first:]
        self._idx = (i + n) % self.capacity
        self._size = min(self.capacity, self._size + n)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, batch_size)
        return {k: v[idx] for k, v in self._store.items()}
