"""Uniform transition replay buffer (reference:
rllib/utils/replay_buffers/ — the new-stack EpisodeReplayBuffer role,
simplified to flat transition storage in preallocated numpy rings)."""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._store: Optional[Dict[str, np.ndarray]] = None
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: Dict[str, np.ndarray]) -> None:
        """Add a batch of transitions {key: [N, ...]}."""
        n = len(next(iter(batch.values())))
        if self._store is None:
            self._store = {
                k: np.zeros((self.capacity,) + v.shape[1:], v.dtype)
                for k, v in batch.items()}
        i = self._idx
        if i + n <= self.capacity:
            for k, v in batch.items():
                self._store[k][i:i + n] = v
        else:
            first = self.capacity - i
            for k, v in batch.items():
                self._store[k][i:] = v[:first]
                self._store[k][:n - first] = v[first:]
        self._idx = (i + n) % self.capacity
        self._size = min(self.capacity, self._size + n)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, batch_size)
        return {k: v[idx] for k, v in self._store.items()}


class SumTree:
    """Flat-array binary sum tree over `capacity` leaves: O(log n)
    priority updates and prefix-sum sampling (reference:
    rllib/utils/replay_buffers/prioritized_episode_buffer.py's
    segment-tree machinery, re-derived — leaves at [capacity-1,
    2*capacity-1), internal node i sums children 2i+1, 2i+2)."""

    def __init__(self, capacity: int):
        # round up to a power of two so the leaf layer is contiguous
        self.capacity = 1
        while self.capacity < capacity:
            self.capacity *= 2
        self._tree = np.zeros(2 * self.capacity - 1, np.float64)

    @property
    def total(self) -> float:
        return float(self._tree[0])

    def set(self, leaf_idx: np.ndarray, values: np.ndarray) -> None:
        """Vectorized leaf assignment + ancestor re-sum (all leaves sit
        at one depth, so each climb step handles exactly one level)."""
        leaf_idx = np.asarray(leaf_idx, np.int64)
        idx = leaf_idx + self.capacity - 1
        self._tree[idx] = values
        while idx[0] > 0:
            idx = np.unique((idx - 1) // 2)
            self._tree[idx] = self._tree[2 * idx + 1] + \
                self._tree[2 * idx + 2]

    def get(self, leaf_idx: np.ndarray) -> np.ndarray:
        return self._tree[np.asarray(leaf_idx, np.int64)
                          + self.capacity - 1]

    def find(self, prefix_sums: np.ndarray) -> np.ndarray:
        """leaf indices whose cumulative-priority interval contains each
        prefix sum (vectorized descent, one level per iteration)."""
        s = np.asarray(prefix_sums, np.float64).copy()
        idx = np.zeros(len(s), np.int64)
        while idx[0] < self.capacity - 1:     # all leaves reached together
            left = 2 * idx + 1
            left_sum = self._tree[left]
            go_right = s > left_sum
            s = np.where(go_right, s - left_sum, s)
            idx = np.where(go_right, left + 1, left)
        return idx - (self.capacity - 1)


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized experience replay (reference:
    rllib/utils/replay_buffers/prioritized_episode_buffer.py; Schaul et
    al. 2016): P(i) ∝ (|td_i| + eps)^alpha, importance-sampling weights
    w_i = (N * P(i))^-beta normalized by max. New transitions enter at
    the current max priority so everything is trained on at least once.

    sample() returns the batch plus `indices` (pass back to
    update_priorities with the new TD errors) and `weights` (multiply
    into the per-sample loss)."""

    def __init__(self, capacity: int, seed: int = 0, alpha: float = 0.6,
                 beta: float = 0.4, eps: float = 1e-6):
        super().__init__(capacity, seed)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.eps = float(eps)
        self._tree = SumTree(capacity)
        self._max_prio = 1.0

    def add(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        start = self._idx
        super().add(batch)
        idx = (start + np.arange(n)) % self.capacity
        self._tree.set(idx, np.full(n, self._max_prio ** self.alpha))

    def sample(self, batch_size: int,
               beta: Optional[float] = None) -> Dict[str, np.ndarray]:
        beta = self.beta if beta is None else float(beta)
        total = self._tree.total
        # stratified prefix sums: one uniform draw per equal segment
        seg = total / batch_size
        s = (np.arange(batch_size) + self._rng.random(batch_size)) * seg
        idx = self._tree.find(np.minimum(s, total * (1 - 1e-12)))
        # guard: never hand out a slot that has no data yet
        idx = np.minimum(idx, self._size - 1)
        prios = self._tree.get(idx)
        probs = prios / max(total, 1e-12)
        weights = (self._size * probs) ** -beta
        weights = weights / weights.max()
        out = {k: v[idx] for k, v in self._store.items()}
        out["indices"] = idx
        out["weights"] = weights.astype(np.float32)
        return out

    def update_priorities(self, indices: np.ndarray,
                          td_errors: np.ndarray) -> None:
        prios = np.abs(np.asarray(td_errors, np.float64)) + self.eps
        self._max_prio = max(self._max_prio, float(prios.max()))
        self._tree.set(np.asarray(indices, np.int64), prios ** self.alpha)


def make_replay_buffer(config: Dict, capacity: int,
                       seed: int = 0) -> ReplayBuffer:
    """Buffer factory from AlgorithmConfig.replay_buffer_config
    (reference: rllib replay_buffer_config {"type": ...})."""
    cfg = dict(config or {})
    kind = cfg.pop("type", "uniform")
    if kind in ("uniform", "ReplayBuffer"):
        return ReplayBuffer(capacity, seed=seed)
    if kind in ("prioritized", "PrioritizedReplayBuffer"):
        return PrioritizedReplayBuffer(capacity, seed=seed, **cfg)
    raise ValueError(f"unknown replay buffer type {kind!r}")
