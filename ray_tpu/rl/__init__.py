from ray_tpu.rl.algorithm import PPO, Algorithm
from ray_tpu.rl.actor_manager import (FaultTolerantRunnerSet,
                                      RunnerSetBroken)
from ray_tpu.rl.appo import APPO
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.dqn import DQN
from ray_tpu.rl.external import (ExternalPPO, PolicyClient,
                                  PolicyServer)
from ray_tpu.rl.impala import IMPALA
from ray_tpu.rl.multi_agent import (MultiAgentConfig, MultiAgentEnv,
                                    MultiAgentEnvRunner, MultiAgentPPO)
from ray_tpu.rl.offline import BC, BCConfig, record_experiences
from ray_tpu.rl.replay_buffer import (PrioritizedReplayBuffer, ReplayBuffer,
                                      make_replay_buffer)
from ray_tpu.rl.sac import SAC
from ray_tpu.rl.vtrace import vtrace

__all__ = ["Algorithm", "PPO", "APPO", "IMPALA", "DQN", "SAC",
           "ExternalPPO", "PolicyClient", "PolicyServer",
           "AlgorithmConfig", "ReplayBuffer", "PrioritizedReplayBuffer",
           "make_replay_buffer", "vtrace", "MultiAgentEnv",
           "MultiAgentConfig", "MultiAgentEnvRunner", "MultiAgentPPO",
           "BC", "BCConfig", "record_experiences",
           "FaultTolerantRunnerSet", "RunnerSetBroken"]
