from ray_tpu.rl.algorithm import PPO, Algorithm
from ray_tpu.rl.config import AlgorithmConfig

__all__ = ["Algorithm", "PPO", "AlgorithmConfig"]
