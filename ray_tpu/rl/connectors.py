"""Env-to-module connector pipeline (reference: rllib/connectors/ —
observation transforms that sit between the env and the RLModule on
every env runner; the learner trains on the CONNECTED observations, so
the module's input shape is derived through the pipeline).

Built-ins: frame stacking and running-statistics observation
normalization — the two transforms rllib's default pipelines apply most
often. Specs are (name, kwargs) pairs so they serialize into the actor
config untouched."""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np


class Connector:
    """Per-runner stateful transform over BATCHED observations [N, ...].
    `reset_mask[i]` marks envs whose episode just reset — stateful
    connectors drop env i's history (reference: rllib connectors are
    episode-scoped for the same reason)."""

    def output_shape(self, input_shape: Tuple[int, ...]) -> Tuple[int, ...]:
        return tuple(input_shape)

    def reset(self, obs: np.ndarray) -> None:
        """Called once with the first observation batch after env reset."""

    def __call__(self, obs: np.ndarray,
                 reset_mask: "np.ndarray" = None) -> np.ndarray:
        raise NotImplementedError

    def peek(self, obs: np.ndarray) -> np.ndarray:
        """Transform WITHOUT advancing connector state — used to connect
        a done step's true final observation (off-policy next_obs) while
        the live stream resets."""
        return self(obs)


class FrameStack(Connector):
    """Concatenate the last k observations along the last axis (flat
    obs) or the channel axis (image obs) — gives feedforward policies
    short-term memory (reference: rllib frame-stacking connector)."""

    def __init__(self, k: int = 4):
        self.k = int(k)
        self._buf: List[np.ndarray] = []

    def output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (input_shape[-1] * self.k,)

    def reset(self, obs):
        self._buf = [obs.copy() for _ in range(self.k)]

    def __call__(self, obs, reset_mask=None):
        if not self._buf:
            self.reset(obs)
        self._buf.pop(0)
        self._buf.append(obs)
        if reset_mask is not None and reset_mask.any():
            # fresh episodes must not see the dead episode's frames
            for frame in self._buf:
                frame[reset_mask] = obs[reset_mask]
        return np.concatenate(self._buf, axis=-1)

    def peek(self, obs):
        if not self._buf:
            return np.concatenate([obs] * self.k, axis=-1)
        return np.concatenate(self._buf[1:] + [obs], axis=-1)


class NormalizeObs(Connector):
    """Running mean/std normalization (Chan's parallel batch merge of
    mean/M2 — one vectorized update per observation batch; reference:
    rllib MeanStdFilter connector). Each runner tracks its own
    statistics — they converge to the same distribution, and weight
    syncs stay stat-free."""

    def __init__(self, clip: float = 10.0, eps: float = 1e-8):
        self.clip = clip
        self.eps = eps
        self.count = 0.0
        self.mean = None
        self.m2 = None

    def __call__(self, obs, reset_mask=None):
        obs = obs.astype(np.float32)
        flat = obs.reshape(len(obs), -1)
        n = float(len(flat))
        b_mean = flat.mean(0, dtype=np.float64)
        b_m2 = ((flat - b_mean) ** 2).sum(0, dtype=np.float64)
        if self.mean is None:
            self.mean = b_mean
            self.m2 = b_m2
            self.count = n
        else:
            delta = b_mean - self.mean
            tot = self.count + n
            self.mean = self.mean + delta * (n / tot)
            self.m2 = self.m2 + b_m2 + delta ** 2 * (self.count * n / tot)
            self.count = tot
        var = self.m2 / max(1.0, self.count - 1)
        std = np.sqrt(var + self.eps)
        out = (flat - self.mean) / std
        return np.clip(out, -self.clip, self.clip) \
            .reshape(obs.shape).astype(np.float32)

    def peek(self, obs):
        obs = obs.astype(np.float32)
        if self.mean is None:
            return obs
        flat = obs.reshape(len(obs), -1)
        std = np.sqrt(self.m2 / max(1.0, self.count - 1) + self.eps)
        out = (flat - self.mean) / std
        return np.clip(out, -self.clip, self.clip) \
            .reshape(obs.shape).astype(np.float32)


_REGISTRY = {"frame_stack": FrameStack, "normalize_obs": NormalizeObs}


def build_pipeline(specs: Sequence) -> List[Connector]:
    """[(name, kwargs), ...] -> connector instances, in order."""
    out = []
    for spec in specs or ():
        if isinstance(spec, str):
            name, kwargs = spec, {}
        else:
            name, kwargs = spec[0], dict(spec[1] or {})
        if name not in _REGISTRY:
            raise ValueError(f"unknown connector {name!r}; "
                             f"have {sorted(_REGISTRY)}")
        out.append(_REGISTRY[name](**kwargs))
    return out


def pipeline_output_shape(specs: Sequence,
                          input_shape: Tuple[int, ...]) -> Tuple[int, ...]:
    shape = tuple(input_shape)
    for c in build_pipeline(specs):
        shape = c.output_shape(shape)
    return shape


def apply_pipeline(pipeline: List[Connector], obs: np.ndarray,
                   is_reset: bool = False,
                   reset_mask: np.ndarray = None) -> np.ndarray:
    for c in pipeline:
        if is_reset:
            c.reset(obs)
        obs = c(obs, reset_mask=reset_mask)
    return obs


def peek_pipeline(pipeline: List[Connector], obs: np.ndarray) -> np.ndarray:
    for c in pipeline:
        obs = c.peek(obs)
    return obs
