"""RLModule: the framework-agnostic model API, jax/flax implementation
(reference: rllib/core/rl_module/ — flax policy+value modules with
pure-function forward passes so env runners and learners share one
parameter pytree).

Three module families (reference: rllib/models/ catalog — MLP, CNN and
continuous-action heads):
- DiscreteRLModule: MLP trunk, categorical head (flat observations)
- ConvDiscreteRLModule: shared CNN encoder, categorical head (image obs)
- ContinuousRLModule: MLP trunk, diagonal-Gaussian head (Box actions,
  reference: rllib TorchDiagGaussian action dist)

Every module exposes the same surface: `sample_actions` (env-runner side)
and `logp_entropy_value` (a pure, jit-traceable function the PPO/IMPALA
losses call), so learners are action-space agnostic."""

from __future__ import annotations

import math
from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn


class PolicyValueNet(nn.Module):
    action_dim: int
    hidden_sizes: Sequence[int] = (64, 64)

    @nn.compact
    def __call__(self, obs):
        x = obs
        for h in self.hidden_sizes:
            x = nn.tanh(nn.Dense(h)(x))
        logits = nn.Dense(self.action_dim)(x)
        v = obs
        for h in self.hidden_sizes:
            v = nn.tanh(nn.Dense(h)(v))
        value = nn.Dense(1)(v)[..., 0]
        return logits, value


class GaussianPolicyValueNet(nn.Module):
    """Diagonal-Gaussian policy for Box action spaces; log_std is a free
    state-independent parameter (rllib's default for PPO)."""
    action_dim: int
    hidden_sizes: Sequence[int] = (64, 64)

    @nn.compact
    def __call__(self, obs):
        x = obs
        for h in self.hidden_sizes:
            x = nn.tanh(nn.Dense(h)(x))
        mean = nn.Dense(self.action_dim,
                        kernel_init=nn.initializers.variance_scaling(
                            0.01, "fan_avg", "uniform"))(x)
        log_std = self.param("log_std", nn.initializers.zeros,
                             (self.action_dim,))
        v = obs
        for h in self.hidden_sizes:
            v = nn.tanh(nn.Dense(h)(v))
        value = nn.Dense(1)(v)[..., 0]
        return mean, jnp.broadcast_to(log_std, mean.shape), value


class ConvPolicyValueNet(nn.Module):
    """Small shared CNN encoder + categorical/value heads for [H,W,C]
    observations."""
    action_dim: int
    hidden_sizes: Sequence[int] = (64,)

    @nn.compact
    def __call__(self, obs):
        x = obs
        x = nn.relu(nn.Conv(16, (3, 3), strides=(2, 2))(x))
        x = nn.relu(nn.Conv(32, (3, 3), strides=(2, 2))(x))
        x = x.reshape(x.shape[:-3] + (-1,))
        for h in self.hidden_sizes:
            x = nn.relu(nn.Dense(h)(x))
        logits = nn.Dense(self.action_dim)(x)
        value = nn.Dense(1)(x)[..., 0]
        return logits, value


class _ModuleBase:
    def forward(self, params, obs):
        return self._forward(params, obs)

    def dist_values(self, params, obs):
        """Traceable: (action-distribution params, values) for flat-batch
        obs. The dist is whatever this family's `seq_logp_entropy`
        consumes — logits for categorical, (mean, log_std) for Gaussian —
        so the vtrace-family losses are action-space agnostic."""
        logits, value = self.net.apply({"params": params}, obs)
        return logits, value

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, weights):
        self.params = jax.device_put(weights)


def _categorical_logp_entropy(logits, actions):
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(logp_all, actions[..., None], axis=-1)[..., 0]
    entropy = -(jnp.exp(logp_all) * logp_all).sum(-1)
    return logp, entropy


def _gaussian_logp_entropy(dist, actions):
    mean, log_std = dist
    z = (actions - mean) / jnp.exp(log_std)
    logp = (-0.5 * (z ** 2) - log_std - 0.5 * math.log(2 * math.pi)).sum(-1)
    entropy = (log_std + 0.5 * (1 + math.log(2 * math.pi))).sum(-1)
    return logp, entropy


class DiscreteRLModule(_ModuleBase):
    """Policy/value module for discrete action spaces (flat obs)."""

    action_np_dtype = np.int64
    action_event_shape: Tuple[int, ...] = ()
    seq_logp_entropy = staticmethod(_categorical_logp_entropy)

    def __init__(self, obs_dim: int, action_dim: int,
                 hidden_sizes: Sequence[int] = (64, 64), seed: int = 0,
                 net: nn.Module = None, obs_shape: Tuple[int, ...] = None):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.net = net or PolicyValueNet(action_dim, tuple(hidden_sizes))
        shape = tuple(obs_shape) if obs_shape else (obs_dim,)
        self.params = self.net.init(
            jax.random.PRNGKey(seed), jnp.zeros((1,) + shape))["params"]
        self._forward = jax.jit(
            lambda p, o: self.net.apply({"params": p}, o))

    def sample_actions(self, params, obs, rng):
        logits, value = self._forward(params, obs)
        action = jax.random.categorical(rng, logits)
        logp = jax.nn.log_softmax(logits)
        logp_a = jnp.take_along_axis(logp, action[:, None], axis=1)[:, 0]
        return (np.asarray(action), np.asarray(logp_a), np.asarray(value))

    def logp_entropy_value(self, params, obs, actions):
        """Pure/traceable: per-sample log-prob of `actions`, policy
        entropy and value estimates — the learner's loss contract."""
        logits, value = self.net.apply({"params": params}, obs)
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, actions[:, None], axis=1)[:, 0]
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1)
        return logp, entropy, value


class ConvDiscreteRLModule(DiscreteRLModule):
    """Discrete actions over image observations ([H,W,C])."""

    def __init__(self, obs_shape: Tuple[int, ...], action_dim: int,
                 hidden_sizes: Sequence[int] = (64,), seed: int = 0):
        super().__init__(int(np.prod(obs_shape)), action_dim,
                         hidden_sizes, seed,
                         net=ConvPolicyValueNet(action_dim,
                                                tuple(hidden_sizes)),
                         obs_shape=obs_shape)


class ContinuousRLModule(_ModuleBase):
    """Diagonal-Gaussian policy for Box action spaces. Actions are
    sampled unsquashed (the env runner clips to the space bounds at step
    time, matching rllib's default PPO setup)."""

    action_np_dtype = np.float32
    seq_logp_entropy = staticmethod(_gaussian_logp_entropy)

    def __init__(self, obs_dim: int, action_dim: int,
                 hidden_sizes: Sequence[int] = (64, 64), seed: int = 0,
                 low=None, high=None):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.action_event_shape = (action_dim,)
        self.low = None if low is None else np.asarray(low, np.float32)
        self.high = None if high is None else np.asarray(high, np.float32)
        self.net = GaussianPolicyValueNet(action_dim, tuple(hidden_sizes))
        self.params = self.net.init(
            jax.random.PRNGKey(seed), jnp.zeros((1, obs_dim)))["params"]
        self._forward = jax.jit(
            lambda p, o: self.net.apply({"params": p}, o))

    def forward(self, params, obs):
        mean, log_std, value = self._forward(params, obs)
        return mean, value

    def dist_values(self, params, obs):
        mean, log_std, value = self.net.apply({"params": params}, obs)
        return (mean, log_std), value

    def sample_actions(self, params, obs, rng):
        mean, log_std, value = self._forward(params, obs)
        std = jnp.exp(log_std)
        noise = jax.random.normal(rng, mean.shape)
        action = mean + std * noise
        logp = (-0.5 * (noise ** 2) - log_std
                - 0.5 * math.log(2 * math.pi)).sum(-1)
        return (np.asarray(action), np.asarray(logp), np.asarray(value))

    def logp_entropy_value(self, params, obs, actions):
        mean, log_std, value = self.net.apply({"params": params}, obs)
        z = (actions - mean) / jnp.exp(log_std)
        logp = (-0.5 * (z ** 2) - log_std
                - 0.5 * math.log(2 * math.pi)).sum(-1)
        entropy = (log_std + 0.5 * (1 + math.log(2 * math.pi))).sum(-1)
        return logp, entropy, value

    def clip_actions(self, actions: np.ndarray) -> np.ndarray:
        if self.low is None:
            return actions
        return np.clip(actions, self.low, self.high)


class LSTMPolicyValueNet(nn.Module):
    """Single-step recurrent policy/value core (reference:
    rllib/models/torch/recurrent_net.py LSTMWrapper — encoder → LSTM →
    categorical/value heads). __call__ is ONE step: (carry, obs[B,D]) ->
    (carry', (logits, value)); sequence unrolls live OUTSIDE the module
    as a lax.scan over apply (rl_module.RecurrentDiscreteRLModule), so
    flax never sees impure scan bodies."""
    action_dim: int
    hidden: int = 64
    embed: int = 64

    @nn.compact
    def __call__(self, carry, obs):
        x = nn.tanh(nn.Dense(self.embed)(obs))
        carry, h = nn.OptimizedLSTMCell(self.hidden)(carry, x)
        logits = nn.Dense(self.action_dim)(h)
        value = nn.Dense(1)(h)[..., 0]
        return carry, (logits, value)


class RecurrentDiscreteRLModule(_ModuleBase):
    """Recurrent (LSTM) module for discrete actions. State contract
    (reference: rllib connector-managed STATE_IN/STATE_OUT):
    - env runner: carries (c, h) across steps, zeroing env i's slot when
      its episode resets (the connector-reset discipline);
    - learner: receives the fragment's initial carry + per-step done
      flags and re-derives every intermediate state with a scanned
      unroll, resetting the carry inside the scan exactly where the
      runner did.
    Time-major [T, B, ...] throughout — the IMPALA/APPO batch shape."""

    is_recurrent = True
    action_np_dtype = np.int64
    action_event_shape: Tuple[int, ...] = ()
    seq_logp_entropy = staticmethod(_categorical_logp_entropy)

    def __init__(self, obs_dim: int, action_dim: int,
                 hidden_sizes: Sequence[int] = (64, 64), seed: int = 0):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.hidden = int(hidden_sizes[0]) if hidden_sizes else 64
        self.net = LSTMPolicyValueNet(action_dim, hidden=self.hidden,
                                      embed=self.hidden)
        carry0 = self.initial_state(1)
        self.params = self.net.init(jax.random.PRNGKey(seed), carry0,
                                    jnp.zeros((1, obs_dim)))["params"]
        self._step = jax.jit(
            lambda p, c, o: self.net.apply({"params": p}, c, o))

        def unroll(params, carry0, obs_seq, resets):
            """obs_seq [T,B,D], resets [T,B] (1.0 where the episode
            restarted BEFORE step t) -> (logits [T,B,A], values [T,B],
            final carry)."""
            def body(carry, xs):
                obs, reset = xs
                carry = jax.tree.map(
                    lambda c: c * (1.0 - reset)[:, None], carry)
                carry, out = self.net.apply({"params": params}, carry, obs)
                return carry, out
            carry, (logits, values) = jax.lax.scan(
                body, carry0, (obs_seq, resets))
            return logits, values, carry

        self._unroll = jax.jit(unroll)

    def initial_state(self, batch_size: int):
        z = jnp.zeros((batch_size, self.hidden), jnp.float32)
        return (z, z)

    def sample_actions(self, params, obs, rng, state=None):
        """One env step: (actions, logp, value, new_state)."""
        if state is None:
            state = self.initial_state(len(obs))
        state, (logits, value) = self._step(params, state, obs)
        action = jax.random.categorical(rng, logits)
        logp = jax.nn.log_softmax(logits)
        logp_a = jnp.take_along_axis(logp, action[:, None], axis=1)[:, 0]
        return (np.asarray(action), np.asarray(logp_a), np.asarray(value),
                state)

    def forward_seq(self, params, obs_seq, resets, carry0):
        """Traceable sequence forward for the learner loss."""
        return self._unroll(params, carry0, obs_seq, resets)

    def forward(self, params, obs, state=None):
        if state is None:
            state = self.initial_state(len(obs))
        state, (logits, value) = self._step(params, state, obs)
        return logits, value

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, weights):
        self.params = jax.device_put(weights)


class LSTMGaussianPolicyValueNet(nn.Module):
    """Single-step recurrent Gaussian policy/value core for Box actions —
    the continuous sibling of LSTMPolicyValueNet (reference:
    rllib/models/torch/recurrent_net.py LSTMWrapper over a DiagGaussian
    head). One step: (carry, obs[B,D]) -> (carry', ((mean, log_std),
    value)); the dist is a pytree so the same lax.scan unroll stacks it
    time-major."""
    action_dim: int
    hidden: int = 64
    embed: int = 64

    @nn.compact
    def __call__(self, carry, obs):
        x = nn.tanh(nn.Dense(self.embed)(obs))
        carry, h = nn.OptimizedLSTMCell(self.hidden)(carry, x)
        mean = nn.Dense(self.action_dim,
                        kernel_init=nn.initializers.variance_scaling(
                            0.01, "fan_avg", "uniform"))(h)
        # start at sigma=e^-1~0.37, not 1.0: recurrent value estimation
        # is slow to settle, and unit noise on a typically-[-1,1] Box
        # swamps the memory signal for the first hundred updates
        log_std = self.param("log_std",
                             nn.initializers.constant(-1.0),
                             (self.action_dim,))
        value = nn.Dense(1)(h)[..., 0]
        return carry, ((mean, jnp.broadcast_to(log_std, mean.shape)),
                       value)


class RecurrentContinuousRLModule(_ModuleBase):
    """Recurrent (LSTM) module for Box action spaces: the
    RecurrentDiscreteRLModule state contract (runner zeroes carries on
    episode reset; learner re-derives every state with a scanned unroll
    resetting at the same points) with a diagonal-Gaussian head.
    Actions sample unsquashed; the env runner clips at step time."""

    is_recurrent = True
    action_np_dtype = np.float32
    seq_logp_entropy = staticmethod(_gaussian_logp_entropy)

    def __init__(self, obs_dim: int, action_dim: int,
                 hidden_sizes: Sequence[int] = (64, 64), seed: int = 0,
                 low=None, high=None):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.action_event_shape = (action_dim,)
        self.low = None if low is None else np.asarray(low, np.float32)
        self.high = None if high is None else np.asarray(high, np.float32)
        self.hidden = int(hidden_sizes[0]) if hidden_sizes else 64
        self.net = LSTMGaussianPolicyValueNet(action_dim,
                                              hidden=self.hidden,
                                              embed=self.hidden)
        carry0 = self.initial_state(1)
        self.params = self.net.init(jax.random.PRNGKey(seed), carry0,
                                    jnp.zeros((1, obs_dim)))["params"]
        self._step = jax.jit(
            lambda p, c, o: self.net.apply({"params": p}, c, o))

        def unroll(params, carry0, obs_seq, resets):
            def body(carry, xs):
                obs, reset = xs
                carry = jax.tree.map(
                    lambda c: c * (1.0 - reset)[:, None], carry)
                carry, out = self.net.apply({"params": params}, carry, obs)
                return carry, out
            carry, (dist, values) = jax.lax.scan(
                body, carry0, (obs_seq, resets))
            return dist, values, carry

        self._unroll = jax.jit(unroll)

    def initial_state(self, batch_size: int):
        z = jnp.zeros((batch_size, self.hidden), jnp.float32)
        return (z, z)

    def sample_actions(self, params, obs, rng, state=None):
        """One env step: (actions, logp, value, new_state)."""
        if state is None:
            state = self.initial_state(len(obs))
        state, ((mean, log_std), value) = self._step(params, state, obs)
        std = jnp.exp(log_std)
        noise = jax.random.normal(rng, mean.shape)
        action = mean + std * noise
        logp = (-0.5 * (noise ** 2) - log_std
                - 0.5 * math.log(2 * math.pi)).sum(-1)
        return (np.asarray(action), np.asarray(logp), np.asarray(value),
                state)

    def forward_seq(self, params, obs_seq, resets, carry0):
        """Traceable sequence forward: ((mean, log_std) [T,B,A], values
        [T,B], final carry)."""
        return self._unroll(params, carry0, obs_seq, resets)

    def forward(self, params, obs, state=None):
        if state is None:
            state = self.initial_state(len(obs))
        state, ((mean, _log_std), value) = self._step(params, state, obs)
        return mean, value

    def clip_actions(self, actions: np.ndarray) -> np.ndarray:
        if self.low is None:
            return actions
        return np.clip(actions, self.low, self.high)


def action_spec_of(space) -> Dict:
    """gymnasium space -> serializable action spec."""
    import gymnasium as gym
    if isinstance(space, gym.spaces.Discrete):
        return {"type": "discrete", "n": int(space.n)}
    if isinstance(space, gym.spaces.Box):
        return {"type": "box", "dim": int(np.prod(space.shape)),
                "low": np.asarray(space.low).ravel().tolist(),
                "high": np.asarray(space.high).ravel().tolist()}
    raise ValueError(f"unsupported action space: {space}")


def make_rl_module(obs_shape: Tuple[int, ...], action_spec: Dict,
                   hidden_sizes: Sequence[int] = (64, 64), seed: int = 0,
                   use_lstm: bool = False):
    """Module factory keyed by obs rank + action spec (reference:
    rllib/core/rl_module/default catalog selection; use_lstm mirrors
    rllib's model_config use_lstm switch)."""
    obs_shape = tuple(obs_shape)
    if use_lstm:
        if len(obs_shape) > 1:
            raise ValueError(
                f"use_lstm requires flat observations, got shape "
                f"{obs_shape}; stack a flattening connector or use the "
                f"CNN module (conv+LSTM is not implemented)")
        if action_spec["type"] == "discrete":
            return RecurrentDiscreteRLModule(
                int(np.prod(obs_shape)), action_spec["n"], hidden_sizes,
                seed=seed)
        if action_spec["type"] == "box":
            return RecurrentContinuousRLModule(
                int(np.prod(obs_shape)), action_spec["dim"], hidden_sizes,
                seed=seed, low=action_spec.get("low"),
                high=action_spec.get("high"))
        raise ValueError(f"use_lstm: unsupported action spec "
                         f"{action_spec}")
    if action_spec["type"] == "discrete":
        if len(obs_shape) == 3:
            return ConvDiscreteRLModule(obs_shape, action_spec["n"],
                                        hidden_sizes, seed=seed)
        return DiscreteRLModule(int(np.prod(obs_shape)), action_spec["n"],
                                hidden_sizes, seed=seed)
    if action_spec["type"] == "box":
        return ContinuousRLModule(int(np.prod(obs_shape)),
                                  action_spec["dim"], hidden_sizes,
                                  seed=seed, low=action_spec.get("low"),
                                  high=action_spec.get("high"))
    raise ValueError(action_spec)
