"""RLModule: the framework-agnostic model API, jax/flax implementation
(reference: rllib/core/rl_module/ — here a flax policy+value module with
pure-function forward passes so env runners and learners share one
parameter pytree)."""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn


class PolicyValueNet(nn.Module):
    action_dim: int
    hidden_sizes: Sequence[int] = (64, 64)

    @nn.compact
    def __call__(self, obs):
        x = obs
        for h in self.hidden_sizes:
            x = nn.tanh(nn.Dense(h)(x))
        logits = nn.Dense(self.action_dim)(x)
        v = x
        for h in self.hidden_sizes:
            v = nn.tanh(nn.Dense(h)(v))
        value = nn.Dense(1)(v)[..., 0]
        return logits, value


class DiscreteRLModule:
    """Policy/value module for discrete action spaces."""

    def __init__(self, obs_dim: int, action_dim: int,
                 hidden_sizes: Sequence[int] = (64, 64), seed: int = 0):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.net = PolicyValueNet(action_dim, tuple(hidden_sizes))
        self.params = self.net.init(
            jax.random.PRNGKey(seed), jnp.zeros((1, obs_dim)))["params"]
        self._forward = jax.jit(
            lambda p, o: self.net.apply({"params": p}, o))

    def forward(self, params, obs):
        return self._forward(params, obs)

    def sample_actions(self, params, obs, rng):
        logits, value = self._forward(params, obs)
        action = jax.random.categorical(rng, logits)
        logp = jax.nn.log_softmax(logits)
        logp_a = jnp.take_along_axis(logp, action[:, None], axis=1)[:, 0]
        return (np.asarray(action), np.asarray(logp_a), np.asarray(value))

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, weights):
        self.params = jax.device_put(weights)
