"""Multi-agent RL: env API, env runner with policy mapping, and a
multi-policy PPO driver (reference: rllib/env/multi_agent_env.py,
rllib/env/multi_agent_env_runner.py, multi-module RLModule spec in
rllib/core/rl_module/ — policies train independently or shared via the
policy_mapping_fn, each on its own JaxLearner).

Env contract (reference MultiAgentEnv):
    reset(seed) -> (obs: {agent_id: ob}, info)
    step(actions: {agent_id: act}) ->
        (obs, rewards, terminateds, truncateds, info)   # all keyed dicts;
        terminateds/truncateds carry "__all__" for episode end
    agents -> list of agent ids; observation/action spaces per agent via
    observation_space(agent), action_space(agent).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

import numpy as np


class MultiAgentEnv:
    """Base class; subclass and implement reset/step/spaces."""

    agents: List[str] = []

    def reset(self, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, actions: Dict[str, Any]):
        raise NotImplementedError

    def observation_space(self, agent_id: str):
        raise NotImplementedError

    def action_space(self, agent_id: str):
        raise NotImplementedError


@dataclasses.dataclass
class MultiAgentConfig:
    env_maker: Callable[[], MultiAgentEnv] = None
    # agent_id -> policy_id; shared policies = many agents -> one id
    policy_mapping_fn: Callable[[str], str] = lambda aid: aid
    num_env_runners: int = 2
    rollout_fragment_length: int = 64
    num_epochs: int = 4
    minibatch_size: int = 128
    lr: float = 3e-4
    gamma: float = 0.99
    lambda_: float = 0.95
    clip_param: float = 0.2
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.01
    grad_clip: float = 0.5
    hidden_sizes: tuple = (64, 64)
    seed: int = 0
    # fault tolerance: dead/hung env runners are replaced in their slot
    # mid-training with current weights pushed to the replacement
    restart_failed_env_runners: bool = True
    max_runner_restarts: int = 3


class MultiAgentEnvRunner:
    """Steps one multi-agent env; groups per-agent trajectories by policy
    and computes GAE per agent stream (the role the reference's
    connector pipelines + MultiAgentEpisode play)."""

    def __init__(self, cfg: Dict, runner_index: int = 0):
        import jax

        from ray_tpu.rl.rl_module import DiscreteRLModule
        self.cfg = cfg
        self.env = cfg["env_maker"]()
        self.mapping = cfg["policy_mapping_fn"]
        self.policies: Dict[str, DiscreteRLModule] = {}
        for aid in self.env.agents:
            pid = self.mapping(aid)
            if pid not in self.policies:
                obs_dim = int(np.prod(
                    self.env.observation_space(aid).shape))
                act_dim = self.env.action_space(aid).n
                self.policies[pid] = DiscreteRLModule(
                    obs_dim, act_dim, cfg.get("hidden_sizes", (64, 64)),
                    seed=cfg.get("seed", 0))
        self.rng = jax.random.PRNGKey(
            cfg.get("seed", 0) + runner_index * 1000)
        self.obs, _ = self.env.reset(seed=cfg.get("seed", 0) + runner_index)
        self.gamma = cfg["gamma"]
        self.lam = cfg["lambda_"]
        self._episode_return = 0.0
        self._episode_returns: List[float] = []

    def policy_ids(self) -> List[str]:
        return sorted(self.policies)

    def set_weights(self, weights: Dict[str, Any]):
        for pid, w in weights.items():
            self.policies[pid].set_weights(w)
        return True

    def sample(self, num_steps: Optional[int] = None) -> Dict[str, Dict]:
        """Run `num_steps` env steps; returns {policy_id: flat batch with
        obs/actions/logp/advantages/value_targets}."""
        import jax
        T = num_steps or self.cfg["rollout_fragment_length"]
        # per-agent trajectory buffers
        traj: Dict[str, Dict[str, list]] = {
            aid: {"obs": [], "act": [], "logp": [], "rew": [], "val": [],
                  "done": []}
            for aid in self.env.agents}
        for _ in range(T):
            actions = {}
            for aid, ob in self.obs.items():
                pol = self.policies[self.mapping(aid)]
                self.rng, key = jax.random.split(self.rng)
                a, logp, v = pol.sample_actions(
                    pol.params, np.asarray(ob, np.float32)[None], key)
                actions[aid] = int(a[0])
                t = traj[aid]
                t["obs"].append(np.asarray(ob, np.float32))
                t["act"].append(int(a[0]))
                t["logp"].append(float(logp[0]))
                t["val"].append(float(v[0]))
            obs, rews, terms, truncs, _ = self.env.step(actions)
            done = bool(terms.get("__all__")) or bool(truncs.get("__all__"))
            for aid in actions:
                traj[aid]["rew"].append(float(rews.get(aid, 0.0)))
                traj[aid]["done"].append(1.0 if done else 0.0)
            self._episode_return += sum(rews.values())
            if done:
                self._episode_returns.append(self._episode_return)
                self._episode_return = 0.0
                obs, _ = self.env.reset()
            self.obs = obs

        out: Dict[str, Dict[str, list]] = {}
        for aid, t in traj.items():
            pid = self.mapping(aid)
            pol = self.policies[pid]
            # bootstrap with the value of the agent's current obs unless
            # the stream ended with a terminal
            if t["done"] and t["done"][-1] > 0:
                last_val = 0.0
            else:
                ob = np.asarray(self.obs[aid], np.float32)[None]
                _, v = pol.forward(pol.params, ob)
                last_val = float(np.asarray(v)[0])
            n = len(t["obs"])
            adv = np.zeros(n, np.float32)
            lastgaelam = 0.0
            for i in reversed(range(n)):
                nonterminal = 1.0 - t["done"][i]
                next_value = t["val"][i + 1] if i + 1 < n else last_val
                delta = t["rew"][i] + self.gamma * next_value * nonterminal \
                    - t["val"][i]
                lastgaelam = delta + self.gamma * self.lam * nonterminal \
                    * lastgaelam
                adv[i] = lastgaelam
            targets = adv + np.asarray(t["val"], np.float32)
            dst = out.setdefault(pid, {"obs": [], "actions": [], "logp": [],
                                       "advantages": [],
                                       "value_targets": []})
            dst["obs"].extend(t["obs"])
            dst["actions"].extend(t["act"])
            dst["logp"].extend(t["logp"])
            dst["advantages"].extend(adv.tolist())
            dst["value_targets"].extend(targets.tolist())
        return {pid: {"obs": np.asarray(b["obs"], np.float32),
                      "actions": np.asarray(b["actions"], np.int64),
                      "logp": np.asarray(b["logp"], np.float32),
                      "advantages": np.asarray(b["advantages"], np.float32),
                      "value_targets": np.asarray(b["value_targets"],
                                                  np.float32)}
                for pid, b in out.items()}

    def get_metrics(self) -> Dict:
        out = {"episode_return_mean":
               float(np.mean(self._episode_returns[-20:]))
               if self._episode_returns else None,
               "episodes": len(self._episode_returns)}
        return out


class MultiAgentPPO:
    """PPO over a policy map: each policy updates on the experience of the
    agents mapped to it (reference: multi-agent training_step in
    algorithm.py + LearnerGroup with a module per policy)."""

    def __init__(self, config: MultiAgentConfig):
        import ray_tpu
        from ray_tpu.rl.actor_manager import FaultTolerantRunnerSet
        from ray_tpu.rl.learner import JaxLearner

        self.config = config
        cfg_dict = dataclasses.asdict(config)
        cfg_dict["env_maker"] = config.env_maker
        cfg_dict["policy_mapping_fn"] = config.policy_mapping_fn
        runner_cls = ray_tpu.remote(num_cpus=0.25)(MultiAgentEnvRunner)
        # fault-tolerant runner set: slot i is always runner_index=i, so a
        # restart preserves seeding/sharding; the on_restart hook (below,
        # once learners exist) pushes the CURRENT per-policy weights so a
        # replacement rejoins mid-training at the live optimum
        self.env_runners = FaultTolerantRunnerSet(
            lambda i: runner_cls.remote(cfg_dict, i),
            config.num_env_runners,
            max_restarts=config.max_runner_restarts,
            restart_enabled=config.restart_failed_env_runners)
        # learners are built from the env's spaces, one per policy
        probe = config.env_maker()
        self.learners: Dict[str, JaxLearner] = {}
        for aid in probe.agents:
            pid = config.policy_mapping_fn(aid)
            if pid not in self.learners:
                obs_dim = int(np.prod(probe.observation_space(aid).shape))
                act_dim = probe.action_space(aid).n
                self.learners[pid] = JaxLearner(cfg_dict, obs_dim, act_dim)
        self.env_runners.set_on_restart(self._restore_runner)
        self.iteration = 0
        self._sync_weights()

    def _restore_runner(self, runner):
        import ray_tpu
        weights = {pid: ln.get_weights()
                   for pid, ln in self.learners.items()}
        ray_tpu.get(runner.set_weights.remote(ray_tpu.put(weights)),
                    timeout=60.0)

    def _sync_weights(self):
        weights = {pid: ln.get_weights()
                   for pid, ln in self.learners.items()}
        import ray_tpu
        ref = ray_tpu.put(weights)
        self.env_runners.foreach("set_weights", ref, timeout=120.0)

    def training_step(self) -> Dict:
        batches = self.env_runners.foreach("sample")
        merged: Dict[str, Dict[str, np.ndarray]] = {}
        for b in batches:
            for pid, pb in b.items():
                dst = merged.setdefault(pid, {})
                for k, v in pb.items():
                    dst.setdefault(k, []).append(v)
        stats = {}
        for pid, pb in merged.items():
            batch = {k: np.concatenate(v) for k, v in pb.items()}
            stats[pid] = self.learners[pid].update_from_batch(batch)
        self._sync_weights()
        self.iteration += 1
        return stats

    def train(self) -> Dict:
        stats = self.training_step()
        metrics = self.env_runners.foreach("get_metrics")
        returns = [m["episode_return_mean"] for m in metrics
                   if m["episode_return_mean"] is not None]
        return {"iteration": self.iteration,
                "episode_return_mean":
                    float(np.mean(returns)) if returns else None,
                "learners": stats}
