"""AlgorithmConfig: fluent builder (reference:
rllib/algorithms/algorithm_config.py — .environment().env_runners()
.training() chaining, new API stack)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class AlgorithmConfig:
    env: Optional[str] = None
    env_config: Dict[str, Any] = dataclasses.field(default_factory=dict)
    num_env_runners: int = 2
    num_envs_per_env_runner: int = 4
    rollout_fragment_length: int = 64
    train_batch_size: int = 512
    minibatch_size: int = 128
    num_epochs: int = 4
    lr: float = 3e-4
    gamma: float = 0.99
    lambda_: float = 0.95
    clip_param: float = 0.2
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.01
    grad_clip: float = 0.5
    hidden_sizes: tuple = (64, 64)
    num_learners: int = 1
    seed: int = 0
    # env-to-module connector pipeline, e.g.
    # [("frame_stack", {"k": 4}), ("normalize_obs", {})]
    connectors: tuple = ()
    # off-policy knobs (DQN / SAC)
    replay_capacity: int = 50_000
    tau: float = 0.005              # polyak target coefficient
    initial_alpha: float = 0.2      # SAC entropy temperature (auto-tuned)
    target_entropy: Optional[float] = None   # default: -action_dim
    updates_per_step: float = 1.0   # grad updates per env step (SAC)
    # replay buffer selection (reference: replay_buffer_config) —
    # {"type": "uniform"} or {"type": "prioritized", "alpha": .., "beta": ..}
    replay_buffer_config: Dict[str, Any] = dataclasses.field(
        default_factory=lambda: {"type": "uniform"})
    # recurrent policy (reference: model_config use_lstm) — IMPALA/APPO
    use_lstm: bool = False
    # APPO: learner steps between hard target-network syncs
    target_update_freq: int = 2
    # env-runner fault tolerance (reference: AlgorithmConfig
    # .fault_tolerance(restart_failed_env_runners=True) +
    # rllib/utils/actor_manager.py): dead runners are replaced in-slot
    # mid-training, current weights re-pushed, their round dropped
    restart_failed_env_runners: bool = True
    max_env_runner_restarts: int = 3

    # fluent builder API (reference: AlgorithmConfig chaining)
    def environment(self, env: str, env_config: Optional[Dict] = None):
        self.env = env
        if env_config is not None:
            self.env_config = env_config
        return self

    def env_runners(self, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs):
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown training option {k!r}")
            setattr(self, k, v)
        return self

    def learners(self, num_learners: Optional[int] = None):
        if num_learners is not None:
            self.num_learners = num_learners
        return self

    def build(self):
        from ray_tpu.rl.algorithm import PPO
        return PPO(self)
