"""Built-in test/benchmark environments (reference: rllib's tuned
examples lean on Atari/MuJoCo, which need ROMs/licenses; this package
ships a dependency-free pixel env so the conv-module path has a
regression gate that runs anywhere)."""

from __future__ import annotations

from typing import Optional

import numpy as np

try:
    import gymnasium as gym
    _BASE = gym.Env
except Exception:          # pragma: no cover - gymnasium is baked in
    gym = None
    _BASE = object


class GridTargetEnv(_BASE):
    """Pixel observation task: an 8x8 single-channel image shows the
    agent (1.0) and a fixed center target (0.5). Four actions move the
    agent; reaching the target pays +1 and ends the episode, every step
    costs -0.05. Solvable by a small CNN in a few thousand steps —
    random policy averages ~-0.5, a greedy policy ~ +0.6."""

    SIZE = 8
    MAX_STEPS = 24

    def __init__(self, render_mode: Optional[str] = None):
        self.observation_space = gym.spaces.Box(
            0.0, 1.0, (self.SIZE, self.SIZE, 1), np.float32)
        self.action_space = gym.spaces.Discrete(4)
        self.render_mode = render_mode
        self._rng = np.random.default_rng(0)
        self._pos = (0, 0)
        self._t = 0

    def _obs(self):
        img = np.zeros((self.SIZE, self.SIZE, 1), np.float32)
        c = self.SIZE // 2
        img[c, c, 0] = 0.5
        img[self._pos[0], self._pos[1], 0] = 1.0
        return img

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        while True:
            pos = tuple(self._rng.integers(0, self.SIZE, 2))
            if pos != (self.SIZE // 2, self.SIZE // 2):
                break
        self._pos = pos
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        dr, dc = [(-1, 0), (1, 0), (0, -1), (0, 1)][int(action)]
        r = min(max(self._pos[0] + dr, 0), self.SIZE - 1)
        c = min(max(self._pos[1] + dc, 0), self.SIZE - 1)
        self._pos = (r, c)
        self._t += 1
        at_goal = self._pos == (self.SIZE // 2, self.SIZE // 2)
        reward = 1.0 if at_goal else -0.05
        terminated = at_goal
        truncated = self._t >= self.MAX_STEPS
        return self._obs(), reward, terminated, truncated, {}


class StatelessCartPole(_BASE):
    """CartPole with the velocity components masked out — the classic
    partially-observable recurrence gate (reference:
    rllib/examples/envs/classes/stateless_cartpole.py): a feedforward
    policy plateaus near random because [position, angle] alone don't
    determine the optimal action; an LSTM recovers the velocities from
    its memory."""

    def __init__(self, render_mode: Optional[str] = None):
        self._env = gym.make("CartPole-v1")
        self.observation_space = gym.spaces.Box(
            -np.inf, np.inf, (2,), np.float32)
        self.action_space = self._env.action_space
        self.render_mode = render_mode

    @staticmethod
    def _mask(obs):
        return np.asarray([obs[0], obs[2]], np.float32)

    def reset(self, *, seed=None, options=None):
        obs, info = self._env.reset(seed=seed, options=options)
        return self._mask(obs), info

    def step(self, action):
        obs, rew, term, trunc, info = self._env.step(action)
        return self._mask(obs), rew, term, trunc, info


class RepeatAfterMeEnv(_BASE):
    """Memory probe (reference:
    rllib/examples/envs/classes/repeat_after_me_env.py): each step shows
    a random one-hot token; the reward pays +1 for echoing the PREVIOUS
    step's token. A memoryless policy can't beat chance (~half of
    MAX_STEPS); an LSTM solves it almost perfectly — a crisp, fast
    recurrence gate."""

    MAX_STEPS = 32

    def __init__(self, render_mode: Optional[str] = None):
        self.observation_space = gym.spaces.Box(0.0, 1.0, (2,), np.float32)
        self.action_space = gym.spaces.Discrete(2)
        self.render_mode = render_mode
        self._rng = np.random.default_rng(0)
        self._prev = 0
        self._t = 0

    def _obs(self, tok: int):
        o = np.zeros(2, np.float32)
        o[tok] = 1.0
        return o

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        # prev = token shown one obs ago (what the action must echo);
        # cur = token in the obs the agent is looking at right now
        self._prev = None
        self._cur = int(self._rng.integers(0, 2))
        return self._obs(self._cur), {}

    def step(self, action):
        # the current obs shows a NEW token, so echoing what the agent
        # sees scores chance — only memory of the previous obs pays
        reward = float(self._prev is not None
                       and int(action) == self._prev)
        self._t += 1
        self._prev = self._cur
        self._cur = int(self._rng.integers(0, 2))
        return (self._obs(self._cur), reward, False,
                self._t >= self.MAX_STEPS, {})


class ContinuousRepeatAfterMeEnv(_BASE):
    """Continuous-action memory probe — the Box-action sibling of
    RepeatAfterMeEnv (reference: rllib repeat_after_me + its tuned
    continuous variants): each step shows a random target in [-1, 1];
    the reward pays 1 - |action - PREVIOUS step's target|. A memoryless
    policy's best play is action=0 (E|target| = 0.5 → ~15.5 of 31);
    carrying the previous observation approaches 31."""

    MAX_STEPS = 32

    def __init__(self, render_mode: Optional[str] = None):
        self.observation_space = gym.spaces.Box(-1.0, 1.0, (1,),
                                                np.float32)
        self.action_space = gym.spaces.Box(-1.0, 1.0, (1,), np.float32)
        self.render_mode = render_mode
        self._rng = np.random.default_rng(0)
        self._prev = None
        self._cur = 0.0
        self._t = 0

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        self._prev = None
        self._cur = float(self._rng.uniform(-1.0, 1.0))
        return np.array([self._cur], np.float32), {}

    def step(self, action):
        a = float(np.clip(np.asarray(action).ravel()[0], -1.0, 1.0))
        reward = (0.0 if self._prev is None
                  else 1.0 - abs(a - self._prev))
        self._t += 1
        self._prev = self._cur
        self._cur = float(self._rng.uniform(-1.0, 1.0))
        return (np.array([self._cur], np.float32), reward, False,
                self._t >= self.MAX_STEPS, {})


def register_envs():
    """Idempotently register the built-in envs with gymnasium."""
    if gym is None:
        return
    try:
        gym.spec("ray_tpu/GridTarget-v0")
    except Exception:
        gym.register(id="ray_tpu/GridTarget-v0",
                     entry_point="ray_tpu.rl.envs:GridTargetEnv")
    try:
        gym.spec("ray_tpu/StatelessCartPole-v0")
    except Exception:
        gym.register(id="ray_tpu/StatelessCartPole-v0",
                     entry_point="ray_tpu.rl.envs:StatelessCartPole")
    try:
        gym.spec("ray_tpu/RepeatAfterMe-v0")
    except Exception:
        gym.register(id="ray_tpu/RepeatAfterMe-v0",
                     entry_point="ray_tpu.rl.envs:RepeatAfterMeEnv")
    try:
        gym.spec("ray_tpu/ContinuousRepeatAfterMe-v0")
    except Exception:
        gym.register(
            id="ray_tpu/ContinuousRepeatAfterMe-v0",
            entry_point="ray_tpu.rl.envs:ContinuousRepeatAfterMeEnv")


register_envs()
