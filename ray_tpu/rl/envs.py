"""Built-in test/benchmark environments (reference: rllib's tuned
examples lean on Atari/MuJoCo, which need ROMs/licenses; this package
ships a dependency-free pixel env so the conv-module path has a
regression gate that runs anywhere)."""

from __future__ import annotations

from typing import Optional

import numpy as np

try:
    import gymnasium as gym
    _BASE = gym.Env
except Exception:          # pragma: no cover - gymnasium is baked in
    gym = None
    _BASE = object


class GridTargetEnv(_BASE):
    """Pixel observation task: an 8x8 single-channel image shows the
    agent (1.0) and a fixed center target (0.5). Four actions move the
    agent; reaching the target pays +1 and ends the episode, every step
    costs -0.05. Solvable by a small CNN in a few thousand steps —
    random policy averages ~-0.5, a greedy policy ~ +0.6."""

    SIZE = 8
    MAX_STEPS = 24

    def __init__(self, render_mode: Optional[str] = None):
        self.observation_space = gym.spaces.Box(
            0.0, 1.0, (self.SIZE, self.SIZE, 1), np.float32)
        self.action_space = gym.spaces.Discrete(4)
        self.render_mode = render_mode
        self._rng = np.random.default_rng(0)
        self._pos = (0, 0)
        self._t = 0

    def _obs(self):
        img = np.zeros((self.SIZE, self.SIZE, 1), np.float32)
        c = self.SIZE // 2
        img[c, c, 0] = 0.5
        img[self._pos[0], self._pos[1], 0] = 1.0
        return img

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        while True:
            pos = tuple(self._rng.integers(0, self.SIZE, 2))
            if pos != (self.SIZE // 2, self.SIZE // 2):
                break
        self._pos = pos
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        dr, dc = [(-1, 0), (1, 0), (0, -1), (0, 1)][int(action)]
        r = min(max(self._pos[0] + dr, 0), self.SIZE - 1)
        c = min(max(self._pos[1] + dc, 0), self.SIZE - 1)
        self._pos = (r, c)
        self._t += 1
        at_goal = self._pos == (self.SIZE // 2, self.SIZE // 2)
        reward = 1.0 if at_goal else -0.05
        terminated = at_goal
        truncated = self._t >= self.MAX_STEPS
        return self._obs(), reward, terminated, truncated, {}


def register_envs():
    """Idempotently register the built-in envs with gymnasium."""
    if gym is None:
        return
    try:
        gym.spec("ray_tpu/GridTarget-v0")
    except Exception:
        gym.register(id="ray_tpu/GridTarget-v0",
                     entry_point="ray_tpu.rl.envs:GridTargetEnv")


register_envs()
