"""EnvRunner: actor sampling episodes from gymnasium vector envs
(reference: rllib/env/single_agent_env_runner.py:63 — sample :133; module
forward for action selection runs inside the runner; GAE advantages are
computed here at fragment end so the learner gets ready batches, the role
the reference's learner connector pipeline plays)."""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class EnvRunner:
    def __init__(self, config: Dict):
        # rollout workers are CPU-side: a per-step policy forward for a
        # handful of envs is latency-bound, and round-tripping it through
        # a TPU (tunnel) turns ~3000 steps/s into ~20. The learner is
        # where the accelerator belongs (reference: env runners are CPU
        # actors; only Learner workers get GPUs/TPUs). The env var alone
        # is not enough — device plugins registered via sitecustomize
        # override it — so pin via jax.config before the backend spins up.
        import gymnasium as gym
        import jax
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass   # backend already initialized (driver-local runner)

        from ray_tpu.rl import envs as _envs   # registers built-in envs
        _envs.register_envs()
        self.cfg = config
        self.n_envs = config["num_envs_per_env_runner"]
        # SAME_STEP autoreset: a done step returns the RESET observation
        # (the true final obs rides in infos), so every recorded
        # transition is real — gymnasium >=1.0's default NextStep mode
        # would interleave a bogus action-ignored reset step into the
        # rollout (stale obs, reward 0) that GAE/vtrace would train on
        self.envs = gym.vector.SyncVectorEnv(
            [lambda: gym.make(config["env"], **config.get("env_config", {}))
             for _ in range(self.n_envs)],
            autoreset_mode=gym.vector.AutoresetMode.SAME_STEP)
        from ray_tpu.rl.connectors import (apply_pipeline, build_pipeline,
                                           pipeline_output_shape)
        from ray_tpu.rl.rl_module import action_spec_of, make_rl_module
        raw_shape = self.envs.single_observation_space.shape
        self._pipeline = build_pipeline(config.get("connectors") or ())
        self._apply_pipeline = apply_pipeline
        obs_shape = pipeline_output_shape(config.get("connectors") or (),
                                          raw_shape)
        self.action_spec = action_spec_of(self.envs.single_action_space)
        self.module = make_rl_module(
            obs_shape, self.action_spec,
            config.get("hidden_sizes", (64, 64)),
            seed=config.get("seed", 0),
            use_lstm=config.get("use_lstm", False))
        # recurrent modules: per-env LSTM carry, zeroed on episode reset
        # (the connector state discipline — rl_module docstring)
        self._state = (self.module.initial_state(self.n_envs)
                       if getattr(self.module, "is_recurrent", False)
                       else None)
        self.rng = jax.random.PRNGKey(config.get("seed", 0)
                                      + config.get("runner_index", 0) * 1000)
        self.obs, _ = self.envs.reset(seed=config.get("seed", 0)
                                      + config.get("runner_index", 0))
        # connected view of the current obs: the module (and therefore
        # the learner's batches) only ever sees pipeline output
        self._cobs = self._apply_pipeline(
            self._pipeline, self.obs.astype(np.float32), is_reset=True)
        self.gamma = config["gamma"]
        self.lam = config["lambda_"]
        self._episode_returns = []
        self._running_returns = np.zeros(self.n_envs)

    def set_weights(self, weights):
        self.module.set_weights(weights)
        return True

    def sample(self, num_steps: Optional[int] = None) -> Dict[str, np.ndarray]:
        """Collect a fragment of num_steps per env; returns flat batch with
        GAE advantages and value targets."""
        import jax
        T = num_steps or self.cfg["rollout_fragment_length"]
        N = self.n_envs
        obs_buf = np.zeros((T, N) + self._cobs.shape[1:], np.float32)
        act_buf = np.zeros((T, N) + self.module.action_event_shape,
                           self.module.action_np_dtype)
        logp_buf = np.zeros((T, N), np.float32)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.float32)
        val_buf = np.zeros((T, N), np.float32)

        obs = self.obs
        cobs = self._cobs
        for t in range(T):
            self.rng, key = jax.random.split(self.rng)
            action, logp, value = self.module.sample_actions(
                self.module.params, cobs.astype(np.float32), key)
            env_action = (self.module.clip_actions(action)
                          if hasattr(self.module, "clip_actions")
                          else action)
            nxt, rew, term, trunc, _ = self.envs.step(env_action)
            done = np.logical_or(term, trunc)
            obs_buf[t] = cobs
            act_buf[t] = action
            logp_buf[t] = logp
            rew_buf[t] = rew
            done_buf[t] = done.astype(np.float32)
            val_buf[t] = value
            self._running_returns += rew
            for i, d in enumerate(done):
                if d:
                    self._episode_returns.append(self._running_returns[i])
                    self._running_returns[i] = 0.0
            obs = nxt
            cobs = self._apply_pipeline(self._pipeline,
                                        nxt.astype(np.float32),
                                        reset_mask=done)
        self.obs = obs
        self._cobs = cobs

        # bootstrap value for the final obs
        _, last_val = self.module.forward(self.module.params,
                                          cobs.astype(np.float32))
        last_val = np.asarray(last_val)
        adv = np.zeros((T, N), np.float32)
        lastgaelam = np.zeros(N, np.float32)
        for t in reversed(range(T)):
            nonterminal = 1.0 - done_buf[t]
            next_value = val_buf[t + 1] if t + 1 < T else last_val
            delta = rew_buf[t] + self.gamma * next_value * nonterminal \
                - val_buf[t]
            lastgaelam = delta + self.gamma * self.lam * nonterminal \
                * lastgaelam
            adv[t] = lastgaelam
        targets = adv + val_buf

        flat = lambda a: a.reshape((T * N,) + a.shape[2:])  # noqa: E731
        return {"obs": flat(obs_buf), "actions": flat(act_buf),
                "logp": flat(logp_buf), "advantages": flat(adv),
                "value_targets": flat(targets)}

    def sample_trajectory(self, num_steps: Optional[int] = None
                          ) -> Dict[str, np.ndarray]:
        """Time-major fragment [T, N, ...] with behavior log-probs and a
        bootstrap value — the shape V-trace consumes (IMPALA path; the
        reference's equivalent is the env-runner → aggregator episode flow,
        rllib/algorithms/impala/impala.py)."""
        import jax
        T = num_steps or self.cfg["rollout_fragment_length"]
        N = self.n_envs
        obs_buf = np.zeros((T, N) + self._cobs.shape[1:], np.float32)
        act_buf = np.zeros((T, N) + self.module.action_event_shape,
                           self.module.action_np_dtype)
        logp_buf = np.zeros((T, N), np.float32)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.float32)

        recurrent = self._state is not None
        initial_state = (tuple(np.asarray(s) for s in self._state)
                         if recurrent else None)
        obs = self.obs
        cobs = self._cobs
        for t in range(T):
            self.rng, key = jax.random.split(self.rng)
            if recurrent:
                action, logp, _value, self._state = \
                    self.module.sample_actions(
                        self.module.params, cobs.astype(np.float32), key,
                        self._state)
            else:
                action, logp, _value = self.module.sample_actions(
                    self.module.params, cobs.astype(np.float32), key)
            # step with clipped actions; learn on the unclipped sample
            # (its logp is what the behavior distribution produced)
            env_action = (self.module.clip_actions(action)
                          if hasattr(self.module, "clip_actions")
                          else action)
            nxt, rew, term, trunc, _ = self.envs.step(env_action)
            done = np.logical_or(term, trunc)
            obs_buf[t] = cobs
            act_buf[t] = action
            logp_buf[t] = logp
            rew_buf[t] = rew
            done_buf[t] = done.astype(np.float32)
            self._running_returns += rew
            for i, d in enumerate(done):
                if d:
                    self._episode_returns.append(self._running_returns[i])
                    self._running_returns[i] = 0.0
            if recurrent and done.any():
                # fresh episodes must not see the dead episode's memory
                mask = 1.0 - done.astype(np.float32)[:, None]
                self._state = tuple(np.asarray(s) * mask
                                    for s in self._state)
            obs = nxt
            cobs = self._apply_pipeline(self._pipeline,
                                        nxt.astype(np.float32),
                                        reset_mask=done)
        self.obs = obs
        self._cobs = cobs
        if recurrent:
            _, last_val = self.module.forward(
                self.module.params, cobs.astype(np.float32), self._state)
        else:
            _, last_val = self.module.forward(self.module.params,
                                              cobs.astype(np.float32))
        out = {"obs": obs_buf, "actions": act_buf,
               "behavior_logp": logp_buf, "rewards": rew_buf,
               "dones": done_buf,
               "bootstrap_obs": np.asarray(cobs, np.float32),
               "bootstrap_value": np.asarray(last_val, np.float32)}
        if recurrent:
            # fragment-start carry: the learner re-derives every
            # intermediate state from this + the done flags
            out["initial_state_c"] = initial_state[0]
            out["initial_state_h"] = initial_state[1]
        return out

    def get_metrics(self) -> Dict:
        out = {"episode_return_mean":
               float(np.mean(self._episode_returns[-20:]))
               if self._episode_returns else None,
               "num_episodes": len(self._episode_returns)}
        return out
