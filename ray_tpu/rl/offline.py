"""Offline RL: dataset recording + behavior cloning from a ray_tpu.data
Dataset (reference: rllib/offline/ — dataset reader/writer, BC in
rllib/algorithms/bc/). Experiences are rows ({"obs": [...], "action": i,
"reward": r, "done": b}) so any Data source/sink (json, parquet) works."""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np


def record_experiences(env_maker, policy=None, num_steps: int = 1000,
                       seed: int = 0):
    """Roll a (random or given) policy in a gymnasium env and return the
    experience rows — feed to ray_tpu.data.from_items or write_json for
    later offline training (reference: offline dataset writer,
    rllib/offline/output_writer.py)."""
    import gymnasium as gym
    env = env_maker() if callable(env_maker) else gym.make(env_maker)
    rng = np.random.default_rng(seed)
    rows: List[Dict] = []
    obs, _ = env.reset(seed=seed)
    for _ in range(num_steps):
        if policy is None:
            action = int(rng.integers(env.action_space.n))
        else:
            a, _, _ = policy.sample_actions(
                policy.params, np.asarray(obs, np.float32)[None],
                _np_key(rng))
            action = int(a[0])
        nxt, rew, term, trunc, _ = env.step(action)
        rows.append({"obs": np.asarray(obs, np.float32).tolist(),
                     "action": action, "reward": float(rew),
                     "done": bool(term or trunc)})
        obs = nxt
        if term or trunc:
            obs, _ = env.reset()
    env.close()
    return rows


def _np_key(rng):
    import jax
    return jax.random.PRNGKey(int(rng.integers(2**31)))


@dataclasses.dataclass
class BCConfig:
    dataset: object = None          # ray_tpu.data.Dataset of experience rows
    obs_dim: int = 0
    action_dim: int = 0
    lr: float = 1e-3
    train_batch_size: int = 256
    num_epochs: int = 1
    hidden_sizes: tuple = (64, 64)
    seed: int = 0


class BC:
    """Behavior cloning: supervised log-likelihood of recorded actions
    (reference: rllib BC on the new API stack — an offline Learner over a
    dataset reader)."""

    def __init__(self, config: BCConfig):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rl.rl_module import DiscreteRLModule
        self.config = config
        self.module = DiscreteRLModule(config.obs_dim, config.action_dim,
                                       config.hidden_sizes,
                                       seed=config.seed)
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.module.params)
        net = self.module.net

        def loss_fn(params, obs, actions):
            logits, _ = net.apply({"params": params}, obs)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(logp, actions[:, None], axis=1)[:, 0]
            return nll.mean()

        @jax.jit
        def update(params, opt_state, obs, actions):
            loss, grads = jax.value_and_grad(loss_fn)(params, obs, actions)
            updates, opt_state = self.optimizer.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._update = update
        self.iteration = 0

    def train(self) -> Dict:
        """One pass over the dataset in batches."""
        losses = []
        it = self.config.dataset.iter_batches(
            batch_size=self.config.train_batch_size, batch_format="numpy")
        for batch in it:
            obs = np.asarray([np.asarray(o, np.float32)
                              for o in batch["obs"]])
            actions = np.asarray(batch["action"], np.int64)
            for _ in range(self.config.num_epochs):
                self.module.params, self.opt_state, loss = self._update(
                    self.module.params, self.opt_state, obs, actions)
            losses.append(float(loss))
        self.iteration += 1
        return {"iteration": self.iteration,
                "loss": float(np.mean(losses)) if losses else None,
                "num_batches": len(losses)}

    def action_accuracy(self, dataset=None) -> float:
        """Fraction of dataset actions the greedy policy reproduces."""
        ds = dataset or self.config.dataset
        total = hit = 0
        for batch in ds.iter_batches(batch_size=512,
                                     batch_format="numpy"):
            obs = np.asarray([np.asarray(o, np.float32)
                              for o in batch["obs"]])
            actions = np.asarray(batch["action"], np.int64)
            logits, _ = self.module.forward(self.module.params, obs)
            pred = np.asarray(logits).argmax(axis=1)
            hit += int((pred == actions).sum())
            total += len(actions)
        return hit / max(total, 1)
