"""APPO: asynchronous PPO — IMPALA's async actor-learner machinery with
a PPO clipped-surrogate policy loss and a periodically-synced target
network supplying the V-trace targets (reference:
rllib/algorithms/appo/appo.py + appo_learner — clip param, target
network update period `target_network_update_freq`; re-designed on this
package's jitted-update IMPALA skeleton rather than a translated loss
graph).

Why the target network: the surrogate clips the ratio pi/behavior, but
the value targets must stay fixed while the policy takes several async
steps off one behavior distribution — computing V-trace targets from a
lagged copy keeps them stable (the reference's argument verbatim)."""

from __future__ import annotations

from typing import Dict

import numpy as np

from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.impala import IMPALA, ImpalaLearner, _seq_forward


class AppoLearner(ImpalaLearner):
    """IMPALA learner + clipped surrogate + lagged value-target net."""

    def __init__(self, config: Dict, obs_dim: int, action_dim: int):
        super().__init__(config, obs_dim, action_dim)
        import jax
        import optax

        self.target_params = self.module.params
        self.target_update_freq = int(config.get("target_update_freq", 2))
        self._steps_since_target = 0
        loss_fn = self._make_appo_loss()

        @jax.jit
        def update(params, target_params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch)
            updates, new_opt = self.optimizer.update(grads, opt_state,
                                                     params)
            return optax.apply_updates(params, updates), new_opt, loss, aux

        self._update_appo = update

    def _make_appo_loss(self):
        import jax
        import jax.numpy as jnp
        from ray_tpu.rl.vtrace import vtrace
        cfg = self.cfg
        gamma = cfg["gamma"]
        clip = cfg.get("clip_param", 0.2)
        vf_coeff = cfg["vf_loss_coeff"]
        ent_coeff = cfg["entropy_coeff"]
        module = self.module

        def loss_fn(params, target_params, batch):
            dist, values = _seq_forward(module, params, batch)
            cur_logp, entropy = module.seq_logp_entropy(
                dist, batch["actions"])
            # lagged copy: value targets + the off-policy correction's
            # target-policy term both come from the frozen params
            t_dist, t_values = _seq_forward(module, target_params, batch)
            t_logp, _ = module.seq_logp_entropy(t_dist, batch["actions"])
            discounts = gamma * (1.0 - batch["dones"])
            vt = vtrace(batch["behavior_logp"], t_logp, batch["rewards"],
                        discounts, t_values, batch["bootstrap_value"])
            ratio = jnp.exp(cur_logp - batch["behavior_logp"])
            adv = vt.pg_advantages
            surr = jnp.minimum(ratio * adv,
                               jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
            pg_loss = -surr.mean()
            vf_loss = ((values - vt.vs) ** 2).mean()
            entropy = entropy.mean()
            total = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
            return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": entropy,
                           "mean_ratio": ratio.mean()}

        return loss_fn

    def update_from_trajectory(self, traj: Dict[str, np.ndarray]) -> Dict:
        import jax.numpy as jnp
        batch = {k: jnp.asarray(v) for k, v in traj.items()
                 if k != "bootstrap_obs"}
        # multiple surrogate passes per fragment are exactly what the
        # PPO-style clip is for (reference APPO num_sgd_iter); the
        # lagged target keeps the V-trace targets fixed across passes
        for _ in range(max(1, int(self.cfg.get("num_epochs", 1)))):
            self.module.params, self.opt_state, loss, aux = \
                self._update_appo(self.module.params, self.target_params,
                                  self.opt_state, batch)
        self._steps_since_target += 1
        if self._steps_since_target >= self.target_update_freq:
            self.target_params = self.module.params
            self._steps_since_target = 0
        out = {k: float(v) for k, v in aux.items()}
        out["total_loss"] = float(loss)
        return out


class APPO(IMPALA):
    """Async PPO driver: identical async sampling/weight-sync loop as
    IMPALA, APPO learner update."""

    def _build_learner(self, cfg_dict, obs_dim, action_dim):
        self.learner = AppoLearner(cfg_dict, obs_dim, action_dim)


def appo_config() -> AlgorithmConfig:
    """AlgorithmConfig preset tuned like the reference's APPO defaults."""
    return AlgorithmConfig().training(lr=5e-4, grad_clip=40.0,
                                      entropy_coeff=0.01)
