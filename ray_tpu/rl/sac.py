"""SAC: soft actor-critic for continuous control (reference:
rllib/algorithms/sac/ — squashed-Gaussian policy, twin Q with a min
target, polyak-averaged target networks, auto-tuned entropy temperature;
the whole update is ONE jitted function, target sync by tau each step).

TPU-first shape: every grad update (actor + both critics + alpha) is a
single compiled step over a replay minibatch — no per-network Python
round trips."""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Dict, Optional, Sequence

import numpy as np

from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.replay_buffer import (PrioritizedReplayBuffer,
                                      make_replay_buffer)

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


def make_nets(action_dim: int, hidden_sizes: Sequence[int]):
    from flax import linen as nn

    class Policy(nn.Module):
        @nn.compact
        def __call__(self, obs):
            x = obs
            for h in hidden_sizes:
                x = nn.relu(nn.Dense(h)(x))
            mean = nn.Dense(action_dim)(x)
            log_std = nn.Dense(action_dim)(x)
            import jax.numpy as jnp
            return mean, jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)

    class TwinQ(nn.Module):
        @nn.compact
        def __call__(self, obs, action):
            import jax.numpy as jnp
            x = jnp.concatenate([obs, action], -1)
            qs = []
            for _ in range(2):
                h = x
                for w in hidden_sizes:
                    h = nn.relu(nn.Dense(w)(h))
                qs.append(nn.Dense(1)(h)[..., 0])
            return qs[0], qs[1]

    return Policy(), TwinQ()


def squashed_sample(mean, log_std, key):
    """a = tanh(u), u ~ N(mean, std); returns (action, logp) with the
    tanh change-of-variables correction (SAC paper appendix C)."""
    import jax
    import jax.numpy as jnp
    std = jnp.exp(log_std)
    u = mean + std * jax.random.normal(key, mean.shape)
    logp_u = (-0.5 * ((u - mean) / std) ** 2 - log_std
              - 0.5 * math.log(2 * math.pi)).sum(-1)
    a = jnp.tanh(u)
    logp = logp_u - jnp.log(1 - a ** 2 + 1e-6).sum(-1)
    return a, logp


class SacEnvRunner:
    """Stochastic transition collector; actions squashed to [-1,1] and
    affine-mapped to the env's Box bounds."""

    def __init__(self, config: Dict):
        import gymnasium as gym
        import jax
        try:
            jax.config.update("jax_platforms", "cpu")   # rollouts: CPU
        except Exception:
            pass
        import jax.numpy as jnp
        self.cfg = config
        self.n_envs = config["num_envs_per_env_runner"]
        # SAME_STEP autoreset (see rl/env_runner.py) — the done step
        # returns the reset obs; the TRUE final obs rides in infos and
        # patches next_obs so Q targets never bootstrap across episodes
        self.envs = gym.vector.SyncVectorEnv(
            [lambda: gym.make(config["env"], **config.get("env_config", {}))
             for _ in range(self.n_envs)],
            autoreset_mode=gym.vector.AutoresetMode.SAME_STEP)
        space = self.envs.single_action_space
        self.low = np.asarray(space.low, np.float32)
        self.high = np.asarray(space.high, np.float32)
        from ray_tpu.rl.connectors import (apply_pipeline, build_pipeline,
                                           peek_pipeline,
                                           pipeline_output_shape)
        self._pipeline = build_pipeline(config.get("connectors") or ())
        self._apply_pipeline = apply_pipeline
        self._peek_pipeline = peek_pipeline
        obs_dim = int(np.prod(pipeline_output_shape(
            config.get("connectors") or (),
            self.envs.single_observation_space.shape)))
        action_dim = int(np.prod(space.shape))
        self.policy, _ = make_nets(action_dim,
                                   tuple(config.get("hidden_sizes",
                                                    (64, 64))))
        self.params = self.policy.init(
            jax.random.PRNGKey(config.get("seed", 0)),
            jnp.zeros((1, obs_dim)))["params"]
        self._fwd = jax.jit(
            lambda p, o: self.policy.apply({"params": p}, o))
        self.rng = jax.random.PRNGKey(config.get("seed", 0)
                                      + config.get("runner_index", 0) * 997)
        # warmup random actions share the config.seed reproducibility
        # contract with the PRNGKeys above
        self._np_rng = np.random.default_rng(
            config.get("seed", 0) + config.get("runner_index", 0) * 997 + 1)
        self.obs, _ = self.envs.reset(
            seed=config.get("seed", 0) + config.get("runner_index", 0))
        self._cobs = self._apply_pipeline(
            self._pipeline, self.obs.astype(np.float32), is_reset=True)
        self._episode_returns = []
        self._running_returns = np.zeros(self.n_envs)

    def set_weights(self, weights):
        import jax
        self.params = jax.device_put(weights)
        return True

    def _to_env(self, a: np.ndarray) -> np.ndarray:
        return self.low + (a + 1.0) * 0.5 * (self.high - self.low)

    def sample(self, num_steps: Optional[int] = None,
               random_actions: bool = False) -> Dict[str, np.ndarray]:
        import jax
        T = num_steps or self.cfg["rollout_fragment_length"]
        N = self.n_envs
        obs_b, act_b, rew_b, done_b, next_b = [], [], [], [], []
        obs = self.obs
        cobs = self._cobs
        for _ in range(T):
            if random_actions:
                a = self._np_rng.uniform(-1, 1, (N,) + self.low.shape)
            else:
                self.rng, key = jax.random.split(self.rng)
                mean, log_std = self._fwd(self.params,
                                          cobs.astype(np.float32))
                a, _ = squashed_sample(mean, log_std, key)
                a = np.asarray(a)
            nxt, rew, term, trunc, info = self.envs.step(self._to_env(a))
            done = np.logical_or(term, trunc)
            # true next obs: at done steps the env already reset, the
            # actual final observation is in infos (SAME_STEP mode)
            true_next = nxt.astype(np.float32)
            if done.any() and "final_obs" in info:
                true_next = true_next.copy()
                mask = info.get("_final_obs", done)
                for i in np.nonzero(mask)[0]:
                    true_next[i] = info["final_obs"][i]
            cnext = self._peek_pipeline(self._pipeline, true_next)
            obs_b.append(cobs.copy())
            act_b.append(a)
            rew_b.append(rew)
            done_b.append(term.astype(np.float32))  # bootstrap truncation
            next_b.append(cnext)
            self._running_returns += rew
            for i, d in enumerate(done):
                if d:
                    self._episode_returns.append(self._running_returns[i])
                    self._running_returns[i] = 0.0
            obs = nxt
            cobs = self._apply_pipeline(self._pipeline,
                                        nxt.astype(np.float32),
                                        reset_mask=done)
        self.obs = obs
        self._cobs = cobs
        cat = lambda xs: np.concatenate(xs, 0)  # noqa: E731
        return {"obs": cat(obs_b).astype(np.float32),
                "actions": cat(act_b).astype(np.float32),
                "rewards": cat(rew_b).astype(np.float32),
                "dones": cat(done_b).astype(np.float32),
                "next_obs": cat(next_b).astype(np.float32)}

    def get_metrics(self) -> Dict:
        return {"episode_return_mean":
                float(np.mean(self._episode_returns[-20:]))
                if self._episode_returns else None,
                "num_episodes": len(self._episode_returns)}


class SAC:
    """Driver: replay collection + one jitted actor/critic/alpha update."""

    def __init__(self, config: AlgorithmConfig):
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        import optax
        import ray_tpu

        self.config = config
        cfg = dataclasses.asdict(config)
        probe = gym.make(config.env, **config.env_config)
        from ray_tpu.rl.connectors import pipeline_output_shape
        obs_dim = int(np.prod(pipeline_output_shape(
            config.connectors or (), probe.observation_space.shape)))
        action_dim = int(np.prod(probe.action_space.shape))
        probe.close()

        runner_cls = ray_tpu.remote(SacEnvRunner)
        from ray_tpu.rl.actor_manager import FaultTolerantRunnerSet
        self.env_runners = FaultTolerantRunnerSet(
            lambda i: runner_cls.remote({**cfg, "runner_index": i}),
            config.num_env_runners,
            max_restarts=config.max_env_runner_restarts,
            restart_enabled=config.restart_failed_env_runners,
            on_restart=lambda r: __import__("ray_tpu").get(
                r.set_weights.remote(self._current_weights_ref()),
                timeout=300))
        self.buffer = make_replay_buffer(config.replay_buffer_config,
                                         config.replay_capacity,
                                         seed=config.seed)
        self.policy, self.qnet = make_nets(action_dim,
                                           tuple(config.hidden_sizes))
        k0, k1 = jax.random.split(jax.random.PRNGKey(config.seed))
        obs0 = jnp.zeros((1, obs_dim))
        act0 = jnp.zeros((1, action_dim))
        pi_params = self.policy.init(k0, obs0)["params"]
        q_params = self.qnet.init(k1, obs0, act0)["params"]
        log_alpha = jnp.asarray(math.log(config.initial_alpha))
        self.state = {"pi": pi_params, "q": q_params,
                      "q_target": q_params, "log_alpha": log_alpha}
        self.opt = {
            "pi": optax.adam(config.lr),
            "q": optax.adam(config.lr),
            "alpha": optax.adam(config.lr),
        }
        self.opt_state = {
            "pi": self.opt["pi"].init(pi_params),
            "q": self.opt["q"].init(q_params),
            "alpha": self.opt["alpha"].init(log_alpha),
        }
        gamma = config.gamma
        tau = config.tau
        target_entropy = (config.target_entropy
                          if config.target_entropy is not None
                          else -float(action_dim))
        policy, qnet = self.policy, self.qnet
        opt = self.opt

        def q_loss(q_params, state, batch, key, weights):
            mean, log_std = policy.apply({"params": state["pi"]},
                                         batch["next_obs"])
            a2, logp2 = squashed_sample(mean, log_std, key)
            tq1, tq2 = qnet.apply({"params": state["q_target"]},
                                  batch["next_obs"], a2)
            alpha = jnp.exp(state["log_alpha"])
            target = batch["rewards"] + gamma * (1 - batch["dones"]) * (
                jnp.minimum(tq1, tq2) - alpha * logp2)
            target = jax.lax.stop_gradient(target)
            q1, q2 = qnet.apply({"params": q_params},
                                batch["obs"], batch["actions"])
            # per-sample IS weights (prioritized replay; ones = uniform)
            td = 0.5 * (jnp.abs(q1 - target) + jnp.abs(q2 - target))
            loss = (weights * ((q1 - target) ** 2
                               + (q2 - target) ** 2)).mean()
            return loss, td

        def pi_loss(pi_params, state, batch, key):
            mean, log_std = policy.apply({"params": pi_params},
                                         batch["obs"])
            a, logp = squashed_sample(mean, log_std, key)
            q1, q2 = qnet.apply({"params": state["q"]}, batch["obs"], a)
            alpha = jax.lax.stop_gradient(jnp.exp(state["log_alpha"]))
            return (alpha * logp - jnp.minimum(q1, q2)).mean(), logp

        def alpha_loss(log_alpha, logp):
            return (-jnp.exp(log_alpha)
                    * jax.lax.stop_gradient(logp + target_entropy)).mean()

        @jax.jit
        def update(state, opt_state, batch, key, weights):
            k1, k2 = jax.random.split(key)
            (ql, td), q_grads = jax.value_and_grad(q_loss, has_aux=True)(
                state["q"], state, batch, k1, weights)
            qu, new_q_opt = opt["q"].update(q_grads, opt_state["q"],
                                            state["q"])
            new_q = optax.apply_updates(state["q"], qu)
            state = {**state, "q": new_q}
            (pl, logp), pi_grads = jax.value_and_grad(
                pi_loss, has_aux=True)(state["pi"], state, batch, k2)
            pu, new_pi_opt = opt["pi"].update(pi_grads, opt_state["pi"],
                                              state["pi"])
            new_pi = optax.apply_updates(state["pi"], pu)
            al, a_grad = jax.value_and_grad(alpha_loss)(
                state["log_alpha"], logp)
            au, new_a_opt = opt["alpha"].update(
                a_grad, opt_state["alpha"], state["log_alpha"])
            new_log_alpha = optax.apply_updates(state["log_alpha"], au)
            new_target = jax.tree.map(
                lambda t, q: (1 - tau) * t + tau * q,
                state["q_target"], new_q)
            new_state = {"pi": new_pi, "q": new_q, "q_target": new_target,
                         "log_alpha": new_log_alpha}
            new_opt = {"pi": new_pi_opt, "q": new_q_opt,
                       "alpha": new_a_opt}
            return new_state, new_opt, {"q_loss": ql, "pi_loss": pl,
                                        "alpha": jnp.exp(new_log_alpha)}, td

        self._update = update
        self._key = jax.random.PRNGKey(config.seed + 7)
        self.iteration = 0
        self._warmup = True
        self._sync_runner_weights()

    def _current_weights_ref(self):
        import jax
        import ray_tpu
        return ray_tpu.put(jax.device_get(self.state["pi"]))

    def _sync_runner_weights(self):
        self.env_runners.foreach("set_weights",
                                 self._current_weights_ref(), timeout=300)

    def training_step(self) -> Dict:
        import jax
        import jax.numpy as jnp
        import ray_tpu
        cfg = self.config
        t0 = time.perf_counter()
        batches = self.env_runners.foreach(
            "sample", random_actions=self._warmup, timeout=600)
        self._warmup = False
        steps = 0
        for b in batches:
            self.buffer.add(b)
            steps += len(b["obs"])
        metrics = {}
        if len(self.buffer) >= cfg.minibatch_size:
            prioritized = isinstance(self.buffer, PrioritizedReplayBuffer)
            n_updates = max(1, int(steps * cfg.updates_per_step))
            for _ in range(n_updates):
                mb = self.buffer.sample(cfg.minibatch_size)
                indices = mb.pop("indices", None)
                weights = mb.pop("weights", None)
                w = (jnp.asarray(weights) if weights is not None
                     else jnp.ones(cfg.minibatch_size, jnp.float32))
                mb = {k: jnp.asarray(v) for k, v in mb.items()}
                self._key, sub = jax.random.split(self._key)
                self.state, self.opt_state, metrics, td = self._update(
                    self.state, self.opt_state, mb, sub, w)
                if prioritized:
                    self.buffer.update_priorities(indices, np.asarray(td))
            metrics = {k: float(v) for k, v in metrics.items()}
        self._sync_runner_weights()
        wall = time.perf_counter() - t0
        runner_metrics = self.env_runners.foreach("get_metrics",
                                                  timeout=120)
        returns = [m["episode_return_mean"] for m in runner_metrics
                   if m["episode_return_mean"] is not None]
        return {"episode_return_mean":
                float(np.mean(returns)) if returns else None,
                "num_env_steps_sampled": steps,
                "env_steps_per_s": steps / max(1e-9, wall),
                "replay_size": len(self.buffer), **metrics}

    def train(self) -> Dict:
        self.iteration += 1
        out = self.training_step()
        out["training_iteration"] = self.iteration
        return out

    def get_weights(self):
        import jax
        return jax.device_get(self.state["pi"])

    def stop(self):
        import ray_tpu
        for r in self.env_runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self.env_runners = []
