"""External-env plane: policy server + client (reference:
rllib/env/policy_server_input.py + policy_client.py — simulators the
framework does NOT manage connect over HTTP, ask the current policy for
actions, report rewards, and their experience trains the learner).

Shape: a PolicyServer actor hosts the policy module and a threaded HTTP
endpoint. Each get_action runs the module forward (recording logp +
value for the eventual PPO loss); episode ends compute GAE server-side
— the server plays the env-runner's role for envs it cannot step.
ExternalPPO swaps env runners for policy servers in the standard
sample → learn → sync-weights loop. External sims keep working across
weight syncs (actions just start coming from the newer policy)."""

from __future__ import annotations

import json
import threading
import uuid
from typing import Any, Dict, List, Optional

import numpy as np


class PolicyServer:
    """Actor: HTTP policy endpoint + experience buffer.

    Routes (POST, JSON bodies):
      /start_episode  {}                          -> {episode_id}
      /get_action     {episode_id, observation}   -> {action}
      /log_returns    {episode_id, reward}        -> {}
      /end_episode    {episode_id, observation}   -> {}
    """

    def __init__(self, config: Dict, port: int = 0):
        import http.server

        from ray_tpu.rl.rl_module import make_rl_module
        self.cfg = config
        obs_shape = tuple(config["obs_shape"])
        self.module = make_rl_module(
            obs_shape, config["action_spec"],
            config.get("hidden_sizes", (64, 64)),
            seed=config.get("seed", 0))
        import jax
        self._rng = jax.random.PRNGKey(config.get("seed", 0) + 31)
        self.gamma = config.get("gamma", 0.99)
        self.lam = config.get("lambda_", 0.95)
        self._lock = threading.Lock()
        # episode_id -> {"obs": [...], "actions": [...], "logp": [...],
        #               "values": [...], "rewards": [...]}
        self._episodes: Dict[str, Dict[str, List]] = {}
        self._complete: List[Dict[str, np.ndarray]] = []   # GAE'd fragments
        self._returns: List[float] = []

        server = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                try:
                    out = server._route(self.path, body)
                    data = json.dumps(out).encode()
                    self.send_response(200)
                except Exception as e:   # surfaced to the client
                    data = json.dumps({"error": f"{type(e).__name__}: "
                                                f"{e}"}).encode()
                    self.send_response(400)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):
                pass

        self._http = http.server.ThreadingHTTPServer(("0.0.0.0", port),
                                                     _Handler)
        threading.Thread(target=self._http.serve_forever,
                         daemon=True).start()

    # ------------------------------------------------------------ routes
    def _route(self, path: str, body: Dict) -> Dict:
        if path == "/start_episode":
            eid = body.get("episode_id") or uuid.uuid4().hex[:12]
            with self._lock:
                self._episodes[eid] = {"obs": [], "actions": [],
                                       "logp": [], "values": [],
                                       "rewards": []}
            return {"episode_id": eid}
        if path == "/get_action":
            return {"action": self._get_action(
                body["episode_id"], np.asarray(body["observation"],
                                               np.float32))}
        if path == "/log_returns":
            with self._lock:
                ep = self._episodes[body["episode_id"]]
                ep["rewards"].append(float(body["reward"]))
            return {}
        if path == "/end_episode":
            self._end_episode(body["episode_id"],
                              np.asarray(body["observation"], np.float32))
            return {}
        raise ValueError(f"unknown route {path}")

    def _get_action(self, eid: str, obs: np.ndarray):
        import jax
        with self._lock:
            self._rng, key = jax.random.split(self._rng)
            action, logp, value = self.module.sample_actions(
                self.module.params, obs[None], key)
            ep = self._episodes[eid]
            if len(ep["rewards"]) < len(ep["actions"]):
                # client skipped log_returns for a step: implicit 0
                ep["rewards"].append(0.0)
            ep["obs"].append(obs)
            ep["actions"].append(np.asarray(action)[0])
            ep["logp"].append(float(logp[0]))
            ep["values"].append(float(value[0]))
        act = np.asarray(action)[0]
        return act.item() if act.shape == () else act.tolist()

    def _end_episode(self, eid: str, final_obs: np.ndarray):
        """Close the episode and GAE it into a training fragment (the
        env-runner's fragment-end role; terminal value = 0 — external
        episodes end on real termination)."""
        with self._lock:
            ep = self._episodes.pop(eid)
            T = len(ep["actions"])
            if T == 0:
                return
            while len(ep["rewards"]) < T:
                ep["rewards"].append(0.0)
            rew = np.asarray(ep["rewards"], np.float32)
            val = np.asarray(ep["values"], np.float32)
            adv = np.zeros(T, np.float32)
            lastgaelam = 0.0
            for t in reversed(range(T)):
                next_value = val[t + 1] if t + 1 < T else 0.0
                delta = rew[t] + self.gamma * next_value - val[t]
                lastgaelam = delta + self.gamma * self.lam * lastgaelam
                adv[t] = lastgaelam
            self._complete.append({
                "obs": np.stack(ep["obs"]).astype(np.float32),
                "actions": np.asarray(ep["actions"]),
                "logp": np.asarray(ep["logp"], np.float32),
                "advantages": adv,
                "value_targets": adv + val,
            })
            self._returns.append(float(rew.sum()))

    # ------------------------------------------------------- trainer side
    def address(self) -> str:
        from ray_tpu._private.rpc import node_ip_address
        return f"http://{node_ip_address()}:{self._http.server_port}"

    def set_weights(self, weights) -> bool:
        with self._lock:
            self.module.set_weights(weights)
        return True

    def drain(self) -> List[Dict[str, np.ndarray]]:
        """Completed, GAE'd episode fragments since the last drain."""
        with self._lock:
            out, self._complete = self._complete, []
            return out

    def get_metrics(self) -> Dict:
        with self._lock:
            recent = self._returns[-20:]
            return {"episode_return_mean":
                    float(np.mean(recent)) if recent else None,
                    "num_episodes": len(self._returns)}


class PolicyClient:
    """External-simulator side (reference: rllib PolicyClient): plain
    HTTP, no framework dependency beyond stdlib — an external process
    can copy this class wholesale."""

    def __init__(self, address: str, timeout: float = 60.0):
        self.address = address.rstrip("/")
        self.timeout = timeout

    def _post(self, route: str, body: Dict) -> Dict:
        import urllib.error
        import urllib.request
        req = urllib.request.Request(
            self.address + route, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                out = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read()).get("error", str(e))
            except Exception:
                detail = str(e)
            raise RuntimeError(f"policy server: {detail}") from None
        if "error" in out:
            raise RuntimeError(out["error"])
        return out

    def start_episode(self, episode_id: Optional[str] = None) -> str:
        return self._post("/start_episode",
                          {"episode_id": episode_id})["episode_id"]

    def get_action(self, episode_id: str, observation) -> Any:
        obs = np.asarray(observation, np.float32).tolist()
        return self._post("/get_action", {"episode_id": episode_id,
                                          "observation": obs})["action"]

    def log_returns(self, episode_id: str, reward: float) -> None:
        self._post("/log_returns", {"episode_id": episode_id,
                                    "reward": float(reward)})

    def end_episode(self, episode_id: str, observation) -> None:
        obs = np.asarray(observation, np.float32).tolist()
        self._post("/end_episode", {"episode_id": episode_id,
                                    "observation": obs})


class ExternalPPO:
    """PPO whose experience arrives from external simulators through
    PolicyServer actors instead of managed env runners (reference:
    rllib's policy-server workflow: server input + standard PPO
    training loop)."""

    def __init__(self, config, num_servers: int = 1):
        import dataclasses

        import gymnasium as gym
        import ray_tpu
        from ray_tpu.rl import envs as _envs
        from ray_tpu.rl.learner import LearnerGroup
        from ray_tpu.rl.rl_module import action_spec_of
        _envs.register_envs()
        self.config = config
        probe = gym.make(config.env, **config.env_config)
        obs_shape = probe.observation_space.shape
        spec = action_spec_of(probe.action_space)
        probe.close()
        cfg_dict = dataclasses.asdict(config)
        cfg_dict["obs_shape"] = list(obs_shape)
        cfg_dict["action_spec"] = spec
        server_cls = ray_tpu.remote(PolicyServer)
        self.servers = [
            server_cls.options(max_concurrency=8).remote(cfg_dict)
            for _ in range(num_servers)]
        self.addresses = ray_tpu.get(
            [s.address.remote() for s in self.servers], timeout=120)
        obs_dim = int(np.prod(obs_shape))
        action_dim = spec.get("n") or spec["dim"]
        self.learner_group = LearnerGroup(cfg_dict, obs_dim, action_dim)
        self.iteration = 0
        self._sync_weights()

    def _sync_weights(self):
        import ray_tpu
        ref = ray_tpu.put(self.learner_group.get_weights())
        ray_tpu.get([s.set_weights.remote(ref) for s in self.servers],
                    timeout=120)

    def training_step(self) -> Dict:
        import time as _time

        import ray_tpu
        t0 = _time.perf_counter()
        # wait for enough external experience to fill a train batch
        frags: List[Dict[str, np.ndarray]] = []
        rows = 0
        deadline = _time.monotonic() + self.config.train_batch_size / 10
        while rows < self.config.train_batch_size \
                and _time.monotonic() < deadline:
            new = [f for batch in ray_tpu.get(
                [s.drain.remote() for s in self.servers], timeout=60)
                for f in batch]
            frags.extend(new)
            rows += sum(len(f["obs"]) for f in new)
            if rows < self.config.train_batch_size:
                _time.sleep(0.05)
        metrics: Dict = {}
        if frags:
            batch = {k: np.concatenate([f[k] for f in frags])
                     for k in frags[0]}
            metrics = self.learner_group.update_from_batch(batch)
            self._sync_weights()
        server_metrics = ray_tpu.get(
            [s.get_metrics.remote() for s in self.servers], timeout=60)
        returns = [m["episode_return_mean"] for m in server_metrics
                   if m["episode_return_mean"] is not None]
        return {"episode_return_mean":
                float(np.mean(returns)) if returns else None,
                "num_env_steps_sampled": rows,
                "env_steps_per_s": rows / max(1e-9,
                                              _time.perf_counter() - t0),
                **metrics}

    def train(self) -> Dict:
        self.iteration += 1
        out = self.training_step()
        out["training_iteration"] = self.iteration
        return out

    def stop(self):
        import ray_tpu
        for s in self.servers:
            try:
                ray_tpu.kill(s)
            except Exception:
                pass
        self.servers = []
