"""DQN: off-policy Q-learning with replay and a target network
(reference: rllib/algorithms/dqn/ — double-DQN target, epsilon-greedy
exploration; the Q update is one jitted function, target sync by period).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, Optional, Sequence

import numpy as np

from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.replay_buffer import (PrioritizedReplayBuffer,
                                      make_replay_buffer)


class QEnvRunner:
    """Epsilon-greedy transition collector over gym vector envs."""

    def __init__(self, config: Dict):
        import gymnasium as gym
        self.cfg = config
        self.n_envs = config["num_envs_per_env_runner"]
        # SAME_STEP autoreset + final-obs patching (see rl/sac.py)
        self.envs = gym.vector.SyncVectorEnv(
            [lambda: gym.make(config["env"], **config.get("env_config", {}))
             for _ in range(self.n_envs)],
            autoreset_mode=gym.vector.AutoresetMode.SAME_STEP)
        from ray_tpu.rl.connectors import (apply_pipeline, build_pipeline,
                                           peek_pipeline,
                                           pipeline_output_shape)
        self._pipeline = build_pipeline(config.get("connectors") or ())
        self._apply_pipeline = apply_pipeline
        self._peek_pipeline = peek_pipeline
        obs_dim = int(np.prod(pipeline_output_shape(
            config.get("connectors") or (),
            self.envs.single_observation_space.shape)))
        self.action_dim = self.envs.single_action_space.n
        from ray_tpu.rl.dqn import QNet   # self-import for actor pickling
        import jax
        import jax.numpy as jnp
        self.net = QNet(self.action_dim,
                        tuple(config.get("hidden_sizes", (64, 64))))
        self.params = self.net.init(
            jax.random.PRNGKey(config.get("seed", 0)),
            jnp.zeros((1, obs_dim)))["params"]
        self._q = jax.jit(lambda p, o: self.net.apply({"params": p}, o))
        self.rng = np.random.default_rng(
            config.get("seed", 0) + config.get("runner_index", 0) * 1000)
        self.obs, _ = self.envs.reset(
            seed=config.get("seed", 0) + config.get("runner_index", 0))
        self._cobs = self._apply_pipeline(
            self._pipeline, self.obs.astype(np.float32), is_reset=True)
        self._episode_returns = []
        self._running_returns = np.zeros(self.n_envs)

    def set_weights(self, weights):
        import jax
        self.params = jax.device_put(weights)
        return True

    def sample(self, num_steps: Optional[int] = None,
               epsilon: float = 0.1) -> Dict[str, np.ndarray]:
        T = num_steps or self.cfg["rollout_fragment_length"]
        N = self.n_envs
        obs_b, act_b, rew_b, done_b, next_b = [], [], [], [], []
        obs = self.obs
        cobs = self._cobs
        for _ in range(T):
            q = np.asarray(self._q(self.params, cobs.astype(np.float32)))
            greedy = q.argmax(-1)
            random_a = self.rng.integers(0, self.action_dim, N)
            explore = self.rng.random(N) < epsilon
            action = np.where(explore, random_a, greedy)
            nxt, rew, term, trunc, info = self.envs.step(action)
            done = np.logical_or(term, trunc)
            true_next = nxt.astype(np.float32)
            if done.any() and "final_obs" in info:
                true_next = true_next.copy()
                mask = info.get("_final_obs", done)
                for i in np.nonzero(mask)[0]:
                    true_next[i] = info["final_obs"][i]
            obs_b.append(cobs.copy())
            act_b.append(action)
            rew_b.append(rew)
            # bootstrap through time-limit truncation, not termination
            done_b.append(term.astype(np.float32))
            next_b.append(self._peek_pipeline(self._pipeline, true_next))
            self._running_returns += rew
            for i, d in enumerate(done):
                if d:
                    self._episode_returns.append(self._running_returns[i])
                    self._running_returns[i] = 0.0
            obs = nxt
            cobs = self._apply_pipeline(self._pipeline,
                                        nxt.astype(np.float32),
                                        reset_mask=done)
        self.obs = obs
        self._cobs = cobs
        cat = lambda xs: np.concatenate(xs, 0)  # noqa: E731
        return {"obs": cat(obs_b).astype(np.float32),
                "actions": cat(act_b).astype(np.int64),
                "rewards": cat(rew_b).astype(np.float32),
                "dones": cat(done_b).astype(np.float32),
                "next_obs": cat(next_b).astype(np.float32)}

    def get_metrics(self) -> Dict:
        return {"episode_return_mean":
                float(np.mean(self._episode_returns[-20:]))
                if self._episode_returns else None,
                "num_episodes": len(self._episode_returns)}


def QNet(action_dim: int, hidden_sizes: Sequence[int]):
    from flax import linen as nn

    class _QNet(nn.Module):
        @nn.compact
        def __call__(self, obs):
            x = obs
            for h in hidden_sizes:
                x = nn.relu(nn.Dense(h)(x))
            return nn.Dense(action_dim)(x)

    return _QNet()


class DQN:
    """Driver: epsilon-annealed sampling into a replay buffer, double-DQN
    updates, periodic target sync."""

    def __init__(self, config: AlgorithmConfig):
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        import optax
        import ray_tpu

        self.config = config
        cfg = dataclasses.asdict(config)
        probe = gym.make(config.env, **config.env_config)
        from ray_tpu.rl.connectors import pipeline_output_shape
        obs_dim = int(np.prod(pipeline_output_shape(
            config.connectors or (), probe.observation_space.shape)))
        action_dim = probe.action_space.n
        probe.close()

        runner_cls = ray_tpu.remote(QEnvRunner)
        from ray_tpu.rl.actor_manager import FaultTolerantRunnerSet
        self.env_runners = FaultTolerantRunnerSet(
            lambda i: runner_cls.remote({**cfg, "runner_index": i}),
            config.num_env_runners,
            max_restarts=config.max_env_runner_restarts,
            restart_enabled=config.restart_failed_env_runners,
            on_restart=lambda r: __import__("ray_tpu").get(
                r.set_weights.remote(self._current_weights_ref()),
                timeout=300))
        self.buffer = make_replay_buffer(
            config.replay_buffer_config, cfg.get("replay_capacity", 50_000),
            seed=config.seed)
        self.net = QNet(action_dim, tuple(config.hidden_sizes))
        self.params = self.net.init(jax.random.PRNGKey(config.seed),
                                    jnp.zeros((1, obs_dim)))["params"]
        self.target_params = self.params
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        gamma = config.gamma
        net = self.net

        def loss_fn(params, target_params, batch, weights):
            q = net.apply({"params": params}, batch["obs"])
            q_a = jnp.take_along_axis(
                q, batch["actions"][:, None], 1)[:, 0]
            q_next_online = net.apply({"params": params}, batch["next_obs"])
            best = q_next_online.argmax(-1)
            q_next_tgt = net.apply({"params": target_params},
                                   batch["next_obs"])
            q_best = jnp.take_along_axis(q_next_tgt, best[:, None], 1)[:, 0]
            target = batch["rewards"] + gamma * (1 - batch["dones"]) \
                * jax.lax.stop_gradient(q_best)
            td = q_a - target
            # per-sample importance weights (prioritized replay IS
            # correction; all-ones under the uniform buffer)
            return (weights * td ** 2).mean(), td

        @jax.jit
        def update(params, target_params, opt_state, batch, weights):
            (loss, td), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target_params, batch, weights)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            return (optax.apply_updates(params, updates), opt_state, loss,
                    td)

        self._update = update
        self.iteration = 0
        self._grad_steps = 0
        self.epsilon = 1.0
        self._sync_runner_weights()

    def _current_weights_ref(self):
        import jax
        import ray_tpu
        return ray_tpu.put(jax.device_get(self.params))

    def _sync_runner_weights(self):
        self.env_runners.foreach("set_weights",
                                 self._current_weights_ref(), timeout=300)

    def training_step(self) -> Dict:
        import jax.numpy as jnp
        import ray_tpu
        cfg = self.config
        t0 = time.perf_counter()
        batches = self.env_runners.foreach(
            "sample", epsilon=self.epsilon, timeout=600)
        steps = 0
        for b in batches:
            self.buffer.add(b)
            steps += len(b["obs"])
        self.epsilon = max(0.05, self.epsilon * 0.95)

        loss = float("nan")
        if len(self.buffer) >= cfg.minibatch_size:
            prioritized = isinstance(self.buffer, PrioritizedReplayBuffer)
            for _ in range(cfg.num_epochs * 4):
                mb = self.buffer.sample(cfg.minibatch_size)
                indices = mb.pop("indices", None)
                weights = mb.pop("weights", None)
                w = (jnp.asarray(weights) if weights is not None
                     else jnp.ones(cfg.minibatch_size, jnp.float32))
                mb = {k: jnp.asarray(v) for k, v in mb.items()}
                self.params, self.opt_state, loss, td = self._update(
                    self.params, self.target_params, self.opt_state, mb, w)
                if prioritized:
                    self.buffer.update_priorities(indices, np.asarray(td))
                self._grad_steps += 1
                if self._grad_steps % 100 == 0:
                    self.target_params = self.params
            loss = float(loss)
        self._sync_runner_weights()
        wall = time.perf_counter() - t0
        runner_metrics = self.env_runners.foreach("get_metrics",
                                                  timeout=120)
        returns = [m["episode_return_mean"] for m in runner_metrics
                   if m["episode_return_mean"] is not None]
        return {"episode_return_mean":
                float(np.mean(returns)) if returns else None,
                "num_env_steps_sampled": steps,
                "env_steps_per_s": steps / max(1e-9, wall),
                "td_loss": loss, "epsilon": self.epsilon,
                "replay_size": len(self.buffer)}

    def train(self) -> Dict:
        self.iteration += 1
        out = self.training_step()
        out["training_iteration"] = self.iteration
        return out

    def stop(self):
        import ray_tpu
        for r in self.env_runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self.env_runners = []
