"""Fault-tolerant env-runner set (reference:
rllib/utils/actor_manager.py FaultTolerantActorManager +
Algorithm.restart_failed_env_runners — RLlib restarts dead env runners
mid-training and keeps the training loop alive on the survivors).

Re-designed for this package's driver loops: a list-compatible
container (algorithms iterate/len it like the plain list it replaces)
whose `foreach` fans a method out to every runner, drops the round's
results from runners that died (ActorDiedError), and replaces each dead
runner in its slot — same runner_index config, fresh actor — pushing
current weights via the `on_restart` hook. Async drivers (IMPALA) call
`replace` directly when a sampled future surfaces a dead actor.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, List, Optional

logger = logging.getLogger(__name__)


class RunnerSetBroken(RuntimeError):
    """All runners failed, or the restart budget is exhausted."""


class FaultTolerantRunnerSet(list):
    """List of actor handles + restart policy. Slots are stable: the
    runner at index i is always configured with runner_index=i, so
    restarts preserve seeding/sharding structure."""

    def __init__(self, make_runner: Callable[[int], Any], num: int,
                 max_restarts: int = 3, restart_enabled: bool = True,
                 on_restart: Optional[Callable[[Any], None]] = None):
        super().__init__(make_runner(i) for i in range(num))
        self._make = make_runner
        self._on_restart = on_restart
        self.max_restarts = max_restarts
        self.restart_enabled = restart_enabled
        self.num_restarts = 0

    def set_on_restart(self, fn: Callable[[Any], None]) -> None:
        self._on_restart = fn

    def broadcast_weights(self, weights) -> Any:
        """Put `weights` once and pre-position the sealed blob on EVERY
        node through the weight-distribution plane
        (``ray_tpu.broadcast_weights``: spanning arena allocation for
        multi-GB params, log-depth binomial relay fan-out over the
        striped data plane) — so N runners' ``set_weights`` resolve
        their arg from the local arena instead of N point-to-point
        pulls off the learner's node. Returns the ObjectRef to pass to
        ``foreach("set_weights", ref)``. Falls back to a plain put when
        the broadcast plane is unavailable (client mode, degraded
        cluster) — runners then pull point-to-point as before."""
        import ray_tpu
        try:
            return ray_tpu.broadcast_weights(weights)
        except Exception:
            logger.warning("weight broadcast unavailable; falling back "
                           "to point-to-point weight pulls", exc_info=True)
            return ray_tpu.put(weights)

    def replace(self, runner) -> Optional[Any]:
        """Runner observed dead: recreate it in its slot; returns the
        replacement. Returns None if the runner was ALREADY replaced (a
        stale in-flight future can surface one death twice — once via
        foreach, once via the async loop). Raises RunnerSetBroken once
        the restart budget is spent (a persistent crash loop should
        fail the experiment, not spin)."""
        import ray_tpu
        try:
            i = self.index(runner)
        except ValueError:
            logger.debug("runner already replaced; ignoring")
            return None
        if not self.restart_enabled or \
                self.num_restarts >= self.max_restarts:
            raise RunnerSetBroken(
                f"env runner {i} died and restarts are "
                f"{'disabled' if not self.restart_enabled else 'exhausted'}"
                f" ({self.num_restarts}/{self.max_restarts})")
        self.num_restarts += 1
        try:
            ray_tpu.kill(runner)
        except Exception:
            pass
        logger.warning("env runner %d died; restarting (%d/%d)",
                       i, self.num_restarts, self.max_restarts)
        fresh = self._make(i)
        self[i] = fresh
        if self._on_restart is not None:
            try:
                self._on_restart(fresh)
            except Exception:
                logger.exception("on_restart hook failed for runner %d", i)
        return fresh

    def foreach(self, method: str, *args, timeout: float = 600.0,
                **kwargs) -> List[Any]:
        """Call `method` on every runner; per-runner result gather.
        Dead AND timed-out runners are replaced and their result dropped —
        callers get >=1 result or RunnerSetBroken. `timeout` is ONE shared
        deadline for the whole gather (N runners never stretch a round to
        N x timeout; a runner that hangs past the deadline is treated as
        failed exactly like one that died)."""
        import time

        import ray_tpu
        calls = [(r, getattr(r, method).remote(*args, **kwargs))
                 for r in list(self)]
        results = []
        deadline = time.monotonic() + timeout
        for runner, ref in calls:
            remaining = deadline - time.monotonic()
            try:
                results.append(
                    ray_tpu.get(ref, timeout=max(0.001, remaining)))
            except ray_tpu.ActorDiedError:
                self.replace(runner)
            except TimeoutError:   # asyncio.TimeoutError is an alias
                logger.warning(
                    "env runner hung in %s past the %.0fs deadline; "
                    "treating it as failed", method, timeout)
                self.replace(runner)
        if not results:
            raise RunnerSetBroken(f"every env runner died during {method}")
        return results
