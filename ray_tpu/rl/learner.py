"""JaxLearner + LearnerGroup (reference: rllib/core/learner/learner.py,
torch_learner.py:64 compute/apply gradients, learner_group.py:80).
The PPO update is one jitted function (minibatch epochs via host loop);
multi-learner data parallelism averages gradients through the collective
store backend (on TPU pods the learners would instead share one jit over
the device mesh — psum by sharding)."""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class JaxLearner:
    def __init__(self, config: Dict, obs_dim: int, action_dim: int):
        import jax
        import jax.numpy as jnp
        import optax
        from ray_tpu.rl.rl_module import DiscreteRLModule

        self.cfg = config
        self.module = DiscreteRLModule(obs_dim, action_dim,
                                       config.get("hidden_sizes", (64, 64)),
                                       seed=config.get("seed", 0))
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(config.get("grad_clip", 0.5)),
            optax.adam(config["lr"]))
        self.opt_state = self.optimizer.init(self.module.params)
        clip = config["clip_param"]
        vf_coeff = config["vf_loss_coeff"]
        ent_coeff = config["entropy_coeff"]
        net = self.module.net

        def loss_fn(params, batch):
            logits, values = net.apply({"params": params}, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=1)[:, 0]
            ratio = jnp.exp(logp - batch["logp"])
            adv = batch["advantages"]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            pg1 = ratio * adv
            pg2 = jnp.clip(ratio, 1 - clip, 1 + clip) * adv
            pg_loss = -jnp.minimum(pg1, pg2).mean()
            vf_loss = ((values - batch["value_targets"]) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
            return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": entropy}

        import jax

        @jax.jit
        def update(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, new_opt = self.optimizer.update(grads, opt_state,
                                                     params)
            import optax as _ox
            new_params = _ox.apply_updates(params, updates)
            return new_params, new_opt, loss, aux

        @jax.jit
        def grads_only(params, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            return grads, loss, aux

        @jax.jit
        def apply_grads(params, opt_state, grads):
            updates, new_opt = self.optimizer.update(grads, opt_state,
                                                     params)
            import optax as _ox
            return _ox.apply_updates(params, updates), new_opt

        self._update = update
        self._grads_only = grads_only
        self._apply_grads = apply_grads

    def update_from_batch(self, batch: Dict[str, np.ndarray]) -> Dict:
        import jax.numpy as jnp
        n = len(batch["obs"])
        mb = self.cfg["minibatch_size"]
        rng = np.random.default_rng(0)
        metrics = {}
        for _ in range(self.cfg["num_epochs"]):
            idx = rng.permutation(n)
            for start in range(0, n, mb):
                sel = idx[start:start + mb]
                mini = {k: jnp.asarray(v[sel]) for k, v in batch.items()}
                self.module.params, self.opt_state, loss, aux = \
                    self._update(self.module.params, self.opt_state, mini)
        metrics = {k: float(v) for k, v in aux.items()}
        metrics["total_loss"] = float(loss)
        return metrics

    def compute_gradients(self, batch: Dict[str, np.ndarray]):
        import jax
        import jax.numpy as jnp
        mini = {k: jnp.asarray(v) for k, v in batch.items()}
        grads, loss, aux = self._grads_only(self.module.params, mini)
        return jax.device_get(grads), float(loss)

    def apply_gradients(self, grads):
        self.module.params, self.opt_state = self._apply_grads(
            self.module.params, self.opt_state, grads)
        return True

    def get_weights(self):
        return self.module.get_weights()

    def set_weights(self, weights):
        self.module.set_weights(weights)
        return True


class LearnerGroup:
    """Data-parallel learners as actors; single-learner runs in-process
    (reference: learner_group.py local mode vs remote learner actors)."""

    def __init__(self, config: Dict, obs_dim: int, action_dim: int):
        import ray_tpu
        self.cfg = config
        self.n = config.get("num_learners", 1)
        if self.n <= 1:
            self.local = JaxLearner(config, obs_dim, action_dim)
            self.remote = []
        else:
            self.local = None
            cls = ray_tpu.remote(JaxLearner)
            self.remote = [cls.remote(config, obs_dim, action_dim)
                           for _ in range(self.n)]

    def update_from_batch(self, batch: Dict[str, np.ndarray]) -> Dict:
        import ray_tpu
        if self.local is not None:
            return self.local.update_from_batch(batch)
        # split batch across learners, average gradients per minibatch-free
        # round (simplified DDP: one grad step per call per learner)
        import jax
        shards = {k: np.array_split(v, self.n) for k, v in batch.items()}
        per = [{k: shards[k][i] for k in batch} for i in range(self.n)]
        grad_refs = [l.compute_gradients.remote(p)
                     for l, p in zip(self.remote, per)]
        grads_losses = ray_tpu.get(grad_refs, timeout=300)
        grads = [g for g, _ in grads_losses]
        avg = jax.tree.map(lambda *gs: np.mean(np.stack(gs), axis=0),
                           *grads)
        ray_tpu.get([l.apply_gradients.remote(avg) for l in self.remote],
                    timeout=300)
        return {"total_loss": float(np.mean([l for _, l in grads_losses]))}

    def get_weights(self):
        import ray_tpu
        if self.local is not None:
            return self.local.get_weights()
        return ray_tpu.get(self.remote[0].get_weights.remote(), timeout=120)
