"""JaxLearner + LearnerGroup (reference: rllib/core/learner/learner.py,
torch_learner.py:64 compute/apply gradients, learner_group.py:80).

The PPO update is one jitted function (minibatch epochs via host loop).
Multi-learner data parallelism runs the IDENTICAL epoch/minibatch
schedule on every learner with per-minibatch gradient averaging — the
same algorithm as n=1, just with an n-times-larger effective minibatch
(reference: learner_group.py DDP semantics — every learner executes the
same update loop with synced grads; on TPU pods the learners would
instead share one jit over the device mesh, psum by sharding)."""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class JaxLearner:
    def __init__(self, config: Dict, obs_dim: int, action_dim: int):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rl.rl_module import make_rl_module

        self.cfg = config
        obs_shape = tuple(config.get("obs_shape") or (obs_dim,))
        action_spec = (config.get("action_spec")
                       or {"type": "discrete", "n": action_dim})
        self.module = make_rl_module(
            obs_shape, action_spec,
            config.get("hidden_sizes", (64, 64)),
            seed=config.get("seed", 0))
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(config.get("grad_clip", 0.5)),
            optax.adam(config["lr"]))
        self.opt_state = self.optimizer.init(self.module.params)
        self.num_updates = 0
        self._shard: Optional[Dict[str, np.ndarray]] = None
        clip = config["clip_param"]
        vf_coeff = config["vf_loss_coeff"]
        ent_coeff = config["entropy_coeff"]
        module = self.module

        def loss_fn(params, batch):
            logp, entropy, values = module.logp_entropy_value(
                params, batch["obs"], batch["actions"])
            ratio = jnp.exp(logp - batch["logp"])
            adv = batch["advantages"]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            pg1 = ratio * adv
            pg2 = jnp.clip(ratio, 1 - clip, 1 + clip) * adv
            pg_loss = -jnp.minimum(pg1, pg2).mean()
            vf_loss = ((values - batch["value_targets"]) ** 2).mean()
            ent = entropy.mean()
            total = pg_loss + vf_coeff * vf_loss - ent_coeff * ent
            return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": ent}

        @jax.jit
        def update(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, new_opt = self.optimizer.update(grads, opt_state,
                                                     params)
            new_params = optax.apply_updates(params, updates)
            return new_params, new_opt, loss, aux

        @jax.jit
        def grads_only(params, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            return grads, loss, aux

        @jax.jit
        def apply_grads(params, opt_state, grads):
            updates, new_opt = self.optimizer.update(grads, opt_state,
                                                     params)
            return optax.apply_updates(params, updates), new_opt

        self._update = update
        self._grads_only = grads_only
        self._apply_grads = apply_grads
        # per-minibatch time/FLOP attribution: wrap_jit AOT-compiles each
        # minibatch shape once (cost_analysis comes free) and the update
        # loop marks data-build vs compute, so the learner emits
        # runtime_rl_update_mfu + phase gauges into /metrics and the GCS
        # time-series plane (util/profiling.py)
        from ray_tpu.util.profiling import StepProfiler
        self.profiler = StepProfiler("rl_update", emit_span=False,
                                     emit_every=8)
        self._update_profiled = self.profiler.wrap_jit(self._update)

    def update_from_batch(self, batch: Dict[str, np.ndarray]) -> Dict:
        import jax.numpy as jnp
        n = len(batch["obs"])
        mb = self.cfg["minibatch_size"]
        rng = np.random.default_rng(0)
        for _ in range(self.cfg["num_epochs"]):
            idx = rng.permutation(n)
            for start in range(0, n, mb):
                with self.profiler.step(tokens=min(mb, n - start)) as sc:
                    sel = idx[start:start + mb]
                    mini = {k: jnp.asarray(v[sel])
                            for k, v in batch.items()}
                    sc.data_ready()
                    self.module.params, self.opt_state, loss, aux = \
                        self._update_profiled(self.module.params,
                                              self.opt_state, mini)
                    sc.block(loss)
                self.num_updates += 1
        metrics = {k: float(v) for k, v in aux.items()}
        metrics["total_loss"] = float(loss)
        metrics["num_minibatch_updates"] = self.num_updates
        return metrics

    # ------------------------------------------------- multi-learner path
    def set_batch(self, shard: Dict[str, np.ndarray]) -> int:
        """Stage this learner's shard for the epoch/minibatch schedule."""
        self._shard = {k: np.asarray(v) for k, v in shard.items()}
        return len(self._shard["obs"])

    def minibatch_gradients(self, epoch: int, mb_index: int):
        """Gradients for minibatch `mb_index` of epoch `epoch` over the
        staged shard — every learner runs the SAME schedule; the group
        averages these per minibatch (reference DDP semantics)."""
        import jax
        import jax.numpy as jnp
        n = len(self._shard["obs"])
        mb = min(self.cfg["minibatch_size"], n)
        idx = np.random.default_rng(epoch).permutation(n)
        sel = idx[(mb_index * mb) % n:(mb_index * mb) % n + mb]
        mini = {k: jnp.asarray(v[sel]) for k, v in self._shard.items()}
        grads, loss, aux = self._grads_only(self.module.params, mini)
        return (jax.device_get(grads), float(loss),
                {k: float(v) for k, v in aux.items()})

    def compute_gradients(self, batch: Dict[str, np.ndarray]):
        import jax
        import jax.numpy as jnp
        mini = {k: jnp.asarray(v) for k, v in batch.items()}
        grads, loss, aux = self._grads_only(self.module.params, mini)
        return jax.device_get(grads), float(loss)

    def apply_gradients(self, grads):
        self.module.params, self.opt_state = self._apply_grads(
            self.module.params, self.opt_state, grads)
        self.num_updates += 1
        return self.num_updates

    def get_weights(self):
        return self.module.get_weights()

    def set_weights(self, weights):
        self.module.set_weights(weights)
        return True


class LearnerGroup:
    """Data-parallel learners as actors; single-learner runs in-process
    (reference: learner_group.py local mode vs remote learner actors)."""

    def __init__(self, config: Dict, obs_dim: int, action_dim: int):
        import ray_tpu
        self.cfg = config
        self.n = config.get("num_learners", 1)
        self.num_updates = 0
        if self.n <= 1:
            self.local = JaxLearner(config, obs_dim, action_dim)
            self.remote = []
        else:
            self.local = None
            cls = ray_tpu.remote(JaxLearner)
            self.remote = [cls.remote(config, obs_dim, action_dim)
                           for _ in range(self.n)]

    def update_from_batch(self, batch: Dict[str, np.ndarray]) -> Dict:
        import ray_tpu
        if self.local is not None:
            m = self.local.update_from_batch(batch)
            self.num_updates = m["num_minibatch_updates"]
            return m
        # n>1 runs the SAME minibatch-epoch PPO as n=1: each learner
        # holds a shard, every (epoch, minibatch) step computes local
        # grads which are averaged and applied everywhere — NOT one giant
        # step on split shards (round-3 weakness #3)
        import jax
        shards = {k: np.array_split(v, self.n) for k, v in batch.items()}
        per = [{k: shards[k][i] for k in batch} for i in range(self.n)]
        rows = ray_tpu.get(
            [l.set_batch.remote(p) for l, p in zip(self.remote, per)],
            timeout=300)
        mb = self.cfg["minibatch_size"]
        # ceil: the tail minibatch is included, same as the n=1 loop's
        # range(0, n, mb) (a floor would silently drop up to mb-1 rows
        # of experience per shard per epoch)
        n_mb = max(1, -(-min(rows) // max(1, mb)))
        losses, aux = [], {}
        for epoch in range(self.cfg["num_epochs"]):
            for j in range(n_mb):
                outs = ray_tpu.get(
                    [l.minibatch_gradients.remote(epoch, j)
                     for l in self.remote], timeout=300)
                grads = [g for g, _, _ in outs]
                losses = [l for _, l, _ in outs]
                aux = outs[0][2]
                avg = jax.tree.map(
                    lambda *gs: np.mean(np.stack(gs), axis=0), *grads)
                avg_ref = ray_tpu.put(avg)
                self.num_updates = ray_tpu.get(
                    [l.apply_gradients.remote(avg_ref)
                     for l in self.remote], timeout=300)[0]
        return {**aux, "total_loss": float(np.mean(losses)),
                "num_minibatch_updates": self.num_updates}

    def get_weights(self):
        import ray_tpu
        if self.local is not None:
            return self.local.get_weights()
        return ray_tpu.get(self.remote[0].get_weights.remote(), timeout=120)
