"""V-trace off-policy correction (IMPALA), jax implementation.

Computes the v-trace value targets and policy-gradient advantages from
behavior-policy log-probs vs target-policy log-probs (reference:
rllib/algorithms/impala/vtrace_torch.py — re-derived from the IMPALA
paper's eq. 1, not translated). The backward recursion is a lax.scan in
reverse time, so the whole thing jits and differentiates cleanly.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class VTraceReturns(NamedTuple):
    vs: jax.Array              # [T, B] value targets for the baseline loss
    pg_advantages: jax.Array   # [T, B] advantages for the policy gradient


def vtrace(behavior_logp: jax.Array,
           target_logp: jax.Array,
           rewards: jax.Array,
           discounts: jax.Array,
           values: jax.Array,
           bootstrap_value: jax.Array,
           clip_rho_threshold: float = 1.0,
           clip_c_threshold: float = 1.0) -> VTraceReturns:
    """All time-major [T, B]; bootstrap_value [B].

    discounts = gamma * (1 - done): zero at terminal steps.
    """
    rhos = jnp.exp(target_logp - behavior_logp)
    clipped_rhos = jnp.minimum(clip_rho_threshold, rhos)
    cs = jnp.minimum(clip_c_threshold, rhos)

    values_tp1 = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)
    deltas = clipped_rhos * (rewards + discounts * values_tp1 - values)

    def backward(acc, xs):
        delta_t, discount_t, c_t = xs
        acc = delta_t + discount_t * c_t * acc
        return acc, acc

    _, vs_minus_v = lax.scan(
        backward, jnp.zeros_like(bootstrap_value),
        (deltas, discounts, cs), reverse=True)
    vs = vs_minus_v + values

    vs_tp1 = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_advantages = clipped_rhos * (rewards + discounts * vs_tp1 - values)
    return VTraceReturns(vs=lax.stop_gradient(vs),
                         pg_advantages=lax.stop_gradient(pg_advantages))
