"""IMPALA: async actor-learner RL with V-trace off-policy correction
(reference: rllib/algorithms/impala/impala.py — async EnvRunner sampling
with aggregator-style batching :617, vtrace loss; re-designed: the learner
update is one jitted function and asynchrony comes from `ray_tpu.wait`
over in-flight sample futures rather than dedicated aggregator actors —
re-issue a runner's next fragment before learning on its last one)."""

from __future__ import annotations

import time
from typing import Dict, List

import numpy as np

from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig


def _seq_forward(module, params, batch):
    """(dist, values [T,B]) for a time-major trajectory batch, where
    dist is the module family's distribution parameters (logits for
    categorical, (mean, log_std) for Gaussian — consumed by the module's
    `seq_logp_entropy`). Recurrent- and conv-aware: feedforward modules
    flatten time into the batch; recurrent modules re-derive every LSTM
    state with a scanned unroll from the fragment's initial carry,
    resetting exactly where the runner's episodes did (connector state
    discipline)."""
    import jax
    import jax.numpy as jnp
    T, B = batch["dones"].shape
    if getattr(module, "is_recurrent", False):
        resets = jnp.concatenate(
            [jnp.zeros((1, B), jnp.float32), batch["dones"][:-1]], axis=0)
        carry0 = (batch["initial_state_c"], batch["initial_state_h"])
        dist, values, _ = module.forward_seq(params, batch["obs"],
                                             resets, carry0)
        return dist, values
    obs = batch["obs"].reshape((T * B,) + batch["obs"].shape[2:])
    dist, values = module.dist_values(params, obs)
    dist = jax.tree.map(
        lambda a: a.reshape((T, B) + a.shape[1:]), dist)
    return dist, values.reshape(T, B)


class ImpalaLearner:
    """Policy-gradient learner with a V-trace-corrected baseline.
    Modules come from the catalog factory, so IMPALA trains MLP, CNN
    (pixel envs) and LSTM (use_lstm) policies with one loss."""

    def __init__(self, config: Dict, obs_dim: int, action_dim: int):
        import jax
        import optax
        from ray_tpu.rl.rl_module import make_rl_module

        self.cfg = config
        obs_shape = tuple(config.get("obs_shape") or (obs_dim,))
        action_spec = (config.get("action_spec")
                       or {"type": "discrete", "n": action_dim})
        self.module = make_rl_module(
            obs_shape, action_spec, config.get("hidden_sizes", (64, 64)),
            seed=config.get("seed", 0),
            use_lstm=config.get("use_lstm", False))
        # adam rather than the paper's rmsprop(eps=0.1): at small-batch
        # scale the 0.1 epsilon floors the preconditioner and crushes the
        # effective step (no learning on CartPole-size nets); adam's 1e-8
        # epsilon keeps step sizes honest at every scale
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(config.get("grad_clip", 40.0)),
            optax.adam(config["lr"]))
        self.opt_state = self.optimizer.init(self.module.params)
        loss_fn = self._make_loss()

        @jax.jit
        def update(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, new_opt = self.optimizer.update(grads, opt_state,
                                                     params)
            return optax.apply_updates(params, updates), new_opt, loss, aux

        self._update = update

    def _make_loss(self):
        import jax
        import jax.numpy as jnp
        from ray_tpu.rl.vtrace import vtrace
        gamma = self.cfg["gamma"]
        vf_coeff = self.cfg["vf_loss_coeff"]
        ent_coeff = self.cfg["entropy_coeff"]
        module = self.module

        def loss_fn(params, batch):
            dist, values = _seq_forward(module, params, batch)
            tgt_logp, entropy = module.seq_logp_entropy(
                dist, batch["actions"])
            discounts = gamma * (1.0 - batch["dones"])
            vt = vtrace(batch["behavior_logp"], tgt_logp,
                        batch["rewards"], discounts, values,
                        batch["bootstrap_value"])
            pg_loss = -(tgt_logp * vt.pg_advantages).mean()
            vf_loss = ((values - vt.vs) ** 2).mean()
            entropy = entropy.mean()
            total = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
            return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": entropy}

        return loss_fn

    def update_from_trajectory(self, traj: Dict[str, np.ndarray]) -> Dict:
        import jax.numpy as jnp
        batch = {k: jnp.asarray(v) for k, v in traj.items()
                 if k != "bootstrap_obs"}
        # num_epochs passes per fragment: V-trace recomputes the
        # target-policy term each pass, so the rho/c clips absorb the
        # growing off-policyness — sample efficiency without aggregator
        # replay (reference IMPALA replays fragments via its aggregator
        # buffer for the same reason)
        for _ in range(max(1, int(self.cfg.get("num_epochs", 1)))):
            self.module.params, self.opt_state, loss, aux = self._update(
                self.module.params, self.opt_state, batch)
        out = {k: float(v) for k, v in aux.items()}
        out["total_loss"] = float(loss)
        return out

    def get_weights(self):
        return self.module.get_weights()


class IMPALA(Algorithm):
    """Async training_step: learn on fragments as they complete, re-issue
    sampling immediately, sync weights after every learner step."""

    supports_recurrence = True

    def __init__(self, config: AlgorithmConfig):
        self._inflight: Dict = {}
        super().__init__(config)

    def _build_learner(self, cfg_dict, obs_dim, action_dim):
        self.learner = ImpalaLearner(cfg_dict, obs_dim, action_dim)

    def _sync_weights(self):
        import ray_tpu
        ref = ray_tpu.put(self.learner.get_weights())
        self.env_runners.foreach("set_weights", ref, timeout=300)

    def training_step(self) -> Dict:
        import ray_tpu
        t0 = time.perf_counter()
        if not self._inflight:
            for r in self.env_runners:
                self._inflight[r.sample_trajectory.remote()] = r

        n_updates = 0
        steps = 0
        metrics: Dict = {}
        # learn on a full round of fragments, keeping the pipe full
        target = len(self.env_runners)
        while n_updates < target:
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=600)
            ref = ready[0]
            runner = self._inflight.pop(ref)
            try:
                traj = ray_tpu.get(ref)
            except ray_tpu.ActorDiedError:
                # dead runner: replace in-slot (on_restart pushes current
                # weights) and put the replacement to work; this round
                # learns one fewer fragment. replace() returns None when
                # a foreach (e.g. weight sync) already replaced it — the
                # replacement is then the idle runner with no in-flight
                # work, so schedule that one.
                fresh = self.env_runners.replace(runner)
                if fresh is None:
                    busy = {id(r) for r in self._inflight.values()}
                    idle = [r for r in self.env_runners
                            if id(r) not in busy]
                    fresh = idle[0] if idle else None
                if fresh is not None:
                    self._inflight[fresh.sample_trajectory.remote()] = fresh
                n_updates += 1
                continue
            # re-issue before learning: sampling overlaps the update
            self._inflight[runner.sample_trajectory.remote()] = runner
            metrics = self.learner.update_from_trajectory(traj)
            # rewards is [T, N] for every action space; actions would
            # over-count by action_dim on Box envs
            steps += traj["rewards"].size
            n_updates += 1
        self._sync_weights()
        wall = time.perf_counter() - t0
        runner_metrics = self.env_runners.foreach("get_metrics",
                                                  timeout=120)
        returns = [m["episode_return_mean"] for m in runner_metrics
                   if m["episode_return_mean"] is not None]
        return {
            "episode_return_mean":
                float(np.mean(returns)) if returns else None,
            "num_env_steps_sampled": steps,
            "env_steps_per_s": steps / max(1e-9, wall),
            **metrics,
        }

    def get_weights(self):
        return self.learner.get_weights()
