"""IMPALA: async actor-learner RL with V-trace off-policy correction
(reference: rllib/algorithms/impala/impala.py — async EnvRunner sampling
with aggregator-style batching :617, vtrace loss; re-designed: the learner
update is one jitted function and asynchrony comes from `ray_tpu.wait`
over in-flight sample futures rather than dedicated aggregator actors —
re-issue a runner's next fragment before learning on its last one)."""

from __future__ import annotations

import time
from typing import Dict, List

import numpy as np

from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig


class ImpalaLearner:
    """Policy-gradient learner with a V-trace-corrected baseline."""

    def __init__(self, config: Dict, obs_dim: int, action_dim: int):
        import jax
        import jax.numpy as jnp
        import optax
        from ray_tpu.rl.rl_module import DiscreteRLModule
        from ray_tpu.rl.vtrace import vtrace

        self.cfg = config
        self.module = DiscreteRLModule(obs_dim, action_dim,
                                       config.get("hidden_sizes", (64, 64)),
                                       seed=config.get("seed", 0))
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(config.get("grad_clip", 40.0)),
            optax.rmsprop(config["lr"], decay=0.99, eps=0.1))
        self.opt_state = self.optimizer.init(self.module.params)
        gamma = config["gamma"]
        vf_coeff = config["vf_loss_coeff"]
        ent_coeff = config["entropy_coeff"]
        net = self.module.net

        def loss_fn(params, batch):
            T, B = batch["actions"].shape
            obs = batch["obs"].reshape((T * B,) + batch["obs"].shape[2:])
            logits, values = net.apply({"params": params}, obs)
            logits = logits.reshape(T, B, -1)
            values = values.reshape(T, B)
            logp_all = jax.nn.log_softmax(logits)
            tgt_logp = jnp.take_along_axis(
                logp_all, batch["actions"][..., None], axis=-1)[..., 0]
            discounts = gamma * (1.0 - batch["dones"])
            vt = vtrace(batch["behavior_logp"], tgt_logp,
                        batch["rewards"], discounts, values,
                        batch["bootstrap_value"])
            pg_loss = -(tgt_logp * vt.pg_advantages).mean()
            vf_loss = ((values - vt.vs) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
            return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": entropy}

        @jax.jit
        def update(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, new_opt = self.optimizer.update(grads, opt_state,
                                                     params)
            return optax.apply_updates(params, updates), new_opt, loss, aux

        self._update = update

    def update_from_trajectory(self, traj: Dict[str, np.ndarray]) -> Dict:
        import jax.numpy as jnp
        batch = {k: jnp.asarray(v) for k, v in traj.items()
                 if k != "bootstrap_obs"}
        self.module.params, self.opt_state, loss, aux = self._update(
            self.module.params, self.opt_state, batch)
        out = {k: float(v) for k, v in aux.items()}
        out["total_loss"] = float(loss)
        return out

    def get_weights(self):
        return self.module.get_weights()


class IMPALA(Algorithm):
    """Async training_step: learn on fragments as they complete, re-issue
    sampling immediately, sync weights after every learner step."""

    def __init__(self, config: AlgorithmConfig):
        self._inflight: Dict = {}
        super().__init__(config)

    def _build_learner(self, cfg_dict, obs_dim, action_dim):
        self.learner = ImpalaLearner(cfg_dict, obs_dim, action_dim)

    def _sync_weights(self):
        import ray_tpu
        ref = ray_tpu.put(self.learner.get_weights())
        ray_tpu.get([r.set_weights.remote(ref) for r in self.env_runners],
                    timeout=300)

    def training_step(self) -> Dict:
        import ray_tpu
        t0 = time.perf_counter()
        if not self._inflight:
            for r in self.env_runners:
                self._inflight[r.sample_trajectory.remote()] = r

        n_updates = 0
        steps = 0
        metrics: Dict = {}
        # learn on a full round of fragments, keeping the pipe full
        target = len(self.env_runners)
        while n_updates < target:
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=600)
            ref = ready[0]
            runner = self._inflight.pop(ref)
            traj = ray_tpu.get(ref)
            # re-issue before learning: sampling overlaps the update
            self._inflight[runner.sample_trajectory.remote()] = runner
            metrics = self.learner.update_from_trajectory(traj)
            steps += traj["actions"].size
            n_updates += 1
        self._sync_weights()
        wall = time.perf_counter() - t0
        runner_metrics = ray_tpu.get(
            [r.get_metrics.remote() for r in self.env_runners], timeout=120)
        returns = [m["episode_return_mean"] for m in runner_metrics
                   if m["episode_return_mean"] is not None]
        return {
            "episode_return_mean":
                float(np.mean(returns)) if returns else None,
            "num_env_steps_sampled": steps,
            "env_steps_per_s": steps / max(1e-9, wall),
            **metrics,
        }

    def get_weights(self):
        return self.learner.get_weights()
