"""Algorithm: the RL training driver (reference:
rllib/algorithms/algorithm.py:228 — step() :881; PPO training_step
rllib/algorithms/ppo/ppo.py:403: parallel EnvRunner.sample() →
LearnerGroup.update → weight sync → metrics)."""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional

import numpy as np

from ray_tpu.rl.config import AlgorithmConfig


class Algorithm:
    # recurrent (use_lstm) policies need time-major trajectory learning;
    # only the V-trace family implements it (IMPALA/APPO set this True)
    supports_recurrence = False

    def __init__(self, config: AlgorithmConfig):
        import gymnasium as gym
        import ray_tpu
        from ray_tpu.rl.env_runner import EnvRunner
        from ray_tpu.rl.learner import LearnerGroup

        from ray_tpu.rl import envs as _envs
        from ray_tpu.rl.rl_module import action_spec_of
        _envs.register_envs()
        if getattr(config, "use_lstm", False) \
                and not self.supports_recurrence:
            raise ValueError(
                f"use_lstm is not supported by "
                f"{type(self).__name__}; use IMPALA or APPO (their "
                f"time-major V-trace losses carry the LSTM state)")
        self.config = config
        probe = gym.make(config.env, **config.env_config)
        from ray_tpu.rl.connectors import pipeline_output_shape
        # the learner's module sees CONNECTED observations
        obs_shape = pipeline_output_shape(config.connectors or (),
                                          probe.observation_space.shape)
        obs_dim = int(np.prod(obs_shape))
        spec = action_spec_of(probe.action_space)
        action_dim = spec.get("n") or spec["dim"]
        probe.close()

        cfg_dict = dataclasses.asdict(config)
        cfg_dict["obs_shape"] = list(obs_shape)
        cfg_dict["action_spec"] = spec
        runner_cls = ray_tpu.remote(EnvRunner)
        from ray_tpu.rl.actor_manager import FaultTolerantRunnerSet
        self.env_runners = FaultTolerantRunnerSet(
            lambda i: runner_cls.remote({**cfg_dict, "runner_index": i}),
            config.num_env_runners,
            max_restarts=config.max_env_runner_restarts,
            restart_enabled=config.restart_failed_env_runners)
        self._build_learner(cfg_dict, obs_dim, action_dim)
        # restarted runners immediately receive the CURRENT weights (a
        # fresh actor would otherwise sample one round at init weights);
        # the re-push rides the broadcast plane — the blob is already on
        # the restart node's arena, so set_weights resolves locally
        self.env_runners.set_on_restart(
            lambda r: ray_tpu.get(
                r.set_weights.remote(
                    self.env_runners.broadcast_weights(self.get_weights())),
                timeout=300))
        self.iteration = 0
        self._sync_weights()

    def _build_learner(self, cfg_dict, obs_dim, action_dim):
        from ray_tpu.rl.learner import LearnerGroup
        self.learner_group = LearnerGroup(cfg_dict, obs_dim, action_dim)

    def _sync_weights(self):
        # one broadcast instead of num_env_runners point-to-point pulls:
        # every runner's set_weights arg is already in its node's arena
        weights_ref = self.env_runners.broadcast_weights(
            self.learner_group.get_weights())
        self.env_runners.foreach("set_weights", weights_ref, timeout=300)

    def training_step(self) -> Dict:
        t0 = time.perf_counter()
        # dead runners are replaced in-slot; the round proceeds on the
        # survivors' batches (reference: restart_failed_env_runners)
        batches = self.env_runners.foreach("sample", timeout=600)
        sample_time = time.perf_counter() - t0
        batch = {k: np.concatenate([b[k] for b in batches])
                 for k in batches[0]}
        t1 = time.perf_counter()
        learn_metrics = self.learner_group.update_from_batch(batch)
        learn_time = time.perf_counter() - t1
        self._sync_weights()
        runner_metrics = self.env_runners.foreach("get_metrics",
                                                  timeout=120)
        returns = [m["episode_return_mean"] for m in runner_metrics
                   if m["episode_return_mean"] is not None]
        steps = len(batch["obs"])
        return {
            "episode_return_mean":
                float(np.mean(returns)) if returns else None,
            "num_env_steps_sampled": steps,
            "env_steps_per_s": steps / max(1e-9, sample_time),
            "sample_time_s": sample_time,
            "learn_time_s": learn_time,
            **learn_metrics,
        }

    def train(self) -> Dict:
        self.iteration += 1
        out = self.training_step()
        out["training_iteration"] = self.iteration
        return out

    def get_weights(self):
        return self.learner_group.get_weights()

    def stop(self):
        import ray_tpu
        for r in self.env_runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self.env_runners = []


class PPO(Algorithm):
    """Clipped-surrogate PPO with GAE (the loss lives in JaxLearner)."""
