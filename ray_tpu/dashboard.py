"""Dashboard: REST API over cluster state + jobs (reference:
python/ray/dashboard/head.py — aiohttp REST; the web UI is not replicated,
the API surface is). Runs as an actor on the cluster."""

from __future__ import annotations

import asyncio
import json
from typing import Dict, Optional

DASHBOARD_NAME = "_DASHBOARD"


class DashboardServer:
    def __init__(self, port: int = 8265):
        self.port = port
        self._ready = False
        from ray_tpu._private.worker import global_worker
        asyncio.run_coroutine_threadsafe(
            self._start(), global_worker.core.loop).result(timeout=30)

    async def _start(self):
        from aiohttp import web

        app = web.Application()
        r = app.router
        r.add_get("/api/cluster_status", self._cluster_status)
        r.add_get("/api/nodes", self._nodes)
        r.add_get("/api/actors", self._actors)
        r.add_get("/api/tasks", self._tasks)
        r.add_get("/api/timeline", self._timeline)
        r.add_get("/api/memory", self._memory)
        r.add_get("/api/fleet", self._fleet)
        r.add_get("/api/runtime_events", self._runtime_events)
        r.add_get("/api/placement_groups", self._pgs)
        r.add_get("/api/jobs", self._jobs)
        r.add_post("/api/jobs", self._submit_job)
        r.add_get("/api/jobs/{job_id}", self._job_status)
        r.add_get("/api/jobs/{job_id}/logs", self._job_logs)
        r.add_post("/api/jobs/{job_id}/stop", self._job_stop)
        r.add_get("/api/version", self._version)
        r.add_get("/api/metrics/query", self._metrics_query)
        r.add_get("/api/metrics/series", self._metrics_series)
        r.add_get("/metrics", self._metrics)
        r.add_get("/healthz", self._healthz)
        r.add_get("/", self._index)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "0.0.0.0", self.port)
        await site.start()
        self._ready = True

    async def _index(self, request):
        """Single-page UI over the JSON API (reference: the dashboard
        frontend, python/ray/dashboard/ — a full React app there; a
        dependency-free live table view here)."""
        from aiohttp import web
        return web.Response(text=_INDEX_HTML, content_type="text/html")

    def ready(self):
        return self._ready

    async def _in_thread(self, fn, *args):
        return await asyncio.get_event_loop().run_in_executor(
            None, fn, *args)

    async def _healthz(self, request):
        from aiohttp import web
        return web.Response(text="ok")

    async def _metrics(self, request):
        """Prometheus scrape endpoint aggregating every process's pushed
        metrics (reference: prometheus_exporter.py on the metrics agent)."""
        from aiohttp import web

        from ray_tpu.util.metrics import render_prometheus

        def fetch():
            import ray_tpu
            return ray_tpu._get_worker().gcs_call("get_metrics")
        all_metrics = await self._in_thread(fetch)
        return web.Response(text=render_prometheus(all_metrics),
                            content_type="text/plain")

    async def _metrics_query(self, request):
        """Windowed time-series query: ?name=serve_llm_ttft_ms&window=30
        &agg=p95[&threshold=...][&tags={"k":"v"}] — the HTTP face of the
        GCS query_metrics call (util/state.query_metrics)."""
        from aiohttp import web
        from ray_tpu.util import state
        name = request.query.get("name")
        if not name:
            return web.json_response({"error": "name is required"},
                                     status=400)
        try:
            window = float(request.query.get("window", 60.0))
            agg = request.query.get("agg", "avg")
            threshold = request.query.get("threshold")
            threshold = float(threshold) if threshold is not None else None
            tags = request.query.get("tags")
            tags = json.loads(tags) if tags else None
        except (ValueError, json.JSONDecodeError) as e:
            return web.json_response({"error": str(e)}, status=400)
        out = await self._in_thread(
            lambda: state.query_metrics(name, window=window, agg=agg,
                                        tags=tags, threshold=threshold))
        return web.json_response(out)

    async def _metrics_series(self, request):
        from aiohttp import web
        from ray_tpu.util import state
        return web.json_response(
            await self._in_thread(state.list_metric_series))

    async def _version(self, request):
        from aiohttp import web
        import ray_tpu
        return web.json_response({"version": ray_tpu.__version__})

    async def _cluster_status(self, request):
        from aiohttp import web
        from ray_tpu.util import state
        return web.json_response(
            await self._in_thread(state.cluster_summary))

    async def _nodes(self, request):
        from aiohttp import web
        from ray_tpu.util import state
        return web.json_response(await self._in_thread(state.list_nodes))

    async def _actors(self, request):
        from aiohttp import web
        from ray_tpu.util import state
        return web.json_response(await self._in_thread(state.list_actors))

    async def _tasks(self, request):
        from aiohttp import web
        from ray_tpu.util import state
        return web.json_response(await self._in_thread(state.list_tasks))

    async def _timeline(self, request):
        """Unified chrome-trace timeline (tasks + flight-recorder
        runtime events as per-subsystem tracks): save the body to a
        file and open it in chrome://tracing or Perfetto."""
        from aiohttp import web

        def fetch():
            import ray_tpu
            return ray_tpu.timeline()
        return web.json_response(await self._in_thread(fetch))

    async def _memory(self, request):
        """Cluster memory observability: object rows (arena truth joined
        with object-ledger provenance — owner, size, stripe/span
        placement, pins, age, leak flag) plus per-node occupancy/
        fragmentation and ledger totals. ?limit=N bounds the object
        list; ?leaked=1 restricts it to leak-detector hits."""
        from aiohttp import web
        from ray_tpu.util import state
        try:
            limit = int(request.query.get("limit", 1000))
        except ValueError:
            return web.json_response({"error": "bad limit"}, status=400)
        leaked_only = request.query.get("leaked") in ("1", "true", "yes")

        def fetch():
            rows = state.list_objects(limit=limit)
            if leaked_only:
                rows = [r for r in rows if r.get("leaked")]
            return {"objects": rows, "summary": state.memory_summary()}
        return web.json_response(await self._in_thread(fetch))

    async def _fleet(self, request):
        """Fleet-plane view (serve/fleet.py): scale-to-zero state per
        deployment, shell-pool occupancy, cold-start percentiles, and
        configured tenant quotas. 404s when serve isn't running."""
        from aiohttp import web

        def fetch():
            import ray_tpu
            from ray_tpu import serve
            # probe, don't create: fleet_status() via _get_controller
            # would START a serve controller on a serve-less cluster
            ray_tpu.get_actor("SERVE_CONTROLLER", namespace="serve")
            out = serve.fleet_status()
            try:
                quotas = serve.get_tenant_quotas()
                if quotas:
                    out["tenant_quotas"] = quotas
            except Exception:
                pass
            return out
        try:
            return web.json_response(await self._in_thread(fetch))
        except Exception as e:
            return web.json_response(
                {"error": f"{type(e).__name__}: {e}"}, status=404)

    async def _runtime_events(self, request):
        """Raw flight-recorder rows; ?category=engine|store|data|serve
        filters by subsystem."""
        from aiohttp import web
        from ray_tpu.util import state
        category = request.query.get("category") or None
        rows = await self._in_thread(
            lambda: state.list_runtime_events(category=category))
        return web.json_response(rows)

    async def _pgs(self, request):
        from aiohttp import web
        from ray_tpu.util import state
        return web.json_response(
            await self._in_thread(state.list_placement_groups))

    def _client(self):
        from ray_tpu.job_submission import JobSubmissionClient
        return JobSubmissionClient()

    async def _jobs(self, request):
        from aiohttp import web
        return web.json_response(
            await self._in_thread(lambda: self._client().list_jobs()))

    async def _submit_job(self, request):
        from aiohttp import web
        body = await request.json()
        job_id = await self._in_thread(
            lambda: self._client().submit_job(
                entrypoint=body["entrypoint"],
                runtime_env=body.get("runtime_env"),
                metadata=body.get("metadata")))
        return web.json_response({"job_id": job_id})

    async def _job_status(self, request):
        from aiohttp import web
        job_id = request.match_info["job_id"]
        info = await self._in_thread(
            lambda: self._client().get_job_info(job_id))
        if info is None:
            return web.Response(status=404)
        return web.json_response(info)

    async def _job_logs(self, request):
        from aiohttp import web
        job_id = request.match_info["job_id"]
        logs = await self._in_thread(
            lambda: self._client().get_job_logs(job_id))
        return web.json_response({"logs": logs})

    async def _job_stop(self, request):
        from aiohttp import web
        job_id = request.match_info["job_id"]
        ok = await self._in_thread(
            lambda: self._client().stop_job(job_id))
        return web.json_response({"stopped": ok})


def start_dashboard(port: int = 8265):
    """Start (or find) the dashboard actor; returns its handle."""
    import ray_tpu
    try:
        return ray_tpu.get_actor(DASHBOARD_NAME, namespace="_internal")
    except ValueError:
        cls = ray_tpu.remote(DashboardServer)
        h = cls.options(name=DASHBOARD_NAME, namespace="_internal",
                        lifetime="detached", max_concurrency=16,
                        num_cpus=0.1).remote(port)
        ray_tpu.get(h.ready.remote(), timeout=60)
        return h


_INDEX_HTML = """<!doctype html>
<html><head><title>ray_tpu dashboard</title><style>
body{font-family:system-ui,sans-serif;margin:1.5rem;background:#fafafa}
h1{font-size:1.2rem} h2{font-size:1rem;margin:1.2rem 0 .4rem}
table{border-collapse:collapse;width:100%;background:#fff;font-size:.85rem}
th,td{border:1px solid #ddd;padding:.3rem .5rem;text-align:left}
th{background:#f0f0f0} .ALIVE{color:#0a7d34} .DEAD,.FAILED{color:#c0322f}
#err{color:#c0322f}
</style></head><body>
<h1>ray_tpu dashboard</h1><div id="err"></div>
<h2>Cluster</h2><div id="cluster"></div>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Jobs</h2><table id="jobs"></table>
<h2>Recent tasks</h2><table id="tasks"></table>
<script>
function esc(s){return s.replace(/[&<>"']/g,
 m=>({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[m]))}
function cell(v){if(v===null||v===undefined)return"";
 if(typeof v==="object")return JSON.stringify(v);return String(v)}
function render(id, rows, cols){const t=document.getElementById(id);
 if(!rows||!rows.length){t.innerHTML="<tr><td>none</td></tr>";return}
 cols=cols||Object.keys(rows[0]);
 t.innerHTML="<tr>"+cols.map(c=>"<th>"+esc(c)+"</th>").join("")+"</tr>"+
  rows.map(r=>"<tr>"+cols.map(c=>{const v=cell(r[c]);
   let cls="";
   if(c==="state"||c==="status"){cls=" class='"+esc(v).replace(/[^A-Za-z]/g,"")+"'"}
   if(c==="alive"){cls=v==="true"?" class='ALIVE'":" class='DEAD'"}
   return "<td"+cls+">"+esc(v)+"</td>"}).join("")+"</tr>").join("")}
async function refresh(){try{
 const [cl,no,ac,jo,ta]=await Promise.all(
  ["cluster_status","nodes","actors","jobs","tasks"].map(
   p=>fetch("/api/"+p).then(r=>r.json())));
 document.getElementById("cluster").textContent=JSON.stringify(cl);
 render("nodes",no,["node_id","alive","node_ip","total","available"]);
 render("actors",ac,["actor_id","state","name","node_id","num_restarts"]);
 render("jobs",jo);
 render("tasks",(ta||[]).slice(0,50),
        ["task_id","name","state","type","node_id"]);
 document.getElementById("err").textContent="";
}catch(e){document.getElementById("err").textContent="refresh failed: "+e}}
refresh();setInterval(refresh,2000);
</script></body></html>"""
