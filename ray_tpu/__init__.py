"""ray_tpu — a TPU-native distributed runtime with Ray's capabilities.

Public core API (reference: python/ray/_private/worker.py — ray.init :1260,
get/put/wait/remote): tasks, actors, objects over a C+±backed shared-memory
object store, an asyncio control plane, and a JAX/XLA-native device layer.
"""

from __future__ import annotations

import atexit
import threading
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu._private.generator import ObjectRefGenerator
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.serialization import (ActorDiedError, ObjectLostError,
                                            TaskCancelledError, TaskError,
                                            WorkerCrashedError)
from ray_tpu.actor import ActorClass, ActorHandle, method
from ray_tpu.remote_function import RemoteFunction

__version__ = "0.2.0"

_ctx_lock = threading.RLock()
_context: Optional["_Context"] = None


class _Context:
    def __init__(self, worker, node=None, owns_node=False, job_id=0):
        self.worker = worker
        self.node = node
        self.owns_node = owns_node
        self.job_id = job_id


def _get_worker():
    ctx = _context
    if ctx is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return ctx.worker


def is_initialized() -> bool:
    return _context is not None


def _set_connected_from_worker(core):
    """Called by worker_main: tasks executing here see a connected API."""
    global _context
    from ray_tpu._private import worker as worker_mod
    with _ctx_lock:
        if _context is None:
            _context = _Context(worker_mod.global_worker, node=None,
                                owns_node=False, job_id=core.job_id)



def _apply_system_config(values: Dict[str, Any]) -> None:
    """Validate + coerce every entry first (fail fast, no partial
    application), then set cfg and export env overrides so spawned GCS /
    node-manager processes resolve the same values (the GCS then
    re-propagates its snapshot to every joining node). The exported keys
    are recorded so shutdown() can remove them."""
    import os
    from ray_tpu._private.config import cfg, flags
    table = flags()
    coerced = {}
    for k, v in values.items():
        flag = table.get(k)
        if flag is None:
            raise KeyError(f"unknown system config flag {k!r}")
        try:
            coerced[k] = flag.parse(str(v))
        except (TypeError, ValueError):
            raise ValueError(
                f"system config flag {k!r}={v!r} is not a valid "
                f"{flag.type.__name__}")
    for k, v in coerced.items():
        cfg.set(k, v)
        os.environ["RAY_TPU_" + k.upper()] = str(v)
        _exported_config_env.append(("RAY_TPU_" + k.upper(), k))


_exported_config_env: List[tuple] = []

def init(address: Optional[str] = None, *,
         num_cpus: Optional[float] = None,
         resources: Optional[Dict[str, float]] = None,
         object_store_memory: Optional[int] = None,
         namespace: str = "default",
         labels: Optional[Dict[str, str]] = None,
         ignore_reinit_error: bool = False,
         _node_address: Optional[str] = None,
         _store_path: Optional[str] = None,
         _node_id: Optional[str] = None,
         _system_config: Optional[Dict[str, Any]] = None):
    """Connect to (or start) a cluster. With no address, starts a local
    head: GCS + node manager subprocesses (reference: ray.init at
    python/ray/_private/worker.py:1260)."""
    global _context
    from ray_tpu._private import node as node_mod
    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.worker import Worker

    import os
    if address is None:
        address = os.environ.get("RAY_TPU_ADDRESS")
    if _system_config and address is not None:
        raise ValueError(
            "_system_config is only valid when starting a new head; "
            "when joining an existing cluster the head's config wins")
    with _ctx_lock:
        if _context is not None:
            if ignore_reinit_error:
                return _context
            raise RuntimeError("ray_tpu.init() already called "
                               "(use ignore_reinit_error=True)")
        owns_node = False
        node = None
        if _system_config:
            _apply_system_config(_system_config)
        try:
            return _init_locked(address, num_cpus, resources,
                                object_store_memory, namespace, labels,
                                _node_address, _store_path, _node_id,
                                node_mod, worker_mod, Worker)
        except BaseException:
            if _system_config:
                _drain_config_exports()
            raise


def _drain_config_exports() -> None:
    import os
    from ray_tpu._private.config import cfg as _cfg
    for env_key, flag_name in _exported_config_env:
        os.environ.pop(env_key, None)
        _cfg.reset(flag_name)
    _exported_config_env.clear()


def _init_locked(address, num_cpus, resources, object_store_memory,
                 namespace, labels, _node_address, _store_path, _node_id,
                 node_mod, worker_mod, Worker):
        global _context
        owns_node = False
        node = None
        if address is None:
            node = node_mod.start_head(
                num_cpus=num_cpus, resources=resources,
                object_store_memory=object_store_memory, labels=labels)
            owns_node = True
            gcs_address = node.gcs_address
            node_address = node.node_address
            store_path = node.store_path
            node_id = node.node_id
        else:
            gcs_address = address
            node_address = _node_address
            store_path = _store_path
            node_id = _node_id
            if node_address is None:
                # find (or start) a node manager on this host via GCS
                probe = Worker.start(mode="driver", gcs_address=gcs_address,
                                     node_address="", store_path="",
                                     node_id="probe", namespace=namespace)
                try:
                    nodes_list = probe.gcs_call("get_all_nodes")
                finally:
                    probe.stop()
                from ray_tpu._private.rpc import node_ip_address
                my_ip = node_ip_address()
                local = [n for n in nodes_list
                         if n["alive"] and n["node_ip"] in (my_ip, "127.0.0.1")]
                if local:
                    node_address = local[0]["address"]
                    store_path = local[0]["object_store_address"]
                    node_id = local[0]["node_id"]
                else:
                    ln = node_mod.start_node(gcs_address, num_cpus=num_cpus,
                                             resources=resources,
                                             object_store_memory=object_store_memory)
                    node = ln
                    owns_node = True
                    node_address = ln.node_address
                    store_path = ln.store_path
                    node_id = ln.node_id

        worker = Worker.start(mode="driver", gcs_address=gcs_address,
                              node_address=node_address,
                              store_path=store_path, node_id=node_id,
                              namespace=namespace)
        job_id = worker.gcs_call("register_job",
                                 driver_address=worker.core.address,
                                 metadata={})
        worker.core.job_id = job_id
        worker_mod.global_worker = worker
        _context = _Context(worker, node, owns_node, job_id)
        # a prior shutdown() in this process retired the metrics pusher;
        # metrics registered back then must resume pushing now
        from ray_tpu.util import metrics as _metrics
        _metrics.resume_pusher()
        atexit.register(shutdown)
        return _context


def shutdown():
    global _context
    with _ctx_lock:
        ctx = _context
        if ctx is None:
            return
        _context = None
        try:
            ctx.worker.gcs_call("finish_job", job_id=ctx.job_id)
        except Exception:
            pass
        ctx.worker.stop()
        if ctx.owns_node and ctx.node is not None:
            ctx.node.kill()
        from ray_tpu._private import worker as worker_mod
        worker_mod.global_worker = None
        # retire the registry pusher: without a worker it would spin on
        # is_initialized() forever (resume_pusher on the next init)
        from ray_tpu.util import metrics as _metrics
        _metrics.stop_pusher()
        # undo _system_config exports so a later init (or unrelated
        # tooling spawned from this process) doesn't inherit stale values
        _drain_config_exports()


def remote(*args, **kwargs):
    """Decorator making a function a remote task or a class an actor class
    (reference: python/ray/_private/worker.py remote decorator)."""
    def make(obj):
        if isinstance(obj, type):
            return ActorClass(obj, kwargs)
        return RemoteFunction(obj, kwargs)

    if len(args) == 1 and callable(args[0]) and not kwargs:
        return make(args[0])
    return make


def _client():
    from ray_tpu.client import current_client
    return current_client()


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None):
    cc = _client()
    if cc is not None:
        return cc.get(refs, timeout=timeout)
    return _get_worker().get(refs, timeout=timeout)


def put(value: Any) -> ObjectRef:
    cc = _client()
    if cc is not None:
        return cc.put(value)
    return _get_worker().put(value)


def broadcast_weights(weights: Any, node_ids: Optional[Sequence[str]] = None,
                      *, max_retries: int = 2) -> ObjectRef:
    """Distribute one (multi-GB) weight blob to every node, fast.

    One source ``put`` into a pinned arena span (objects larger than one
    arena stripe land in a spanning allocation transparently), then a
    log-depth binomial relay tree fans the sealed bytes out across the
    cluster over the striped raw-socket data plane — senders stream
    pinned memoryviews, receivers ``recv_into`` their own spanning
    allocations, zero staging copies end to end. If a relay node dies
    mid-subtree the root retries through the surviving holders.

    ``weights`` may be any serializable value (a params pytree, a state
    dict, raw bytes) or an existing :class:`ObjectRef`. Returns the ref;
    consumers on every node ``ray_tpu.get`` it zero-copy from their
    local arena. ``node_ids=None`` targets every node in the cluster.
    """
    w = _get_worker()
    ref = weights if isinstance(weights, ObjectRef) else w.put(weights)
    w.broadcast_weights(ref, list(node_ids) if node_ids is not None
                        else None, max_retries=max_retries)
    return ref


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None):
    cc = _client()
    if cc is not None:
        return cc.wait(list(refs), num_returns=num_returns, timeout=timeout)
    return _get_worker().wait(list(refs), num_returns=num_returns,
                              timeout=timeout)


def kill(actor: ActorHandle, *, no_restart: bool = True):
    cc = _client()
    if cc is not None:
        return cc.kill(actor)
    _get_worker().kill_actor(actor._id, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False):
    """Cancel a pending/queued task (reference: ray.cancel — queued tasks
    drop with TaskCancelledError; force also kills a running worker)."""
    return _get_worker().cancel(ref, force=force)


def timeline(filename: Optional[str] = None):
    """Export the unified timeline — task executions PLUS the flight
    recorder's runtime events (engine steps, spills, shuffle windows,
    serve phases as per-subsystem tracks) PLUS gauge time-series as
    counter tracks (slot occupancy, queue depth) — as a chrome://tracing
    JSON (reference: `ray timeline`, python/ray/_private/state.py chrome
    trace export)."""
    import json

    from ray_tpu._private import events as _events
    from ray_tpu.util.metrics import push_once as _push_metrics
    from ray_tpu.util.tracing import task_events_to_chrome
    _events.flush()     # this process's buffered spans make the export
    _push_metrics()     # ...and its freshest gauge samples
    rows = _get_worker().gcs_call("list_task_events", limit=20000)
    try:
        series = _get_worker().gcs_call("dump_metric_series",
                                        kinds=["gauge"])
    except Exception:
        series = None   # older GCS without the time-series plane
    events = task_events_to_chrome(rows, gauge_series=series)
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
        return filename
    return events


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    cc = _client()
    if cc is not None:
        return cc.get_actor(name, namespace)
    info = _get_worker().gcs_call("get_named_actor", name=name,
                                  namespace=namespace)
    if info is None:
        raise ValueError(f"no actor named {name!r} in namespace {namespace!r}")
    return ActorHandle(info["actor_id"], info.get("method_names") or [], {})


def nodes() -> List[Dict]:
    return _get_worker().gcs_call("get_all_nodes")


def cluster_resources() -> Dict[str, float]:
    total: Dict[str, float] = {}
    for n in nodes():
        if n["alive"]:
            for k, v in n["total"].items():
                total[k] = total.get(k, 0.0) + v
    return total


def available_resources() -> Dict[str, float]:
    avail: Dict[str, float] = {}
    for n in nodes():
        if n["alive"]:
            for k, v in n["available"].items():
                avail[k] = avail.get(k, 0.0) + v
    return avail


def get_gcs_address() -> str:
    ctx = _context
    if ctx is None:
        raise RuntimeError("not initialized")
    return ctx.worker.core.gcs_address


def get_runtime_context():
    ctx = _context
    w = _get_worker()
    return {"job_id": w.core.job_id, "node_id": w.core.node_id,
            "worker_id": w.core.worker_id,
            "actor_id": w.core.actor_id,
            "gcs_address": w.core.gcs_address}


import ray_tpu.util as util  # noqa: E402  (public subpackage)

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "get", "put",
    "broadcast_weights", "wait",
    "kill", "cancel", "timeline", "get_actor", "nodes", "cluster_resources",
    "available_resources", "ObjectRef", "ObjectRefGenerator",
    "ActorHandle", "ActorClass",
    "RemoteFunction", "TaskError", "ActorDiedError", "ObjectLostError",
    "WorkerCrashedError", "TaskCancelledError", "util", "method",
    "get_runtime_context", "get_gcs_address",
]
