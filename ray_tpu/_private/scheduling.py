"""Cluster scheduling policies over the node resource view.

Re-design of the reference's two-level scheduler policy layer
(reference: src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.h:50,
scorer.cc, scheduling_policy.h). Same observable behavior — hybrid
pack-then-spread with a utilization threshold and top-k randomization,
plus SPREAD / NODE_AFFINITY / placement-group policies — implemented as
pure functions over plain dicts so the GCS (actors, placement groups) and
node managers (task spillback) share one code path and the logic is unit
testable with fake node maps, like the reference's scheduler tests.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Sequence, Tuple

EPS = 1e-9

# Resources that exist on every node implicitly.
IMPLICIT_RESOURCES = ("CPU", "memory", "object_store_memory")

# A node's view: {"total": {res: qty}, "available": {res: qty}, "labels": {...},
#                 "alive": bool, "address": str}


def subtract(avail: Dict[str, float], req: Dict[str, float]) -> None:
    for k, v in req.items():
        avail[k] = avail.get(k, 0.0) - v


def add_back(avail: Dict[str, float], req: Dict[str, float]) -> None:
    for k, v in req.items():
        avail[k] = avail.get(k, 0.0) + v


def fits(avail: Dict[str, float], req: Dict[str, float]) -> bool:
    for k, v in req.items():
        if v > EPS and avail.get(k, 0.0) + EPS < v:
            return False
    return True


def feasible(total: Dict[str, float], req: Dict[str, float]) -> bool:
    """Could this node EVER run the request (ignoring current usage)?"""
    for k, v in req.items():
        if v > EPS and total.get(k, 0.0) + EPS < v:
            return False
    return True


def _utilization(node: Dict, req: Dict[str, float]) -> float:
    """Max fractional utilization across requested-and-present resources
    after hypothetically placing `req` (the reference's scorer)."""
    total, avail = node["total"], node["available"]
    score = 0.0
    for k, cap in total.items():
        if cap <= EPS:
            continue
        used = cap - avail.get(k, 0.0) + req.get(k, 0.0)
        score = max(score, min(1.0, used / cap))
    return score


def hybrid_policy(nodes: Dict[str, Dict], req: Dict[str, float],
                  preferred_node: Optional[str] = None,
                  spread_threshold: float = 0.5,
                  top_k_fraction: float = 0.2,
                  rng: Optional[random.Random] = None) -> Optional[str]:
    """Default policy: prefer the local/preferred node while its utilization
    stays under `spread_threshold`, else pack onto the least-utilized
    feasible nodes, randomizing among the top-k to avoid herding
    (reference: hybrid_scheduling_policy.cc)."""
    rng = rng or random
    if preferred_node is not None:
        node = nodes.get(preferred_node)
        if (node is not None and node.get("alive", True)
                and fits(node["available"], req)
                and _utilization(node, req) < spread_threshold):
            return preferred_node

    candidates: List[Tuple[float, str]] = []
    for nid, node in nodes.items():
        if not node.get("alive", True):
            continue
        if not fits(node["available"], req):
            continue
        candidates.append((_utilization(node, req), nid))
    if not candidates:
        return None
    candidates.sort()
    k = max(1, int(len(candidates) * top_k_fraction))
    # prefer below-threshold nodes among the top-k
    below = [c for c in candidates[:k] if c[0] < spread_threshold]
    pool = below or candidates[:k]
    return rng.choice(pool)[1]


def spread_policy(nodes: Dict[str, Dict], req: Dict[str, float],
                  rng: Optional[random.Random] = None) -> Optional[str]:
    """Least-utilized feasible node (SPREAD scheduling strategy)."""
    best, best_score = None, 2.0
    for nid, node in nodes.items():
        if not node.get("alive", True) or not fits(node["available"], req):
            continue
        s = _utilization(node, req)
        if s < best_score:
            best, best_score = nid, s
    return best


def node_affinity_policy(nodes: Dict[str, Dict], req: Dict[str, float],
                         node_id: str, soft: bool) -> Optional[str]:
    node = nodes.get(node_id)
    if node is not None and node.get("alive", True) and fits(node["available"], req):
        return node_id
    if soft:
        return hybrid_policy(nodes, req)
    return None


def pick_node(nodes: Dict[str, Dict], req: Dict[str, float],
              strategy: str = "DEFAULT",
              preferred_node: Optional[str] = None,
              strategy_args: Optional[Dict] = None) -> Optional[str]:
    strategy_args = strategy_args or {}
    if strategy == "SPREAD":
        return spread_policy(nodes, req)
    if strategy == "NODE_AFFINITY":
        return node_affinity_policy(nodes, req, strategy_args["node_id"],
                                    strategy_args.get("soft", False))
    return hybrid_policy(nodes, req, preferred_node=preferred_node)


def schedule_bundles(nodes: Dict[str, Dict], bundles: Sequence[Dict[str, float]],
                     strategy: str) -> Optional[List[str]]:
    """Placement-group bundle placement (reference:
    src/ray/raylet/scheduling/policy/bundle_scheduling_policy.cc).
    Returns one node id per bundle, or None if infeasible. Works on a copy
    of availability so partial placements don't leak."""
    shadow = {nid: {**n, "available": dict(n["available"])}
              for nid, n in nodes.items() if n.get("alive", True)}

    def place(bundle, allowed=None, forbidden=()):
        order = sorted(shadow.items(), key=lambda kv: _utilization(kv[1], bundle))
        if strategy in ("SPREAD", "STRICT_SPREAD"):
            pass  # least-utilized first = spread
        else:  # PACK: most-utilized first
            order = order[::-1]
        for nid, node in order:
            if allowed is not None and nid not in allowed:
                continue
            if nid in forbidden:
                continue
            if fits(node["available"], bundle):
                subtract(node["available"], bundle)
                return nid
        return None

    placement: List[str] = []
    if strategy == "STRICT_PACK":
        # all bundles on one node
        for nid, node in sorted(shadow.items(),
                                key=lambda kv: _utilization(kv[1], {}), reverse=True):
            avail = dict(node["available"])
            ok = True
            for b in bundles:
                if not fits(avail, b):
                    ok = False
                    break
                subtract(avail, b)
            if ok:
                return [nid] * len(bundles)
        return None
    used: set = set()
    for bundle in bundles:
        forbidden = used if strategy == "STRICT_SPREAD" else ()
        nid = place(bundle, forbidden=forbidden)
        if nid is None:
            return None
        placement.append(nid)
        used.add(nid)
    return placement
