"""Worker process entry point (reference:
python/ray/_private/workers/default_worker.py). The asyncio loop runs on the
main thread; task execution happens in executor threads, so user code inside
tasks can call the public API through the same threadsafe bridge the driver
uses."""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import sys


def main():
    from ray_tpu._private.proc_util import set_pdeathsig_from_env
    set_pdeathsig_from_env()
    parser = argparse.ArgumentParser()
    parser.add_argument("--node-address", required=True)
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--store-path", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--session-name", default="session")
    args = parser.parse_args()
    logging.basicConfig(
        level=os.environ.get("RAY_TPU_LOG_LEVEL", "WARNING"),
        format=f"[worker {os.getpid()}] %(levelname)s %(message)s")

    # Worker-side jax platform pin. Some environments register device
    # plugins through sitecustomize and override the JAX_PLATFORMS env
    # var with jax.config at interpreter start; tests (and CPU-only
    # deployments) need workers pinned to a platform the same way the
    # driver pins itself with jax.config.update.
    plat = os.environ.get("RAY_TPU_JAX_PLATFORMS")
    if plat:
        try:
            import jax
            jax.config.update("jax_platforms", plat)
        except Exception:
            logging.getLogger(__name__).exception(
                "jax platform pin %r failed", plat)

    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.worker import CoreWorker, Worker

    core = CoreWorker(mode="worker", gcs_address=args.gcs_address,
                      node_address=args.node_address,
                      store_path=args.store_path, node_id=args.node_id)
    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    from ray_tpu.util import sanitizers
    loop.run_until_complete(core.start_async())
    if sanitizers.enabled():
        loop.call_soon(sanitizers.maybe_install)
    worker_mod.global_worker = Worker(core, owns_loop=False)

    # crash black box: continuous on-disk mirror of this worker's event
    # ring + metrics snapshots; clean shutdown seals it in stop_async
    from ray_tpu._private import blackbox
    from ray_tpu._private.config import cfg
    blackbox.configure(
        cfg.blackbox_dir or f"/tmp/raytpu/{args.session_name}/blackbox",
        f"worker-{core.worker_id[:12]}", node_id=args.node_id,
        worker_id=core.worker_id)

    import ray_tpu
    ray_tpu._set_connected_from_worker(core)

    try:
        loop.run_forever()
    except KeyboardInterrupt:
        pass
    sys.exit(0)


if __name__ == "__main__":
    main()
