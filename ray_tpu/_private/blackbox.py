"""Crash black boxes: bounded on-disk NDJSON mirrors of each process's
flight-recorder ring + last metrics snapshot.

The flight recorder (events.py) and the metrics pusher both ship state
to the GCS — which is exactly the component that is gone in the
failures worth debugging (GCS death, node-manager SIGKILL, a worker
OOM-killed mid-launch). The black box is the local, durable complement:
every daemon continuously appends its event records and periodic
metrics snapshots to a size-bounded NDJSON file, so whatever survives
on disk after a crash IS the post-mortem. `ray_tpu blackbox` stitches
the surviving boxes of a session into one cross-node timeline
(clock-skew adjusted via the GCS clock offset each process learns at
registration).

Survivability model, in order of violence:

- **SIGKILL / OOM-kill / power loss**: nothing runs at death. The box
  is written *continuously* (every event record is appended as it is
  recorded, via events.set_tap), so the file already holds everything
  up to the last append. The final line may be torn; the reader skips
  unparseable lines.
- **Fatal-but-catchable (SIGTERM, GCS-disconnect suicide, unhandled
  exit)**: `seal(reason)` writes a final metrics snapshot, any ring
  records the tap never saw, and a terminal ``seal`` record, then
  fsyncs. A box without a seal record therefore died hard — the
  stitcher labels it so.
- **Clean exit**: same seal path via atexit, reason="clean_exit".

Bounded-size discipline mirrors the in-memory ring: the live segment
rotates to a single ``.1`` segment at max_bytes/2, so live+rotated stay
under max_bytes and always hold the NEWEST records.

File format: one JSON object per line. Every record carries ``ts``
(wall clock), ``seq`` (per-box monotonic counter — total order within
a box even when wall clocks step), and ``kind``:

- ``header`` — process identity, pid, clock_offset_s, opened-at; first
  line of every segment.
- ``event`` — one flight-recorder record (name/category/span ids/
  start/end/attrs), mirrored as recorded.
- ``metrics`` — a registry snapshot (same shape as report_metrics
  payloads).
- ``marker`` — process-lifecycle breadcrumbs (startup, gcs_disconnect,
  signal received, ...).
- ``seal`` — terminal record with the seal reason.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "BlackBox", "configure", "get", "record", "seal", "reset",
    "scan_boxes", "read_box", "stitch", "count_boxes", "box_path",
]

_SUFFIX = ".bbox.ndjson"

_lock = threading.Lock()
_box: Optional["BlackBox"] = None


def box_path(directory: str, process: str, pid: Optional[int] = None) -> str:
    pid = os.getpid() if pid is None else pid
    return os.path.join(directory, f"{process}-{pid}{_SUFFIX}")


class BlackBox:
    """One process's black box. Thread-safe; every write appends one
    NDJSON line and rotates at the size bound. Writes are line-buffered
    through a plain file object — an append is two syscalls, cheap
    enough to ride the event tap."""

    def __init__(self, path: str, max_bytes: int = 4 * 1024 * 1024,
                 process: str = "proc", node_id: str = "",
                 worker_id: str = "", clock_offset_s: float = 0.0):
        self.path = path
        self.max_bytes = max(int(max_bytes), 4096)
        self.process = process
        self.node_id = node_id
        self.worker_id = worker_id
        self.clock_offset_s = float(clock_offset_s)
        self._seq = 0
        self._size = 0
        self._sealed = False
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self._size = self._f.tell()
        self._write_header()

    # ------------------------------------------------------------- writes
    def _write_header(self) -> None:
        self._write({"kind": "header", "process": self.process,
                     "pid": os.getpid(), "node_id": self.node_id,
                     "worker_id": self.worker_id,
                     "clock_offset_s": self.clock_offset_s})

    def set_clock_offset(self, offset_s: float) -> None:
        """Update the local-minus-GCS clock offset once it is measured
        (registration happens after the box opens). Re-headers so the
        reader sees the freshest offset regardless of segment."""
        self.clock_offset_s = float(offset_s)
        self._write_header()

    def _write(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            if self._sealed:
                return
            self._seq += 1
            rec.setdefault("ts", time.time())
            rec["seq"] = self._seq
            try:
                line = json.dumps(rec, default=str) + "\n"
            except Exception:
                return
            try:
                if self._size + len(line) > self.max_bytes // 2:
                    self._rotate()
                self._f.write(line)
                self._f.flush()
                self._size += len(line)
            except Exception:
                pass

    def _rotate(self) -> None:
        # live -> .1 (replacing any prior .1): live+rotated <= max_bytes,
        # and the newest max_bytes/2 of history always survives.
        self._f.close()
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            pass
        self._f = open(self.path, "a", encoding="utf-8")
        self._size = self._f.tell()
        # re-header the fresh segment inline (already under _lock):
        self._seq += 1
        hdr = {"kind": "header", "process": self.process,
               "pid": os.getpid(), "node_id": self.node_id,
               "worker_id": self.worker_id,
               "clock_offset_s": self.clock_offset_s,
               "ts": time.time(), "seq": self._seq, "rotated": True}
        line = json.dumps(hdr) + "\n"
        self._f.write(line)
        self._f.flush()
        self._size += len(line)

    def on_event(self, rec: Dict[str, Any]) -> None:
        """events.set_tap target: mirror one ring record."""
        self._write({"kind": "event", "name": rec.get("name"),
                     "category": rec.get("category"),
                     "event_kind": rec.get("kind"),
                     "trace_id": rec.get("trace_id"),
                     "span_id": rec.get("span_id"),
                     "parent_span_id": rec.get("parent_span_id"),
                     "start": rec.get("start"), "end": rec.get("end"),
                     "attrs": rec.get("attrs") or {},
                     "ts": rec.get("end") or rec.get("start")})

    def record(self, kind: str, **fields) -> None:
        """Lifecycle breadcrumb (kind='marker' unless caller overrides
        via a recognized kind like 'metrics')."""
        rec = {"kind": kind}
        rec.update(fields)
        self._write(rec)

    def snapshot_metrics(self) -> None:
        try:
            from ray_tpu.util.metrics import registry_snapshot
            rows = registry_snapshot()
        except Exception:
            rows = []
        if rows:
            self._write({"kind": "metrics", "metrics": rows})

    def seal(self, reason: str) -> None:
        """Terminal flush: final metrics snapshot, any ring records the
        tap missed (recorded before configure()), the seal record, then
        fsync. Idempotent — first reason wins."""
        with self._lock:
            if self._sealed:
                return
        self.snapshot_metrics()
        try:
            from ray_tpu._private import events as _events
            for rec in _events.peek():
                if not rec.get("_bb_seen"):
                    self.on_event(rec)
        except Exception:
            pass
        self._write({"kind": "seal", "reason": reason})
        with self._lock:
            self._sealed = True
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
            except Exception:
                pass

    def close(self) -> None:
        with self._lock:
            self._sealed = True
            try:
                self._f.close()
            except Exception:
                pass


# ----------------------------------------------------------- process wiring
def configure(directory: str, process: str, node_id: str = "",
              worker_id: str = "", max_bytes: Optional[int] = None,
              metrics_interval_s: Optional[float] = None,
              tap_events: bool = True) -> Optional[BlackBox]:
    """Open (or return) this process's black box and wire it in:
    events-recorder tap, periodic metrics snapshots, atexit seal.
    Returns None when disabled via cfg.blackbox_enabled. SIGTERM
    handling stays with the caller (daemons own their signal policy);
    they call `seal()` on their death paths."""
    global _box
    from ray_tpu._private.config import cfg
    if not cfg.blackbox_enabled:
        return None
    with _lock:
        if _box is not None:
            return _box
        box = BlackBox(
            box_path(directory, process),
            max_bytes=int(max_bytes if max_bytes is not None
                          else cfg.blackbox_max_bytes),
            process=process, node_id=node_id, worker_id=worker_id)
        _box = box
    box.record("marker", event="startup", argv=" ".join(sys.argv[:3]))
    if tap_events:
        from ray_tpu._private import events as _events

        def _tap(rec, _box=box):
            rec["_bb_seen"] = True
            _box.on_event(rec)

        _events.set_tap(_tap)
        # backfill anything recorded before the tap existed
        for rec in _events.peek():
            if not rec.get("_bb_seen"):
                rec["_bb_seen"] = True
                box.on_event(rec)
    interval = (cfg.blackbox_metrics_interval_s
                if metrics_interval_s is None else metrics_interval_s)
    if interval and interval > 0:
        def _loop(_box=box, _dt=float(interval)):
            while not _box._sealed:
                time.sleep(_dt)
                try:
                    _box.snapshot_metrics()
                except Exception:
                    logging.getLogger(__name__).debug(
                        "blackbox metrics snapshot failed", exc_info=True)
        threading.Thread(target=_loop, name="blackbox-metrics",
                         daemon=True).start()
    atexit.register(lambda: box.seal("clean_exit"))
    return box


def get() -> Optional[BlackBox]:
    return _box


def record(kind: str, **fields) -> None:
    if _box is not None:
        _box.record(kind, **fields)


def seal(reason: str) -> None:
    if _box is not None:
        _box.seal(reason)


def reset() -> None:
    """Test hook: drop the process singleton (and its events tap)."""
    global _box
    with _lock:
        if _box is not None:
            _box.close()
        _box = None
    try:
        from ray_tpu._private import events as _events
        _events.set_tap(None)
    except Exception:
        pass


# ------------------------------------------------------------------ readers
def scan_boxes(directory: str) -> List[str]:
    """Live-segment paths of every box under `directory` (rotated .1
    segments are folded into their box by read_box)."""
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return []
    return [os.path.join(directory, n) for n in names
            if n.endswith(_SUFFIX)]


def count_boxes(directory: str) -> int:
    return len(scan_boxes(directory))


def read_box(path: str) -> List[Dict[str, Any]]:
    """All parseable records of one box, rotated segment first, in
    write order. Torn trailing lines (a SIGKILL mid-append) and any
    other garbage lines are skipped, not fatal."""
    records: List[Dict[str, Any]] = []
    for seg in (path + ".1", path):
        try:
            with open(seg, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except Exception:
                        continue
                    if isinstance(rec, dict):
                        records.append(rec)
        except OSError:
            continue
    return records


def stitch(paths: List[str],
           max_skew_s: float = 0.0) -> Dict[str, Any]:
    """Merge multiple boxes into one cross-node timeline.

    Each box's records are ordered by their per-box ``seq`` (immune to
    wall-clock steps within a process), then k-way merged on the
    skew-adjusted timestamp ``ts - clock_offset_s`` (every box's clock
    mapped onto the GCS clock). Ties break deterministically on
    (adjusted_ts, box_index, seq). `max_skew_s` > 0 additionally clamps
    implausible offsets to 0 (a box that claims hours of skew keeps its
    internal order but is not allowed to reorder everyone else).

    Returns {"boxes": [per-box summaries], "records": merged rows with
    box/process/adjusted ts annotations}.
    """
    boxes: List[Dict[str, Any]] = []
    merged: List[Dict[str, Any]] = []
    for idx, path in enumerate(paths):
        recs = read_box(path)
        offset = 0.0
        process = os.path.basename(path)[:-len(_SUFFIX)]
        node_id = worker_id = ""
        sealed_reason = None
        for r in recs:
            if r.get("kind") == "header":
                try:
                    offset = float(r.get("clock_offset_s") or 0.0)
                except (TypeError, ValueError):
                    offset = 0.0
                process = r.get("process") or process
                node_id = r.get("node_id") or node_id
                worker_id = r.get("worker_id") or worker_id
            elif r.get("kind") == "seal":
                sealed_reason = r.get("reason") or "sealed"
        if max_skew_s and abs(offset) > max_skew_s:
            offset = 0.0
        recs.sort(key=lambda r: r.get("seq", 0))
        for r in recs:
            try:
                ts = float(r.get("ts") or 0.0)
            except (TypeError, ValueError):
                ts = 0.0
            merged.append({"adj_ts": ts - offset, "box": idx,
                           "seq": r.get("seq", 0), "process": process,
                           "node_id": node_id, "rec": r})
        boxes.append({"path": path, "process": process,
                      "node_id": node_id, "worker_id": worker_id,
                      "clock_offset_s": offset, "records": len(recs),
                      "sealed": sealed_reason is not None,
                      "seal_reason": sealed_reason or "none (died hard)"})
    merged.sort(key=lambda m: (m["adj_ts"], m["box"], m["seq"]))
    return {"boxes": boxes, "records": merged}
