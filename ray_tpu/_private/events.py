"""Flight recorder: per-process runtime-event spans.

The task-event plane (worker.py `_record_task_event` -> GCS
`add_task_events`) sees task *boundaries*; everything inside a task —
an engine decode step, a spill pass, a shuffle reduce window — is
invisible to it. This module records those interior phases as spans and
instants and ships them into the SAME GCS sink as a distinct
``kind="runtime_event"`` row, so the existing read side (``ray_tpu
timeline``, OTLP export, the dashboard) renders runtime phases and
tasks on one merged timeline (reference: Ray keeps lineage/event
metadata in the GCS for exactly this kind of post-hoc debugging,
PAPERS.md arxiv 1712.05889 §4.2; chrome-trace export via
python/ray/_private/state.py).

Design constraints, in order:

1. **Hot-path cost**: a disabled recorder is one global-flag read; an
   enabled one is two clock reads plus a locked list append. No
   serialization, no RPC, no allocation beyond the record dict. The
   acceptance bench (`bench.py observability_overhead`) holds the enabled
   recorder under 5% on the put and decode-step paths.
2. **Bounded memory with deterministic drop accounting**: the ring
   keeps the NEWEST `capacity` records; every overwrite increments a
   counter that is reported in-band (an ``events.dropped`` instant
   rides each flush that lost records), so a truncated timeline says
   so on the timeline itself.
3. **No hard runtime coupling**: the recorder works in a bare process
   (engine unit tests, probes) — records just rotate in the ring. A
   flusher thread starts lazily and ships batches only once a sink
   exists (the connected worker, or an explicit `set_sink` as used by
   the node manager).

Trace context: spans parent under the enclosing task's propagated
(trace_id, span_id) — read from worker.py's executing-task context —
so one Serve request renders proxy -> replica -> engine-slot ->
first-token as a single trace. `trace_context()` lets non-task threads
(the HTTP proxy, tests) establish a context explicitly.
"""

from __future__ import annotations

import contextlib
import logging
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "record_span", "record_instant", "record_complete", "start_span",
    "Span", "current_context", "trace_context", "new_trace_id",
    "new_span_id", "enabled", "set_enabled", "flush", "drain", "stats",
    "configure", "set_sink", "set_identity", "set_tap", "peek",
]

_lock = threading.Lock()
_buf: List[Dict] = []
_dropped_total = 0            # lifetime drops (never reset)
_dropped_unreported = 0       # drops since the last flushed batch
_capacity = int(os.environ.get("RAY_TPU_RUNTIME_EVENT_BUFFER", "8192"))
_enabled = os.environ.get("RAY_TPU_FLIGHT_RECORDER", "1") != "0"
_sink: Optional[Callable[[List[Dict]], None]] = None
_tap: Optional[Callable[[Dict], None]] = None
_identity: Dict[str, str] = {}
_flusher_started = False
_tls = threading.local()


# --------------------------------------------------------------------- ids
# span ids are the recorder's per-record hot cost: a counter mixed with
# a per-process random salt (splitmix64-style) is ~5x cheaper than an
# os.urandom syscall per span and still collision-safe across processes
# (64 random salt bits under multiplicative diffusion). Trace ids are
# minted rarely (once per root) and stay fully random.
_id_salt = int.from_bytes(os.urandom(8), "little")
_id_counter = __import__("itertools").count(1)
_MASK64 = (1 << 64) - 1


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    n = (next(_id_counter) * 0x9E3779B97F4A7C15 + _id_salt) & _MASK64
    n ^= n >> 31
    return format((n * 0xBF58476D1CE4E5B9) & _MASK64, "016x")


# ----------------------------------------------------------------- context
def current_context() -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) of the innermost active context: an explicit
    `trace_context()` on this thread wins, else the executing task's
    propagated context (worker.py sets it per execution thread /
    coroutine). None outside any traced scope."""
    ctx = getattr(_tls, "trace", None)
    if ctx and ctx[0]:
        return ctx
    w = sys.modules.get("ray_tpu._private.worker")
    if w is not None:
        ctx = getattr(w._exec_tls, "trace", None) or w._trace_ctx.get()
        if ctx and ctx[0]:
            return ctx
    return None


@contextlib.contextmanager
def trace_context(trace_id: Optional[str], span_id: Optional[str]):
    """Establish (trace_id, span_id) as the current thread's trace
    context. Also mirrored into worker.py's execution TLS so task
    submissions made inside the block chain their spans under it (the
    proxy wraps each routed handle call this way)."""
    prev = getattr(_tls, "trace", None)
    _tls.trace = (trace_id, span_id)
    w = sys.modules.get("ray_tpu._private.worker")
    w_prev = None
    if w is not None:
        w_prev = getattr(w._exec_tls, "trace", None)
        w._exec_tls.trace = (trace_id, span_id)
    try:
        yield
    finally:
        _tls.trace = prev
        if w is not None:
            w._exec_tls.trace = w_prev


# ------------------------------------------------------------------- spans
class Span:
    """One in-flight runtime span. `end()` commits it to the ring;
    a span never ended is never recorded (use `cancel()` to make that
    explicit). Safe to end from a different thread than start."""

    __slots__ = ("name", "category", "trace_id", "span_id",
                 "parent_span_id", "start", "attrs", "_done")

    def __init__(self, name: str, category: str,
                 trace_id: Optional[str], parent_span_id: Optional[str],
                 start: Optional[float], attrs: Dict):
        self.name = name
        self.category = category
        self.trace_id = trace_id or new_trace_id()
        self.span_id = new_span_id()
        self.parent_span_id = parent_span_id
        self.start = time.time() if start is None else start
        self.attrs = attrs
        self._done = False

    def set(self, **attrs):
        self.attrs.update(attrs)
        return self

    def end(self, end: Optional[float] = None, **attrs):
        if self._done:
            return
        self._done = True
        if attrs:
            self.attrs.update(attrs)
        _append({"kind": "span", "name": self.name,
                 "category": self.category, "trace_id": self.trace_id,
                 "span_id": self.span_id,
                 "parent_span_id": self.parent_span_id,
                 "start": self.start,
                 "end": time.time() if end is None else end,
                 "attrs": self.attrs})

    def cancel(self):
        self._done = True


class _NullSpan:
    """Recorder disabled: every operation is a no-op attribute hit."""

    __slots__ = ()
    name = category = trace_id = span_id = parent_span_id = None
    start = 0.0
    attrs: Dict = {}

    def set(self, **attrs):
        return self

    def end(self, end=None, **attrs):
        pass

    def cancel(self):
        pass


_NULL_SPAN = _NullSpan()


def start_span(name: str, category: str = "runtime",
               trace_id: Optional[str] = None,
               parent_span_id: Optional[str] = None,
               start: Optional[float] = None, **attrs):
    """Open a span. With no explicit trace_id/parent, it chains under
    `current_context()`; with neither, it roots a fresh trace."""
    if not _enabled:
        return _NULL_SPAN
    if trace_id is None and parent_span_id is None:
        ctx = current_context()
        if ctx is not None:
            trace_id, parent_span_id = ctx
    return Span(name, category, trace_id, parent_span_id, start, attrs)


@contextlib.contextmanager
def record_span(name: str, category: str = "runtime",
                trace_id: Optional[str] = None,
                parent_span_id: Optional[str] = None, **attrs):
    """Context-manager sugar over start_span/end. An exception inside
    the block is recorded on the span (`error` attr) and re-raised."""
    sp = start_span(name, category, trace_id=trace_id,
                    parent_span_id=parent_span_id, **attrs)
    try:
        yield sp
    except BaseException as e:
        sp.end(error=type(e).__name__)
        raise
    else:
        sp.end()


def record_instant(name: str, category: str = "runtime",
                   trace_id: Optional[str] = None,
                   parent_span_id: Optional[str] = None,
                   ts: Optional[float] = None, **attrs) -> None:
    """A zero-duration event (compile tick, eviction, drop marker)."""
    if not _enabled:
        return
    if trace_id is None and parent_span_id is None:
        ctx = current_context()
        if ctx is not None:
            trace_id, parent_span_id = ctx
    now = time.time() if ts is None else ts
    _append({"kind": "instant", "name": name, "category": category,
             "trace_id": trace_id or new_trace_id(),
             "span_id": new_span_id(), "parent_span_id": parent_span_id,
             "start": now, "end": now, "attrs": attrs})


def record_complete(name: str, start: float, end: float,
                    category: str = "runtime",
                    trace_id: Optional[str] = None,
                    parent_span_id: Optional[str] = None, **attrs) -> None:
    """Record an already-measured window (for call sites that decide
    AFTER the fact whether the window is worth recording, e.g. a spill
    pass that moved zero bytes)."""
    if not _enabled:
        return
    if trace_id is None and parent_span_id is None:
        ctx = current_context()
        if ctx is not None:
            trace_id, parent_span_id = ctx
    _append({"kind": "span", "name": name, "category": category,
             "trace_id": trace_id or new_trace_id(),
             "span_id": new_span_id(), "parent_span_id": parent_span_id,
             "start": start, "end": max(end, start), "attrs": attrs})


# -------------------------------------------------------------- ring + flush
def _append(rec: Dict) -> None:
    global _dropped_total, _dropped_unreported
    with _lock:
        if len(_buf) >= _capacity:
            # drop OLDEST: the newest records are the ones a post-mortem
            # needs; every drop is counted and reported in-band
            del _buf[0]
            _dropped_total += 1
            _dropped_unreported += 1
        _buf.append(rec)
    if _tap is not None:
        try:
            _tap(rec)
        except Exception:
            pass
    if not _flusher_started:
        _ensure_flusher()


def enabled() -> bool:
    return _enabled


def set_enabled(value: bool) -> None:
    global _enabled
    _enabled = bool(value)


def configure(capacity: Optional[int] = None) -> None:
    """Test/tuning hook; shrinking the capacity drops oldest records
    immediately (counted, like any overflow)."""
    global _capacity, _dropped_total, _dropped_unreported
    if capacity is not None:
        with _lock:
            _capacity = max(1, int(capacity))
            while len(_buf) > _capacity:
                del _buf[0]
                _dropped_total += 1
                _dropped_unreported += 1


def stats() -> Dict[str, int]:
    with _lock:
        return {"buffered": len(_buf), "capacity": _capacity,
                "dropped_total": _dropped_total,
                "dropped_unreported": _dropped_unreported}


def set_sink(fn: Optional[Callable[[List[Dict]], None]]) -> None:
    """Install an explicit flush target (a callable taking a batch of
    GCS task-event rows). Daemons that are not workers (the node
    manager) use this to ship through their own GCS connection."""
    global _sink
    _sink = fn


def set_tap(fn: Optional[Callable[[Dict], None]]) -> None:
    """Install a copy-tap: called with every ring record as it is
    appended, WITHOUT consuming it (flush/drain still ship normally).
    The crash black box uses this to mirror the flight recorder to disk
    continuously, so a SIGKILL'd process still leaves its last records
    behind. Must be cheap and must not raise (exceptions are swallowed
    to protect the recording hot path)."""
    global _tap
    _tap = fn


def peek(max_records: Optional[int] = None) -> List[Dict]:
    """Copy (do NOT consume) the newest buffered records — the black
    box seals with these so a final flush and a post-mortem snapshot
    can both see the same tail."""
    with _lock:
        if max_records is None:
            return list(_buf)
        return list(_buf[-max_records:])


def set_identity(node_id: Optional[str] = None,
                 worker_id: Optional[str] = None) -> None:
    if node_id:
        _identity["node_id"] = node_id
    if worker_id:
        _identity["worker_id"] = worker_id


def _process_identity() -> Tuple[str, str]:
    node_id = _identity.get("node_id")
    worker_id = _identity.get("worker_id")
    if node_id and worker_id:
        return node_id, worker_id
    w = sys.modules.get("ray_tpu._private.worker")
    core = getattr(getattr(w, "global_worker", None), "core", None) \
        if w is not None else None
    if core is not None:
        return (node_id or getattr(core, "node_id", None)
                or f"pid-{os.getpid()}",
                worker_id or getattr(core, "worker_id", None)
                or f"pid-{os.getpid()}")
    pid = f"pid-{os.getpid()}"
    return node_id or pid, worker_id or pid


def _rows_for(rec: Dict, node_id: str, worker_id: str) -> List[Dict]:
    """One ring record -> GCS task-event rows. The span id doubles as
    the row's task_id so the GCS merge (keyed on task_id) folds the
    RUNNING/FINISHED pair into one row with both state times."""
    base = {
        "task_id": rec["span_id"], "kind": "runtime_event",
        "name": rec["name"], "category": rec["category"],
        "type": "RUNTIME_EVENT", "event_kind": rec["kind"],
        "trace_id": rec["trace_id"], "span_id": rec["span_id"],
        "parent_span_id": rec["parent_span_id"],
        "node_id": node_id, "worker_id": worker_id,
        "attrs": rec["attrs"],
        "state": "RUNNING", "ts": rec["start"],
    }
    if rec["kind"] == "instant":
        return [base]
    return [base, {"task_id": rec["span_id"], "state": "FINISHED",
                   "ts": rec["end"]}]


def drain(max_records: Optional[int] = None) -> List[Dict]:
    """Pop buffered records and render them as GCS task-event rows,
    feeding the built-in runtime metrics as a side effect. When records
    were dropped since the last drain, the batch carries an
    ``events.dropped`` instant with the exact count."""
    global _dropped_unreported
    with _lock:
        n = len(_buf) if max_records is None else min(max_records,
                                                      len(_buf))
        batch, dropped = _buf[:n], _dropped_unreported
        del _buf[:n]
        if batch:
            _dropped_unreported = 0
    if not batch:
        return []
    node_id, worker_id = _process_identity()
    rows: List[Dict] = []
    for rec in batch:
        _observe_builtin_metrics(rec)
        rows.extend(_rows_for(rec, node_id, worker_id))
    if dropped:
        marker = {"kind": "instant", "name": "events.dropped",
                  "category": "recorder", "trace_id": new_trace_id(),
                  "span_id": new_span_id(), "parent_span_id": None,
                  "start": time.time(), "end": time.time(),
                  "attrs": {"count": dropped}}
        _observe_builtin_metrics(marker)
        rows.extend(_rows_for(marker, node_id, worker_id))
    return rows


def _default_sink() -> Optional[Callable[[List[Dict]], None]]:
    if _sink is not None:
        return _sink
    try:
        import ray_tpu
        if not ray_tpu.is_initialized():
            return None
        w = ray_tpu._get_worker()
        return lambda batch: w.gcs_call("add_task_events", events=batch)
    except Exception:
        return None


def flush() -> int:
    """Synchronous flush (shutdown paths, tests). Returns the number of
    rows shipped; 0 when no sink is reachable (records stay buffered)."""
    sink = _default_sink()
    if sink is None:
        return 0
    rows = drain()
    if not rows:
        return 0
    try:
        sink(rows)
    except Exception:
        return 0
    return len(rows)


_flush_err_logged = False


def _flush_loop():
    global _flush_err_logged
    while True:
        time.sleep(1.0)
        try:
            flush()
        except Exception:
            # flush() already swallows sink errors; reaching here means
            # the recorder itself broke — say so once, don't spam a
            # 1 Hz daemon log
            if not _flush_err_logged:
                _flush_err_logged = True
                logging.getLogger(__name__).warning(
                    "event flush loop error (logged once)", exc_info=True)


def _ensure_flusher():
    global _flusher_started
    with _lock:
        if _flusher_started:
            return
        _flusher_started = True
    threading.Thread(target=_flush_loop, name="events-flush",
                     daemon=True).start()


# ------------------------------------------------------- built-in metrics
# Runtime metrics derived from spans, auto-registered on the existing
# /metrics plane the first time their span fires (ISSUE: engine step
# duration, spill bytes, scheduler queue latency). Observation happens
# at drain time — the flusher thread, never the recording hot path.
_builtin: Optional[Dict[str, Any]] = None
_builtin_lock = threading.Lock()


def _get_builtin() -> Dict[str, Any]:
    global _builtin
    if _builtin is None:
        with _builtin_lock:
            if _builtin is None:
                from ray_tpu.util.metrics import Counter, Histogram
                ms = [0.1, 0.5, 1.0, 5.0, 10.0, 25.0, 50.0, 100.0,
                      250.0, 500.0, 1000.0]
                _builtin = {
                    "engine_step_ms": Histogram(
                        "runtime_engine_step_ms",
                        "inference engine decode-step duration (ms)",
                        boundaries=ms),
                    "queue_latency_ms": Histogram(
                        "runtime_scheduler_queue_latency_ms",
                        "request wait from submit to slot admission (ms)",
                        boundaries=ms),
                    "spill_bytes": Counter(
                        "runtime_spill_bytes_total",
                        "object-store bytes spilled to external storage"),
                    "restore_bytes": Counter(
                        "runtime_restore_bytes_total",
                        "object-store bytes restored from external "
                        "storage"),
                    "events_dropped": Counter(
                        "runtime_events_dropped_total",
                        "flight-recorder ring overwrites"),
                }
    return _builtin


def _observe_builtin_metrics(rec: Dict) -> None:
    name = rec["name"]
    try:
        if name == "engine.decode":
            _get_builtin()["engine_step_ms"].observe(
                (rec["end"] - rec["start"]) * 1e3)
        elif name == "engine.slot":
            wait = rec["attrs"].get("queue_wait_ms")
            if wait is not None:
                _get_builtin()["queue_latency_ms"].observe(float(wait))
        elif name == "store.spill":
            _get_builtin()["spill_bytes"].inc(
                float(rec["attrs"].get("bytes", 0) or 0))
        elif name == "store.restore":
            _get_builtin()["restore_bytes"].inc(
                float(rec["attrs"].get("bytes", 0) or 0))
        elif name == "events.dropped":
            _get_builtin()["events_dropped"].inc(
                float(rec["attrs"].get("count", 0) or 0))
    except Exception:
        pass
