"""ObjectRef — a future for an object owned by some worker.

Identity is a 20-byte id embedding the creating TaskID + index (ids.py,
reference: src/ray/common/id.h); `owner_address` is the RPC address of the
owning worker, carried with the ref so any holder can reach the owner for
value/location/refcount messages (reference ownership model:
src/ray/core_worker/reference_count.h:64).

Pickling a ref fires `_serialization_hook` (set by serialization.serialize)
so the runtime can track borrows; unpickling binds the ref to the local
worker runtime and registers the borrow with `_deserialization_hook`.
"""

from __future__ import annotations

import threading
from typing import Optional

# Per-thread active serialization hook (set by serialization.serialize for
# the duration of one pickling pass). Thread-local rather than a class
# attribute: puts and task submissions serialize on their CALLING threads
# concurrently, and a shared hook slot would cross-wire the contained-ref
# tracking of unrelated serializations.
_ser_tls = threading.local()


class ObjectRef:
    _deserialization_hook = None   # set by the worker runtime at startup

    __slots__ = ("id", "owner_address", "_weakly_held")

    def __init__(self, id: bytes, owner_address: str = "",
                 _register: bool = True):
        self.id = id
        self.owner_address = owner_address
        self._weakly_held = not _register
        if _register:
            hook = ObjectRef._local_ref_hook
            if hook is not None:
                hook(self)

    _local_ref_hook = None         # worker runtime: local refcount ++
    _local_unref_hook = None       # worker runtime: local refcount --

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()[:16]})"

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __reduce__(self):
        hook = getattr(_ser_tls, "hook", None)
        if hook is not None:
            hook(self)
        return (_rebuild_ref, (self.id, self.owner_address))

    def __del__(self):
        if not self._weakly_held:
            unref = ObjectRef._local_unref_hook
            if unref is not None:
                try:
                    unref(self)
                except Exception:
                    pass

    # Allow `await ref` inside async actors / driver coroutines.
    def __await__(self):
        from ray_tpu._private.worker import global_worker
        return global_worker.get_async(self).__await__()

    def future(self):
        """concurrent.futures.Future resolving to the object's value."""
        from ray_tpu._private.worker import global_worker
        return global_worker.as_future(self)


def _rebuild_ref(id: bytes, owner_address: str) -> "ObjectRef":
    ref = ObjectRef(id, owner_address, _register=False)
    hook = ObjectRef._deserialization_hook
    if hook is not None:
        hook(ref)
        ref._weakly_held = False
    return ref
