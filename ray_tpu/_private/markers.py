"""Concurrency markers read by rtlint (and by humans).

``@off_loop(lock="_ref_lock")`` declares that a method is a thread
entry point — it runs on CALLER threads, off the owner event loop (the
PR 1 put path, the PR 6 striped-arena client methods) — and names the
instance lock its shared-state mutations must hold. The decorator is a
pure annotation: zero runtime cost, the function is returned unchanged
with ``__rt_off_loop__`` attached for introspection. rtlint's RT003
reads the marker statically and flags any ``self.*`` store in the body
that is not inside ``with self.<lock>:`` — intentional GIL-atomic
publishes carry an inline ``# rtlint: disable=RT003 — <why>`` so the
atomicity argument lives next to the code.

This is the static sibling of ``util/sanitizers.SingleLoopChecker``
(which pins loop-owned components at runtime); together they are this
repo's analog of the reference's ``thread_checker.h`` + tsan CI tier.

Kept dependency-free: imported by ``object_store.py``/``worker.py``
before anything heavy is loadable.
"""

from __future__ import annotations

from typing import Callable, Optional


def off_loop(lock: Optional[str] = None) -> Callable:
    """Mark a method as an off-event-loop thread entry; ``lock`` names
    the instance attribute (e.g. ``"_ref_lock"``) guarding its shared
    mutations. Use as ``@off_loop(lock="_ref_lock")`` (the call form is
    required — rtlint keys on it)."""
    def deco(fn):
        fn.__rt_off_loop__ = {"lock": lock}
        return fn
    return deco
