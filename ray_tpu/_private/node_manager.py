"""Node manager — the per-node daemon (raylet equivalent).

Re-design of the reference's raylet (reference: src/ray/raylet/node_manager.h:119,
worker_pool.h:174, local_task_manager.cc, object_manager/object_manager.h:117).
Owns the node's resource accounting, the worker pool, lease grants for task
execution, placement-group bundle reservations, and node-to-node object
transfer against the shared-memory arena (object_store.py). Differences:

- Scheduling is lease-granting only: callers push tasks directly to leased
  workers; the node manager never sees task payloads (the reference routes
  the lease the same way but also manages arg-dependency pulls — here the
  executing worker pulls its own args through this daemon's pull_object).
- Spillback is an explicit redirect reply carrying the chosen node's
  address (reference: spillback in local_task_manager.cc).
- Object transfer negotiates over control RPCs (request_push/push_begin)
  but chunk bytes move on a dedicated binary data plane — a second raw
  socket per node manager (data_plane.py) that streams pinned-arena
  memoryviews into recv_into() regions, striped across
  cfg.transfer_streams connections, with a msgpack-chunk fallback for
  peers that advertise no data plane. The store arena is mapped by every
  local process so serving bytes is a zero-copy read (reference: chunked
  gRPC Push/Pull distinct from control RPCs, pull_manager.h:52,
  push_manager.h:30).
"""

from __future__ import annotations

import asyncio
import logging
import os
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from ray_tpu._private import ledger, rpc, scheduling
from ray_tpu._private.config import cfg
from ray_tpu._private.object_store import ObjectStoreClient, parallel_write

logger = logging.getLogger(__name__)

# tunables live in config.py (transfer_chunk_bytes, heartbeat_interval_s,
# view_refresh_s, lease_wait_timeout_s, ...)


class WorkerProc:
    __slots__ = ("worker_id", "address", "pid", "conn", "proc", "state",
                 "actor_id", "lease_id", "registered", "env_hash",
                 "idle_since")

    def __init__(self, proc=None):
        self.worker_id = None
        self.address = None
        self.pid = None
        self.conn: Optional[rpc.Connection] = None
        self.proc: Optional[subprocess.Popen] = proc
        self.state = "starting"        # starting | idle | leased | actor | dead
        self.actor_id: Optional[str] = None
        self.lease_id: Optional[str] = None
        self.registered = asyncio.Event()
        # runtime-env pool key: once a worker materializes a pip env it
        # serves ONLY that env (reference: per-env worker pools,
        # worker_pool.h:174)
        self.env_hash: Optional[str] = None
        self.idle_since: float = 0.0


class NodeManager:
    def __init__(self, gcs_address: str, node_id: Optional[str] = None,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 session_name: str = "session",
                 store_bytes: int = 0, port: int = 0,
                 store_path: Optional[str] = None,
                 gcs_address_source: Optional[str] = None):
        self.gcs_address = gcs_address
        # discovery channel for GCS-FT: a restarted GCS (possibly on a
        # new port/host) publishes its address through its store client;
        # the heartbeat reconnect path re-reads it (reference: raylets
        # re-resolve the GCS address from Redis)
        self.gcs_address_source = gcs_address_source
        self.node_id = node_id or os.urandom(16).hex()
        self.session_name = session_name
        self.labels = labels or {}
        self.port = port
        ncpu = os.cpu_count() or 1
        self.total = dict(resources or {})
        self.total.setdefault("CPU", float(ncpu))
        # auto-detect accelerators (TPU chips + pod-slice resources) unless
        # the caller pinned them explicitly (tests use fake resources)
        explicit_tpu = "TPU" in self.total
        if not explicit_tpu:
            try:
                from ray_tpu._private.accelerators import \
                    detect_node_accelerators
                for k, v in detect_node_accelerators().items():
                    self.total.setdefault(k, v)
            except Exception:
                logger.exception("accelerator detection failed")
        # chip ids are the REAL ids (TPU_VISIBLE_CHIPS-aware), not range(n)
        try:
            from ray_tpu._private.accelerators import detect_chip_ids
            ids = detect_chip_ids()
        except Exception:
            ids = []
        n = int(self.total.get("TPU", 0))
        if len(ids) != n:   # explicitly-configured (fake) TPU counts
            ids = [str(i) for i in range(n)]
        self._free_chips = ids
        self.total.setdefault("memory", float(2 * 1024**3))
        self.total.setdefault("object_store_memory",
                              float(store_bytes or 512 * 1024**2))
        self.available = dict(self.total)
        self.store_path = store_path or \
            f"/dev/shm/raytpu_{session_name}_{self.node_id[:12]}"
        self.store_bytes = int(store_bytes or self.total["object_store_memory"])

        self.gcs: Optional[rpc.Connection] = None
        self.server: Optional[rpc.Server] = None
        self.address: Optional[str] = None
        self.unix_address: Optional[str] = None
        self.store: Optional[ObjectStoreClient] = None
        self.pool = rpc.ConnectionPool(name=f"nm-{self.node_id[:8]}")
        # binary data plane (data_plane.py): second raw-stream socket for
        # bulk object chunks, advertised next to the RPC address
        self.data_plane_address: Optional[str] = None
        self._data_server = None
        self._data_client = None

        self.workers: Dict[str, WorkerProc] = {}
        self._idle: List[WorkerProc] = []
        self._spawning = 0
        self._lease_waiters: List[asyncio.Future] = []
        self._leases: Dict[str, Dict] = {}
        self._lease_seq = 0
        self.bundles: Dict[tuple, Dict] = {}   # (pg_id, idx) -> {resources, available, committed}
        self.cluster_view: Dict[str, Dict] = {}
        self._view_version: Optional[int] = None
        self._view_debits: Dict[str, List] = {}   # unconfirmed spill debits
        self._tasks: List[asyncio.Task] = []
        self._draining = False
        self._pulls_inflight: Dict[bytes, asyncio.Future] = {}
        self._pull_bytes_inflight = 0
        self._pull_waiters: "deque" = __import__("collections").deque()
        self._receiving: Dict[bytes, Dict] = {}
        self._recv_done: Dict[bytes, asyncio.Future] = {}
        # queued lease demand, reported in heartbeats for the autoscaler
        self._pending_demand: List[Dict[str, float]] = []
        self._spill_mutex = threading.Lock()
        # leaked objects the GCS ledger sweep told us to reclaim under
        # pressure (consumed first by the spill pass — deleting a leaked
        # object frees bytes without disk IO). Mutated from the owner
        # loop (hint handler) and read from the spill executor thread;
        # individual set ops are GIL-atomic and the hints are advisory.
        self._evict_hints: set = set()
        # pid -> [(path, stream_name, offset), ...] for the log monitor
        self._log_files: Dict[int, list] = {}
        # compiled-DAG channel mirrors this daemon writes into
        self._dag_channels: Dict[str, object] = {}
        # launch critical-path attribution: last-observed duration per
        # launch phase on this node (resource_wait / worker_obtain /
        # become_actor) -> runtime_launch_phase_ms{phase} gauges
        self._launch_phase_ms: Dict[str, float] = {}
        self._launches_total = 0
        self._clock_offset_s = 0.0   # local wall clock minus GCS clock
        # thread_checker.h analog: no-op unless RAY_TPU_LOOP_SANITIZER
        from ray_tpu.util.sanitizers import SingleLoopChecker
        self._loop_checker = SingleLoopChecker("NodeManager")

    # -------------------------------------------------------------- lifecycle
    async def start(self) -> str:
        self.store = ObjectStoreClient(self.store_path, create=True,
                                       size=self.store_bytes,
                                       stripes=cfg.arena_stripes)
        handlers = {
            "register_worker": self.h_register_worker,
            "request_lease": self.h_request_lease,
            "return_lease": self.h_return_lease,
            "create_actor": self.h_create_actor,
            "kill_worker": self.h_kill_worker,
            "prepare_bundle": self.h_prepare_bundle,
            "commit_bundle": self.h_commit_bundle,
            "return_bundle": self.h_return_bundle,
            "pull_object": self.h_pull_object,
            "fetch_object": self.h_fetch_object,
            "request_push": self.h_request_push,
            "push_begin": self.h_push_begin,
            "push_chunk": self.h_push_chunk,
            "push_abort": self.h_push_abort,
            "broadcast_object": self.h_broadcast_object,
            "has_object": self.h_has_object,
            "restore_object": self.h_restore_object,
            "spill_now": self.h_spill_now,
            "free_object": self.h_free_object,
            "free_remote_object": self.h_free_remote_object,
            "get_node_info": self.h_get_node_info,
            "ledger_evict_hint": self.h_ledger_evict_hint,
            "channel_push": self.h_channel_push,
            "channel_publish": self.h_channel_publish,
            "channel_close": self.h_channel_close,
            "dump_stacks": self.h_dump_stacks,
            "ping": lambda conn: "pong",
        }
        self.server = rpc.Server(handlers, name=f"nm-{self.node_id[:8]}")
        self.server.on_disconnect = self._on_disconnect
        self.address = await self.server.listen_tcp("0.0.0.0", self.port)
        self.unix_address = await self.server.listen_unix(
            f"/tmp/raytpu/{self.session_name}/nm_{self.node_id[:12]}.sock")
        if cfg.data_plane_enabled:
            from ray_tpu._private.data_plane import (DataPlaneClient,
                                                     DataPlaneServer)
            self._data_server = DataPlaneServer(self)
            self.data_plane_address = await self._data_server.start("0.0.0.0")
            self._data_client = DataPlaneClient(
                name=f"nm-{self.node_id[:8]}")
        self.gcs = await rpc.connect(
            self.gcs_address, handlers={
                "create_actor": self.h_create_actor,
                "kill_worker": self.h_kill_worker,
                "prepare_bundle": self.h_prepare_bundle,
                "commit_bundle": self.h_commit_bundle,
                "return_bundle": self.h_return_bundle,
                "ledger_evict_hint": self.h_ledger_evict_hint,
                "pubsub": self.h_pubsub,
            }, name="nm->gcs", retries=20)
        resp = await self.gcs.call(
            "register_node", node_id=self.node_id, address=self.address,
            object_store_address=self.store_path,
            data_plane_address=self.data_plane_address,
            resources=self.total, labels=self.labels,
            node_ip=rpc.node_ip_address())
        self.cluster_view = resp["cluster_view"]
        self._view_version = resp.get("view_version")
        # one head-side config governs the cluster (reference:
        # GetSystemConfig handshake, node_manager.proto:432)
        cfg.apply(resp.get("system_config") or {})
        if resp.get("gcs_ts"):
            # local minus GCS clock (half-RTT error bound) — recorded in
            # the black box header so cross-node stitches de-skew
            self._clock_offset_s = time.time() - float(resp["gcs_ts"])
        await self.gcs.call("subscribe", channel="NODE")
        # spill target: node-local dir by default, any fsspec URI when
        # cfg.spill_uri is set (gs:// on real pods; memory:// in tests)
        if cfg.spill_uri:
            from ray_tpu.util import storage as _storage
            _storage.validate_root(cfg.spill_uri, "spill")
            self.spill_dir = _storage.join(
                cfg.spill_uri, self.session_name,
                f"spill_{self.node_id[:8]}")
            self._spill_remote = _storage.is_remote(self.spill_dir)
        else:
            self.spill_dir = (f"/tmp/raytpu/{self.session_name}/"
                              f"spill_{self.node_id[:8]}")
            self._spill_remote = False
        self.spilled: Dict[bytes, str] = {}
        # flight-recorder sink: this daemon is not a worker, so its
        # spill/restore/transfer spans ship over the node manager's own
        # GCS connection (resolved at call time — it is replaced on GCS
        # reconnect)
        from ray_tpu._private import events as _events
        _loop = asyncio.get_event_loop()

        def _ship_events(batch):
            gcs = self.gcs
            if gcs is None or gcs.closed:
                raise ConnectionError("gcs connection down")
            asyncio.run_coroutine_threadsafe(
                gcs.notify("add_task_events", events=batch), _loop)

        _events.set_identity(node_id=self.node_id,
                             worker_id=f"nm-{self.node_id[:12]}")
        _events.set_sink(_ship_events)

        # crash black box: continuous on-disk mirror of this daemon's
        # event ring + metrics snapshots (sealed on the GCS-disconnect
        # death path and on clean exit; a SIGKILL keeps the appends)
        from ray_tpu._private import blackbox as _blackbox
        bb = _blackbox.configure(
            cfg.blackbox_dir or f"/tmp/raytpu/{self.session_name}/blackbox",
            f"nm-{self.node_id[:12]}", node_id=self.node_id,
            worker_id=f"nm-{self.node_id[:12]}")
        if bb is not None and self._clock_offset_s:
            bb.set_clock_offset(self._clock_offset_s)

        # object-lifetime ledger: same daemon-sink pattern — this
        # process's spill/restore/evict/arrival deltas ship over the
        # node manager's own GCS connection
        def _ship_ledger(batch):
            gcs = self.gcs
            if gcs is None or gcs.closed:
                raise ConnectionError("gcs connection down")
            asyncio.run_coroutine_threadsafe(
                gcs.notify("update_object_ledger", records=batch,
                           node_id=self.node_id,
                           worker_id=f"nm-{self.node_id[:12]}"), _loop)

        ledger.set_enabled(cfg.ledger_enabled)
        ledger.set_identity(node_id=self.node_id,
                            worker_id=f"nm-{self.node_id[:12]}")
        ledger.set_sink(_ship_ledger)
        self._tasks = [
            asyncio.ensure_future(self._log_monitor_loop()),
            asyncio.ensure_future(self._heartbeat_loop()),
            asyncio.ensure_future(self._view_refresh_loop()),
            asyncio.ensure_future(self._reap_children_loop()),
            asyncio.ensure_future(self._memory_monitor_loop()),
            asyncio.ensure_future(self._spill_loop()),
            asyncio.ensure_future(self._metrics_push_loop()),
            asyncio.ensure_future(self._ledger_census_loop()),
        ]
        logger.info("node manager %s at %s (store %s, %s)",
                    self.node_id[:12], self.address, self.store_path,
                    {k: v for k, v in self.total.items() if v})
        return self.address

    async def stop(self):
        for t in self._tasks:
            t.cancel()
        for w in self.workers.values():
            self._kill_proc(w)
        await self.server.close()
        if self._data_server is not None:
            await self._data_server.close()
        if self._data_client is not None:
            self._data_client.close()
        if self.gcs:
            await self.gcs.close()
        await self.pool.close()
        if self.store:
            self.store.close()
        try:
            os.unlink(self.store_path)
        except OSError:
            pass

    def _kill_proc(self, w: WorkerProc):
        # workers are session leaders (start_new_session): kill the whole
        # group so user tasks' own subprocesses don't outlive the worker
        if w.proc is not None and w.proc.poll() is None:
            from ray_tpu._private.proc_util import kill_process_group
            kill_process_group(w.proc)

    async def _heartbeat_loop(self):
        # the resource payload rides the heartbeat only when it CHANGED
        # since the last acked beat; idle beats are constant-size liveness
        # pings (reference: versioned deltas over bidi streams instead of
        # full resource broadcast, ray_syncer.h:88)
        last_sent = None
        down_since = None   # monotonic stamp of first failed contact
        while True:
            avail = self._reported_available()
            pending = list(self._pending_demand)
            payload = (avail, pending)
            # explicit timeout: a silently-blackholed GCS connection
            # (half-open TCP) must count toward the reconnect deadline
            # the same as an erroring one
            beat_timeout = max(10.0, cfg.heartbeat_interval_s * 10)
            try:
                if payload == last_sent:
                    await self.gcs.call("heartbeat", node_id=self.node_id,
                                        timeout=beat_timeout)
                else:
                    await self.gcs.call("heartbeat", node_id=self.node_id,
                                        available=avail, pending=pending,
                                        timeout=beat_timeout)
                    last_sent = payload
                down_since = None
            except (rpc.RpcError, rpc.ConnectionLost, asyncio.TimeoutError):
                now = time.monotonic()
                if down_since is None:
                    down_since = now
                elif now - down_since > cfg.gcs_reconnect_timeout_s:
                    # bounded retry, then die cleanly instead of spinning
                    # forever as an orphan (reference: raylet exits after
                    # gcs_rpc_server_reconnect_timeout_s, main.cc:123)
                    logger.error(
                        "GCS %s unreachable for %.0fs "
                        "(> gcs_reconnect_timeout_s=%.0fs); shutting down",
                        self.gcs_address, now - down_since,
                        cfg.gcs_reconnect_timeout_s)
                    for w in list(self.workers.values()):
                        self._kill_proc(w)
                    from ray_tpu._private import blackbox as _blackbox
                    _blackbox.record("marker", event="gcs_disconnect",
                                     gcs=self.gcs_address,
                                     down_s=round(now - down_since, 1))
                    _blackbox.seal("gcs_disconnect")
                    os._exit(1)
                logger.warning("heartbeat failed; reconnecting to GCS")
                last_sent = None
                if self.gcs_address_source:
                    fresh = self._read_gcs_address()
                    if fresh and fresh != self.gcs_address:
                        logger.info("GCS moved: %s -> %s",
                                    self.gcs_address, fresh)
                        self.gcs_address = fresh
                # bound the WHOLE reconnect attempt (dial + re-register
                # + resubscribe) by the remaining exit deadline: a
                # 20-retry backoff chain alone runs ~30s, and an
                # accepted-but-unresponsive GCS would hang the untimed
                # register call forever — either way the death check
                # above must get control back in time
                remaining = max(
                    0.5, down_since + cfg.gcs_reconnect_timeout_s
                    - time.monotonic())

                async def _redial():
                    conn = await rpc.connect(
                        self.gcs_address, handlers=self.gcs.handlers,
                        name="nm->gcs", retries=20)
                    try:
                        await conn.call(
                            "register_node", node_id=self.node_id,
                            address=self.address,
                            object_store_address=self.store_path,
                            data_plane_address=self.data_plane_address,
                            resources=self.total, labels=self.labels,
                            node_ip=rpc.node_ip_address())
                        await conn.call("subscribe", channel="NODE")
                        return conn
                    except BaseException:
                        # incl. the deadline's CancelledError: never
                        # leak a half-registered connection
                        try:
                            await conn.close()
                        except Exception:
                            pass
                        raise

                try:
                    conn = await asyncio.wait_for(_redial(),
                                                  timeout=remaining)
                    old = self.gcs
                    self.gcs = conn
                    # a half-open predecessor holds a socket + a parked
                    # reader task: close it or every reconnect cycle
                    # leaks one of each
                    try:
                        await old.close()
                    # rtlint: disable=RT004 — the replaced half-open conn
                    # is already dead; close is purely hygiene
                    except Exception:
                        pass
                except Exception:
                    # redial failed — log at debug (every heartbeat tick
                    # retries; an error-level line per tick would flood)
                    logger.debug("GCS redial failed; retrying next "
                                 "heartbeat", exc_info=True)
            await asyncio.sleep(cfg.heartbeat_interval_s)

    def _read_gcs_address(self) -> Optional[str]:
        try:
            from ray_tpu._private.store_client import store_client_for
            return store_client_for(self.gcs_address_source).read_address()
        except Exception:
            return None

    def _reported_available(self) -> Dict[str, float]:
        avail = dict(self.available)
        if self.store is not None:
            st = self.store.stats()
            avail["object_store_memory"] = max(
                0.0, float(self.store_bytes - st["bytes_in_use"]))
        return avail

    def _observability_metrics(self) -> list:
        """The node manager's own registry-shaped snapshots. Data-plane
        byte/chunk/connection counters were only visible via
        get_node_info; exporting them here lands them in /metrics AND
        the GCS time-series plane (so `query_metrics(
        "data_plane_bytes_in_total", 30, "rate")` reads live transfer
        bandwidth). Counters are cumulative — the TS ingest diffs them."""
        from ray_tpu.util.metrics import counter_snapshot, gauge_snapshot
        tags = {"node": self.node_id[:12]}
        rows = [gauge_snapshot("node_workers", len(self.workers),
                               "live worker processes", tags)]
        for phase, ms in self._launch_phase_ms.items():
            rows.append(gauge_snapshot(
                "runtime_launch_phase_ms", ms,
                "most recent actor-launch phase duration on this node "
                "(ms)", {**tags, "phase": phase}))
        if self._launches_total:
            rows.append(counter_snapshot(
                "node_actor_launches_total", self._launches_total,
                "actors launched on this node", tags))
        if self.store is not None:
            try:
                st = self.store.stats()
                rows.append(gauge_snapshot(
                    "store_bytes_in_use", st["bytes_in_use"],
                    "shared-memory arena bytes in use", tags))
                rows.append(gauge_snapshot(
                    "store_capacity_bytes", st["capacity"],
                    "shared-memory arena capacity", tags))
                rows.append(gauge_snapshot(
                    "store_objects", st["num_objects"],
                    "live objects in the arena", tags))
                # span residency + worst-stripe occupancy/fragmentation:
                # the `ray_tpu status --watch` memory pane reads these
                # from the TS plane (they previously reached only
                # get_node_info)
                sp = self.store.span_stats()
                rows.append(gauge_snapshot(
                    "store_live_spans", sp["live_spans"],
                    "live spanning (multi-stripe) objects", tags))
                rows.append(gauge_snapshot(
                    "store_span_bytes", sp["span_bytes"],
                    "bytes held by spanning objects", tags))
                rows.append(gauge_snapshot(
                    "store_stripes_claimed", sp["stripes_claimed"],
                    "stripes claimed whole by spanning objects", tags))
                util_max, hole_max = 0.0, 0
                for i in range(self.store.num_stripes()):
                    ss = self.store.stripe_stats(i)
                    if ss["capacity"]:
                        util_max = max(util_max,
                                       ss["bytes_in_use"] / ss["capacity"])
                    fr = self.store.stripe_frag(i)
                    hole_max = max(hole_max, fr["largest_hole"])
                rows.append(gauge_snapshot(
                    "store_stripe_max_utilization", round(util_max, 4),
                    "occupancy fraction of the fullest stripe", tags))
                rows.append(gauge_snapshot(
                    "store_largest_hole_bytes", hole_max,
                    "largest single free block across stripes", tags))
            except Exception:
                pass
        if self._data_server is not None:
            ds, dc = self._data_server, self._data_client
            rows += [
                counter_snapshot("data_plane_bytes_in_total", ds.bytes_in,
                                 "data-plane payload bytes received",
                                 tags),
                counter_snapshot("data_plane_chunks_in_total",
                                 ds.chunks_in,
                                 "data-plane chunks received", tags),
                counter_snapshot("data_plane_bytes_out_total",
                                 dc.bytes_out,
                                 "data-plane payload bytes sent", tags),
                counter_snapshot("data_plane_chunks_out_total",
                                 dc.chunks_out,
                                 "data-plane chunks sent", tags),
                gauge_snapshot("data_plane_active_conns", ds.active_conns,
                               "live inbound data-plane connections",
                               tags),
                gauge_snapshot("data_plane_receiving",
                               len(self._receiving),
                               "objects with an in-progress receive",
                               tags),
            ]
        return rows

    async def _metrics_push_loop(self):
        """The node manager is a daemon, not a worker — the registry
        pusher in util/metrics.py can't carry its counters. Push them
        through its own GCS connection on the same jittered cadence."""
        import random
        while True:
            await asyncio.sleep(
                cfg.metrics_push_interval_s * random.uniform(0.75, 1.25))
            try:
                await self.gcs.notify(
                    "report_metrics",
                    worker_id=f"nm:{self.node_id[:12]}",
                    node_id=self.node_id,
                    metrics=self._observability_metrics())
            # rtlint: disable=RT004 — best-effort push on a jittered
            # cadence; the heartbeat loop owns reconnect and the next
            # tick re-reports cumulative counters (no data loss)
            except Exception:
                pass

    async def _view_refresh_loop(self):
        # versioned delta pull with a periodic full resync as drift guard;
        # steady-state refreshes carry an empty delta (O(changes), not
        # O(nodes) — reference: ray_syncer.h:88)
        n = 0
        while True:
            await asyncio.sleep(cfg.view_refresh_s)
            try:
                since = None if (self._view_version is None
                                 or n % 30 == 29) else self._view_version
                resp = await self.gcs.call("get_cluster_view_delta",
                                           since=since)
                self._view_version = resp["version"]
                if "full" in resp:
                    self.cluster_view = resp["full"]
                    self._view_debits.clear()
                elif resp["delta"]:
                    self.cluster_view.update(resp["delta"])
                    for nid in resp["delta"]:
                        self._view_debits.pop(nid, None)
                n += 1
            except rpc.ConnectionLost:
                self._view_version = None     # resync after reconnect
            except rpc.RpcError:
                # older GCS without the delta handler: fall back to full
                try:
                    self.cluster_view = await self.gcs.call(
                        "get_cluster_view")
                except Exception:
                    logger.debug("cluster-view full resync failed; "
                                 "retrying next refresh", exc_info=True)
            self._expire_view_debits()
            # reap half-received transfers whose pusher died mid-stream
            # (their unsealed buffers would otherwise pin arena space)
            now = time.monotonic()
            for oid, rst in list(self._receiving.items()):
                if now - rst["t"] > 60.0:
                    if rst.get("writers"):
                        # a data-plane handler is parked inside a
                        # recv_into on this object (half-open pusher):
                        # never store.abort under an active writer — the
                        # arena region could be re-allocated while stale
                        # bytes still land in it. Close the feeding
                        # sockets instead; the woken handler aborts.
                        rst["aborted"] = True
                        for s in list(rst.get("conns") or ()):
                            try:
                                s.close()
                            except OSError:
                                pass
                        continue
                    # fail pulls parked on this receive so they retry
                    # immediately instead of waiting out their 300s cap
                    self._abort_receive(
                        oid, "stalled >60s (pusher died?); receive aborted")

    async def _reap_children_loop(self):
        while True:
            await asyncio.sleep(1.0)
            for w in list(self.workers.values()):
                if w.proc is not None and w.proc.poll() is not None \
                        and w.state != "dead":
                    await self._on_worker_death(w, f"exit code {w.proc.returncode}")
            # env-tagged workers serve exactly one pip env: evict them
            # after sitting idle so cycling through many envs can't pin
            # a process per env forever
            now = time.monotonic()
            for w in list(self._idle):
                if (w.state == "idle" and w.env_hash is not None
                        and w.idle_since
                        and now - w.idle_since
                        > cfg.pip_worker_idle_timeout_s):
                    self._idle.remove(w)
                    await self._on_worker_death(
                        w, "idle pip-env worker evicted")

    # ------------------------------------------------------ memory monitor
    @staticmethod
    def _system_memory_fraction() -> float:
        """Used fraction of system memory from /proc/meminfo (the
        reference samples the same source: src/ray/common/memory_monitor.h,
        GetLinuxMemoryBytes)."""
        total = avail = None
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal:"):
                        total = int(line.split()[1])
                    elif line.startswith("MemAvailable:"):
                        avail = int(line.split()[1])
                    if total is not None and avail is not None:
                        break
        except OSError:
            return 0.0
        if not total or avail is None:
            return 0.0
        return 1.0 - avail / total

    @staticmethod
    def _proc_rss_bytes(pid: int) -> int:
        try:
            with open(f"/proc/{pid}/statm") as f:
                return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
        except (OSError, IndexError, ValueError):
            return 0

    async def _memory_monitor_loop(self):
        """OOM defense: when system memory crosses the usage threshold,
        kill the worker with the largest RSS, preferring retriable task
        workers over actors (reference: memory_monitor.h:52 + raylet
        worker killing policies — retriable-first, group-by-owner). The
        owner sees a worker death and retries; without this the kernel
        OOM-killer may take down the whole node manager instead."""
        while True:
            interval = cfg.memory_monitor_interval_s
            if interval <= 0:
                await asyncio.sleep(5.0)
                continue
            await asyncio.sleep(interval)
            try:
                frac = self._system_memory_fraction()
                if frac < cfg.memory_usage_threshold:
                    continue
                victim = self._pick_oom_victim()
                if victim is None:
                    continue
                logger.warning(
                    "memory pressure %.1f%% > %.1f%%: killing worker %s "
                    "(state=%s, rss=%dMB)", frac * 100,
                    cfg.memory_usage_threshold * 100,
                    victim.worker_id and victim.worker_id[:12],
                    victim.state, self._proc_rss_bytes(victim.pid) >> 20)
                await self._on_worker_death(
                    victim, f"killed by memory monitor at {frac:.0%} usage")
            except Exception:
                logger.exception("memory monitor pass failed")

    def _pick_oom_victim(self) -> Optional["WorkerProc"]:
        # leased task workers first (their tasks retry); actors only if
        # nothing else is killable; never idle workers (tiny RSS, and
        # killing them frees nothing the pool won't re-create)
        for states in (("leased",), ("actor",)):
            candidates = [w for w in self.workers.values()
                          if w.state in states and w.pid]
            if candidates:
                return max(candidates, key=lambda w: self._proc_rss_bytes(w.pid))
        return None

    # ------------------------------------------- compiled-DAG channels
    # Cross-node mutable-object push (reference: raylet PushMutableObject,
    # node_manager.proto:442 + experimental_mutable_object_provider.h:30):
    # the writer's node manager fans a published version out to reader
    # nodes, whose node managers write it into a local mirror channel that
    # local readers mmap. Only refs-to-bytes travel the wire; readers stay
    # zero-copy against their node-local shm.
    def _dag_channel(self, path: str, num_readers: int, max_size: int):
        from ray_tpu.experimental.channel import Channel, node_local_path
        local = node_local_path(path, self.node_id)
        ch = self._dag_channels.get(local)
        if ch is None:
            import os as _os
            if _os.path.exists(local):
                ch = Channel(local)
            else:
                ch = Channel(local, max_size=max_size,
                             num_readers=num_readers, create=True)
            self._dag_channels[local] = ch
        return ch

    async def h_channel_push(self, conn, path: str, payload: bytes,
                             num_readers: int = 1,
                             max_size: int = 1 << 20,
                             write_timeout_s: float = 60.0):
        ch = self._dag_channel(path, num_readers, max_size)
        loop = asyncio.get_event_loop()
        # blocking writer-semaphore wait must not stall the daemon loop
        await loop.run_in_executor(None, ch.write_bytes, payload,
                                   write_timeout_s)
        return True

    async def h_channel_publish(self, conn, path: str, payload: bytes,
                                targets: Dict[str, int],
                                max_size: int = 1 << 20,
                                write_timeout_s: float = 60.0):
        """Fan one published version out to the target nodes' mirrors;
        ``targets`` maps node id -> that node's local reader count (each
        mirror is created with its own node's count). All pushes run to
        completion before any failure is raised, so mirrors don't end up
        at divergent versions behind a detached coroutine."""
        async def push(nid, readers):
            view = self.cluster_view.get(nid)
            if view is None or not view.get("alive", True):
                raise rpc.RpcError(f"channel target node {nid[:12]} gone")
            nm = await self.pool.get(view["address"])
            await nm.call("channel_push", path=path, payload=payload,
                          num_readers=readers, max_size=max_size,
                          write_timeout_s=write_timeout_s,
                          timeout=write_timeout_s + 60.0)

        nids = list(targets)
        results = await asyncio.gather(
            *(push(n, targets[n]) for n in nids),
            return_exceptions=True)
        errs = [r for r in results if isinstance(r, BaseException)]
        if errs:
            # partial success leaves mirrors one version ahead of failed
            # targets; a writer retry would then double-publish to the
            # survivors. Close the edge instead: every reader sees
            # ChannelClosed deterministically rather than diverging
            ok = [n for n, r in zip(nids, results)
                  if not isinstance(r, BaseException)]
            if ok:
                try:
                    await self.h_channel_close(conn, path=path, targets=ok)
                except Exception:
                    pass
            raise errs[0]
        return True

    async def h_channel_close(self, conn, path: str,
                              targets: Optional[List[str]] = None):
        """Close the local mirror (readers see ChannelClosed) and
        propagate to target nodes."""
        from ray_tpu.experimental.channel import Channel, node_local_path
        local = node_local_path(path, self.node_id)
        ch = self._dag_channels.pop(local, None)
        if ch is None:
            import os as _os
            if _os.path.exists(local):
                try:
                    ch = Channel(local)
                except OSError:
                    ch = None
        if ch is not None:
            try:
                ch.close()
                ch.destroy()   # drop the shm-backed file too
            except Exception:
                pass
        for nid in targets or []:
            view = self.cluster_view.get(nid)
            if view is None:
                continue
            try:
                nm = await self.pool.get(view["address"])
                await nm.call("channel_close", path=path)
            # rtlint: disable=RT004 — close fan-out to peers that may
            # already be dead; a dead peer's channel needs no close
            except Exception:
                pass
        return True

    def h_pubsub(self, conn, channel, key, payload):
        if channel == "NODE":
            if payload.get("state") == "DEAD":
                view = self.cluster_view.get(key)
                if view:
                    view["alive"] = False
            elif payload.get("state") == "ALIVE":
                self.cluster_view[key] = {
                    "total": payload["total"],
                    "available": payload["available"],
                    "alive": True, "address": payload["address"],
                    "object_store_address": payload["object_store_address"],
                    "data_plane_address": payload.get("data_plane_address"),
                    "node_ip": payload["node_ip"],
                    "labels": payload.get("labels", {})}
                self._wake_lease_waiters()

    @staticmethod
    def _tail_chunk(path: str, off: int) -> bytes:
        """Blocking file read of one log tail; runs in the default
        executor — disk IO on the owner loop would stall heartbeats and
        lease grants behind a slow volume."""
        with open(path, "rb") as f:
            f.seek(off)
            return f.read(256 * 1024)

    async def _log_monitor_loop(self):
        """Tail per-worker log files and publish new lines to the LOGS
        pubsub channel so drivers can echo them (reference: LogMonitor
        python/ray/_private/log_monitor.py:103 magic-prefix routing)."""
        loop = asyncio.get_event_loop()
        while True:
            await asyncio.sleep(cfg.log_tail_interval_s)
            for pid, files in list(self._log_files.items()):
                for i, (path, stream, off) in enumerate(files):
                    try:
                        chunk = await loop.run_in_executor(
                            None, self._tail_chunk, path, off)
                    except OSError:
                        continue
                    if not chunk:
                        continue
                    nl = chunk.rfind(b"\n")
                    if nl < 0:
                        continue
                    chunk = chunk[:nl + 1]
                    files[i] = (path, stream, off + len(chunk))
                    lines = chunk.decode("utf-8", "replace").splitlines()
                    try:
                        await self.gcs.call(
                            "publish", channel="LOGS", key=self.node_id,
                            payload={"pid": pid, "stream": stream,
                                     "ip": rpc.node_ip_address(),
                                     "lines": lines[:200]})
                    # rtlint: disable=RT004 — LOGS fan-out is best-effort
                    # by contract; the file offset already advanced, and
                    # re-publishing stale lines would duplicate output
                    except Exception:
                        pass

    # ------------------------------------------------------------ worker pool
    def _spawn_worker(self, proc_env: Optional[Dict] = None,
                      env_hash: Optional[str] = None) -> WorkerProc:
        env = dict(os.environ)
        env["RAY_TPU_NODE_ID"] = self.node_id
        # a worker never outlives its node manager, detached cluster or
        # not: arm parent-death SIGTERM regardless of how WE were started
        from ray_tpu._private.proc_util import child_env
        env = child_env(env)
        cmd = [sys.executable, "-m", "ray_tpu._private.worker_main",
               "--node-address", self.unix_address,
               "--gcs-address", self.gcs_address,
               "--store-path", self.store_path,
               "--node-id", self.node_id,
               "--session-name", self.session_name]
        if proc_env and proc_env.get("container"):
            # process-scope runtime env: the worker itself runs inside
            # the container image (reference: runtime_env/image_uri.py —
            # worker command under podman run; /tmp/raytpu bind-mount +
            # host network keep it on the node's data plane)
            from ray_tpu._private.runtime_env_plugins import \
                container_command
            cmd = container_command(proc_env, cmd, env)
        # detach stdio so workers never hold a driver/pytest pipe open;
        # per-worker log files under the session dir are tailed by
        # _log_monitor_loop and published to the driver (reference:
        # python/ray/_private/log_monitor.py:103 -> GCS pubsub -> driver)
        log_dir = f"/tmp/raytpu/{self.session_name}/logs"
        os.makedirs(log_dir, exist_ok=True)
        self._worker_seq = getattr(self, "_worker_seq", 0) + 1
        base = os.path.join(log_dir,
                            f"worker-{self.node_id[:8]}-{self._worker_seq}")
        outf = open(base + ".out", "ab")
        errf = open(base + ".err", "ab")
        proc = subprocess.Popen(cmd, env=env, stdin=subprocess.DEVNULL,
                                stdout=outf, stderr=errf,
                                start_new_session=True)
        outf.close()
        errf.close()
        self._log_files[proc.pid] = [(base + ".out", "stdout", 0),
                                     (base + ".err", "stderr", 0)]
        w = WorkerProc(proc)
        # tag at SPAWN, not grant: a container worker that registers
        # into the idle pool before its requester resumes must never be
        # adoptable as a plain "untagged" worker (and vice versa)
        w.env_hash = env_hash
        self._spawning += 1
        return w

    def h_register_worker(self, conn, worker_id: str, address: str, pid: int,
                          mode: str):
        w = None
        # match a spawned-but-unregistered proc by pid
        for cand in self.workers.values():
            if cand.proc is not None and cand.proc.pid == pid:
                w = cand
                break
        if w is None:
            w = WorkerProc()
            if mode == "worker":
                pass
        w.worker_id = worker_id
        w.address = address
        w.pid = pid
        w.conn = conn
        conn.peer_info["worker_id"] = worker_id
        self.workers[worker_id] = w
        if mode == "driver":
            w.state = "driver"
        elif w.state == "starting":
            self._spawning = max(0, self._spawning - 1)
            w.state = "idle"
            self._idle.append(w)
            self._wake_lease_waiters()
        w.registered.set()
        return {"node_id": self.node_id}

    def _on_disconnect(self, conn: rpc.Connection):
        # a pusher node that died mid-transfer drops its control
        # connection: reap every receive it was feeding right away so
        # parked pulls fail over to a surviving holder (the 60s idle
        # sweep only backstops silent stalls)
        for oid, st in list(self._receiving.items()):
            if st.get("ctrl") is conn:
                st["aborted"] = True
                if not st.get("writers"):
                    self._abort_receive(
                        oid, "pusher control connection lost mid-stream")
        wid = conn.peer_info.get("worker_id")
        if wid is None:
            return
        w = self.workers.get(wid)
        if w is not None and w.state not in ("dead", "driver"):
            asyncio.ensure_future(self._on_worker_death(w, "connection lost"))
        elif w is None or w.state == "driver":
            if w is not None:
                self.workers.pop(wid, None)
            # a submitter (driver, or a remote worker that leased here via
            # spillback) vanished: release every lease it owned, or its
            # workers stay "leased" forever and the node's resources leak
            # (reference: raylet treats client-socket disconnect as death
            # and cleans up its leases, node_manager.cc DisconnectClient)
            self._release_owned_leases(wid)

    def _release_owned_leases(self, wid: str):
        """Reclaim leases whose submitter `wid` is gone. The leased worker
        may still be EXECUTING the dead submitter's task — re-idling it
        would double-assign the process (and its chips) while the orphan
        task runs, so kill it and let the pool respawn fresh (reference:
        raylet destroys workers of a disconnected owner,
        node_manager.cc DisconnectClient). Clean shutdowns return leases
        before disconnecting, so this only costs a respawn on crashes."""
        for lid, info in list(self._leases.items()):
            if info.get("owner") == wid:
                asyncio.ensure_future(self._on_worker_death(
                    info["worker"], f"lease owner {wid[:8]} disconnected"))

    async def _on_worker_death(self, w: WorkerProc, reason: str):
        prev_state = w.state
        w.state = "dead"
        if w in self._idle:
            self._idle.remove(w)
        self.workers.pop(w.worker_id, None)
        self._kill_proc(w)
        if w.worker_id and self.gcs and not self.gcs.closed:
            # retire the dead worker's metric snapshot: its gauges
            # (queue depths, occupancy) would otherwise read as live
            # forever in the /metrics aggregate
            try:
                await self.gcs.notify("drop_worker_metrics",
                                      worker_id=w.worker_id)
            except (rpc.RpcError, rpc.ConnectionLost):
                pass
        if w.lease_id is not None:
            self._release_lease(w.lease_id, worker_dead=True)
        if w.worker_id:
            # leases this worker OWNED as a nested-task submitter
            self._release_owned_leases(w.worker_id)
        if prev_state == "actor" and w.actor_id is not None:
            try:
                await self.gcs.call("report_actor_failure", actor_id=w.actor_id,
                                    reason=f"worker died: {reason}",
                                    worker_id=w.worker_id)
            except (rpc.RpcError, rpc.ConnectionLost):
                pass

    async def _obtain_worker(self, timeout: float = 60.0,
                             env_hash: Optional[str] = None,
                             proc_env: Optional[Dict] = None) -> WorkerProc:
        """Pop an idle worker compatible with the requested runtime env
        (matching env, or a fresh untagged worker that becomes tagged),
        spawning a new process if none fits. Process-scope envs
        (container) can never adopt an untagged worker — the process was
        not started inside the image — so they match exactly or spawn."""
        while True:
            picked = fallback = None
            for w in list(self._idle):
                if w.state != "idle":
                    self._idle.remove(w)
                    continue
                if w.env_hash == env_hash:
                    picked = w          # exact env match wins
                    break
                if w.env_hash is None and fallback is None \
                        and proc_env is None:
                    fallback = w        # untagged: taggable if no match
            picked = picked or fallback
            if picked is not None:
                self._idle.remove(picked)
                picked.env_hash = env_hash or picked.env_hash
                return picked
            w = self._spawn_worker(proc_env, env_hash)
            # temporary key until registration rebinds by worker_id
            self.workers[f"spawn-{w.proc.pid}"] = w
            try:
                await asyncio.wait_for(w.registered.wait(), timeout)
            except asyncio.TimeoutError:
                self._kill_proc(w)
                raise RuntimeError("worker failed to start in time")
            self.workers.pop(f"spawn-{w.proc.pid}", None)
            if w.state == "idle" and w in self._idle:
                self._idle.remove(w)
                w.env_hash = env_hash
                return w
            # else someone else grabbed it; loop

    def _wake_lease_waiters(self):
        for fut in self._lease_waiters:
            if not fut.done():
                fut.set_result(None)
        self._lease_waiters.clear()

    # ---------------------------------------------------------------- leases
    def _bundle_pool(self, scheduling_opts: Dict) -> Optional[Dict]:
        pg_id = scheduling_opts.get("placement_group_id")
        if not pg_id:
            return None
        idx = scheduling_opts.get("placement_group_bundle_index", 0)
        return self.bundles.get((pg_id, idx))

    async def h_request_lease(self, conn, resources: Dict[str, float],
                              scheduling: Dict, worker_id: str,
                              env_hash: Optional[str] = None,
                              proc_env: Optional[Dict] = None,
                              spilled: bool = False):
        """Grant a worker lease, queue, or redirect (spillback). A request
        that has already been redirected once is grant-or-queue here — never
        redirected again (the reference's grant_or_reject spillback rule,
        preventing ping-pong on stale cluster views)."""
        self._loop_checker.check()
        deadline = time.monotonic() + cfg.lease_wait_timeout_s
        strategy = scheduling.get("strategy", "DEFAULT")
        infeasible_since = None
        while True:
            # Zombie guard: the submitter may be long gone while this
            # handler sits in the wait loop (its RPC was abandoned at
            # disconnect). Granting to a dead conn leaks the lease
            # forever — the owner-reclaim at disconnect already ran.
            if conn.closed:
                return {"status": "error", "reason": "requester gone"}
            bundle = self._bundle_pool(scheduling)
            pool_avail = bundle["available"] if bundle else self.available
            if scheduling.get("placement_group_id") and bundle is None:
                # bundle lives on another node: redirect the caller there
                spill = await self._bundle_node_address(scheduling)
                if spill is not None:
                    return {"status": "spill", "spill_to": spill}
                return {"status": "error",
                        "reason": "placement group bundle not found"}
            if bundle is None and not spilled \
                    and strategy in ("NODE_AFFINITY", "SPREAD"):
                # strategy decides the node even when we fit locally
                view = self._live_view()
                target = scheduling_pick(view, resources, scheduling,
                                         self.node_id)
                if target is None:
                    if strategy == "NODE_AFFINITY" and not scheduling.get("soft"):
                        return {"status": "error",
                                "reason": "affinity node unavailable"}
                elif target != self.node_id:
                    self._debit_view(target, resources)
                    return {"status": "spill",
                            "spill_to": view[target]["address"]}
            if scheduling_fits(pool_avail, resources) \
                    and self._chips_fit(resources):
                # chips must be claimed atomically with the float
                # accounting: _obtain_worker suspends, and a concurrent
                # request could drain the pool between check and allocate
                scheduling_sub(pool_avail, resources)
                chips = self._allocate_chips(resources)
                try:
                    w = await self._obtain_worker(env_hash=env_hash,
                                                  proc_env=proc_env)
                except RuntimeError as e:
                    self._free_chips.extend(chips)
                    scheduling_addback(pool_avail, resources)
                    return {"status": "error", "reason": str(e)}
                except BaseException:
                    # OSError from spawn, CancelledError from a dropped
                    # caller, ... — never leak the claimed chips/resources
                    self._free_chips.extend(chips)
                    scheduling_addback(pool_avail, resources)
                    raise
                if conn.closed:
                    # requester died while we were obtaining the worker:
                    # the grant reply is undeliverable — roll back
                    self._free_chips.extend(chips)
                    scheduling_addback(pool_avail, resources)
                    w.state = "idle"
                    w.idle_since = time.monotonic()
                    self._idle.append(w)
                    self._wake_lease_waiters()
                    return {"status": "error", "reason": "requester gone"}
                self._lease_seq += 1
                lease_id = f"{self.node_id[:8]}-{self._lease_seq}"
                w.state = "leased"
                w.lease_id = lease_id
                # "owner" = the submitter that requested this lease — a
                # driver or a worker running nested tasks. A submitter
                # that dies (or disconnects without returning its idle
                # leases) must not leak the resources forever.
                self._leases[lease_id] = {"worker": w, "resources": resources,
                                          "bundle": bundle, "chips": chips,
                                          "owner": worker_id}
                # spilled requests arrive over an anonymous pool conn;
                # stamping the submitter id here lets _on_disconnect
                # reclaim its leases when that conn drops
                conn.peer_info.setdefault("worker_id", worker_id)
                return {"status": "ok", "lease_id": lease_id,
                        "worker_address": w.address,
                        "node_address": self.address,
                        "node_id": self.node_id,
                        "resource_ids": {"TPU": chips} if chips else {}}
            if bundle is None and not spilled:
                # consider spillback using the cluster view
                view = self._live_view()
                target = scheduling_pick(view, resources, scheduling, self.node_id)
                if target is not None and target != self.node_id:
                    self._debit_view(target, resources)
                    return {"status": "spill",
                            "spill_to": view[target]["address"]}
                if target is None and not scheduling_feasible_anywhere(
                        view, resources, self.total):
                    # Infeasible in the current view. Keep the request queued
                    # (a node may join — the reference keeps infeasible tasks
                    # pending and surfaces them as autoscaler demand), but
                    # fail after a sustained infeasibility window.
                    if infeasible_since is None:
                        infeasible_since = time.monotonic()
                    elif (time.monotonic() - infeasible_since
                            > cfg.infeasible_grace_s):
                        return {"status": "error",
                                "reason": f"resources {resources} "
                                          f"unschedulable anywhere"}
                else:
                    infeasible_since = None
            # wait for resources to free up locally
            if time.monotonic() > deadline:
                return {"status": "error", "reason": "lease wait timed out"}
            fut = asyncio.get_event_loop().create_future()
            self._lease_waiters.append(fut)
            self._pending_demand.append(dict(resources))
            try:
                await asyncio.wait_for(fut, timeout=1.0)
            except asyncio.TimeoutError:
                pass
            finally:
                try:
                    self._pending_demand.remove(resources)
                except ValueError:
                    pass

    def _debit_view(self, target: str, resources: Dict[str, float]):
        """Optimistically debit a remote node's availability in the local
        view after deciding to spill there: a burst of lease requests
        must not all pick the same (stale-view) target before the next
        sync corrects it (reference: ClusterResourceScheduler's local
        resource-view adjustment on spillback decisions). Debits expire:
        if the spilled lease fails the GCS entry never changes, so under
        delta sync the understated availability would persist until the
        next full resync — a TTL sweep restores unconfirmed debits."""
        v = self.cluster_view.get(target)
        if v is None:
            return
        avail = dict(v.get("available") or {})
        for k, amt in (resources or {}).items():
            if k in avail:
                avail[k] = avail[k] - amt
        self.cluster_view[target] = {**v, "available": avail}
        self._view_debits.setdefault(target, []).append(
            (time.monotonic(), dict(resources or {})))

    def _expire_view_debits(self, ttl: float = 10.0):
        """Credit back optimistic debits never confirmed by a view update
        (confirmed ones are dropped when their node appears in a delta)."""
        now = time.monotonic()
        for target, recs in list(self._view_debits.items()):
            keep = []
            for t, res in recs:
                if now - t < ttl:
                    keep.append((t, res))
                    continue
                v = self.cluster_view.get(target)
                if v is not None:
                    avail = dict(v.get("available") or {})
                    for k, amt in res.items():
                        if k in avail:
                            avail[k] = avail[k] + amt
                    self.cluster_view[target] = {**v, "available": avail}
            if keep:
                self._view_debits[target] = keep
            else:
                self._view_debits.pop(target, None)

    def _live_view(self) -> Dict[str, Dict]:
        # draining nodes take no NEW work (reference: node draining in
        # cluster_task_manager — schedulable set excludes draining)
        view = {nid: v for nid, v in self.cluster_view.items()
                if v.get("alive", True) and not v.get("draining", False)}
        if self.node_id in view:
            view[self.node_id] = {**view[self.node_id],
                                  "available": self._reported_available(),
                                  "total": self.total}
        return view

    async def _bundle_node_address(self, sched: Dict) -> Optional[str]:
        pg_id = sched.get("placement_group_id")
        idx = sched.get("placement_group_bundle_index", 0)
        try:
            info = await self.gcs.call("get_placement_group", pg_id=pg_id)
        except (rpc.RpcError, rpc.ConnectionLost):
            return None
        if not info or info.get("state") != "CREATED":
            return None
        node_ids = info.get("node_ids") or []
        if idx < 0 or idx >= len(node_ids):
            return None
        target = node_ids[idx]
        if target == self.node_id:
            return None   # bundle claims to be here but isn't (race)
        view = self.cluster_view.get(target)
        return view["address"] if view and view.get("alive", True) else None

    def h_return_lease(self, conn, lease_id: str, worker_dead: bool = False):
        self._release_lease(lease_id, worker_dead)
        return True

    def _chips_fit(self, resources: Dict[str, float]) -> bool:
        return int(resources.get("TPU", 0)) <= len(self._free_chips)

    def _allocate_chips(self, resources: Dict[str, float]):
        n = int(resources.get("TPU", 0))
        if n <= 0:
            return []
        if len(self._free_chips) < n:
            # float accounting and physical chip pool diverged — never grant
            # a TPU lease without isolation
            raise RuntimeError(
                f"chip pool exhausted: need {n}, free {self._free_chips}")
        chips = self._free_chips[:n]
        del self._free_chips[:n]
        return chips

    def _release_lease(self, lease_id: str, worker_dead: bool):
        info = self._leases.pop(lease_id, None)
        if info is None:
            return
        self._free_chips.extend(info.get("chips") or [])
        pool_avail = info["bundle"]["available"] if info["bundle"] else self.available
        scheduling_addback(pool_avail, info["resources"])
        w = info["worker"]
        w.lease_id = None
        if not worker_dead and w.state == "leased":
            w.state = "idle"
            w.idle_since = time.monotonic()
            self._idle.append(w)
        self._wake_lease_waiters()

    # ---------------------------------------------------------------- actors
    # ------------------------------------------------- launch attribution
    # The node-manager slice of the actor.launch critical path: each
    # phase records a child span under the trace ctx the GCS forwarded,
    # updates the runtime_launch_phase_ms{phase} gauge, and reports the
    # phase transition so `ray_tpu status` shows where an in-flight
    # launch currently sits.
    def _launch_enter(self, lt: Optional[Dict], phase: str) -> float:
        if lt is not None:
            async def _notify():
                try:
                    await self.gcs.notify(
                        "launch_phase", actor_id=lt.get("actor_id"),
                        phase=phase, node_id=self.node_id)
                except Exception:
                    pass
            try:
                asyncio.ensure_future(_notify())
            except Exception:
                pass
        return time.time()

    def _launch_exit(self, lt: Optional[Dict], phase: str, t0: float,
                     **attrs) -> None:
        end = time.time()
        self._launch_phase_ms[phase] = round((end - t0) * 1e3, 3)
        if lt is not None:
            from ray_tpu._private import events as _events
            _events.record_complete(
                f"launch.{phase}", t0, end, category="launch",
                trace_id=lt.get("trace_id"),
                parent_span_id=lt.get("parent_span_id"),
                actor_id=lt.get("actor_id"), **attrs)

    async def h_create_actor(self, conn, spec: Dict, pg_id=None, bundle_index=0,
                             launch_trace: Optional[Dict] = None):
        lt = launch_trace if cfg.launch_trace_enabled else None
        resources = dict(spec.get("resources") or {})
        bundle = self.bundles.get((pg_id, bundle_index)) if pg_id else None
        pool_avail = bundle["available"] if bundle else self.available
        # queue for resources (leases drain within their idle timeout)
        t_phase = self._launch_enter(lt, "resource_wait")
        waited = False
        deadline = time.monotonic() + cfg.actor_resource_wait_s
        while not (scheduling_fits(pool_avail, resources)
                   and self._chips_fit(resources)):
            if conn is not None and conn.closed:
                raise RuntimeError("actor requester gone")
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"insufficient resources for actor: {resources}")
            waited = True
            fut = asyncio.get_event_loop().create_future()
            self._lease_waiters.append(fut)
            try:
                await asyncio.wait_for(fut, timeout=0.5)
            except asyncio.TimeoutError:
                pass
        self._launch_exit(lt, "resource_wait", t_phase, waited=waited)
        # claim chips atomically with the float accounting (see h_lease)
        scheduling_sub(pool_avail, resources)
        chips = self._allocate_chips(resources)
        # process-scope env (container): the actor's worker process must
        # be spawned inside the image — never adopt a plain pooled worker
        from ray_tpu._private.runtime_env_plugins import (proc_env_of,
                                                          runtime_env_hash)
        proc_env = proc_env_of(spec.get("runtime_env"))
        # same hash scheme as the task-lease path: a pip-only actor can
        # still adopt (and tag) an untagged worker, a containered one
        # matches exactly or spawns inside the image
        env_hash = runtime_env_hash(spec.get("runtime_env"))
        t_phase = self._launch_enter(lt, "worker_obtain")
        try:
            w = await self._obtain_worker(env_hash=env_hash,
                                          proc_env=proc_env)
        except BaseException:
            self._free_chips.extend(chips)
            scheduling_addback(pool_avail, resources)
            raise
        self._launch_exit(lt, "worker_obtain", t_phase,
                          worker=w.worker_id[:12])
        w.state = "actor"
        w.actor_id = spec["actor_id"]
        # register the reservation as a lease keyed off the worker so
        # _on_worker_death releases the resources on crash
        lease_id = f"actor-{spec['actor_id']}-{w.worker_id[:8]}"
        w.lease_id = lease_id
        self._leases[lease_id] = {"worker": w, "resources": resources,
                                  "bundle": bundle, "chips": chips}
        if chips:
            spec = {**spec, "accelerator_ids": {"TPU": chips}}
        if lt is not None:
            # the worker records launch.callable_init under this ctx
            spec = {**spec, "_launch_trace": {
                "trace_id": lt.get("trace_id"),
                "parent_span_id": lt.get("parent_span_id")}}
        t_phase = self._launch_enter(lt, "become_actor")
        try:
            await w.conn.call("become_actor", spec=spec)
        except (rpc.RpcError, rpc.ConnectionLost) as e:
            await self._on_worker_death(w, f"actor init failed: {e}")
            raise RuntimeError(f"actor __init__ failed: {e}")
        self._launch_exit(lt, "become_actor", t_phase)
        self._launches_total += 1
        return {"worker_address": w.address, "worker_id": w.worker_id}

    async def h_dump_stacks(self, conn):
        """This node's live Python stacks: the node manager's own
        threads plus every connected worker's (the `ray_tpu stack` fan-
        out point; reference: `ray stack` py-spy over local PIDs)."""
        from ray_tpu._private.proc_util import format_thread_stacks
        from ray_tpu.util import sanitizers
        out = {"node_manager": {"pid": os.getpid(),
                                "stacks": format_thread_stacks(),
                                "loop_stats": sanitizers.stats_snapshot()},
               "workers": {}}
        for wid, w in list(self.workers.items()):
            if w.conn is None or w.conn.closed or w.state == "dead":
                continue
            try:
                out["workers"][wid] = await asyncio.wait_for(
                    w.conn.call("dump_stacks"), 5.0)
            except Exception as e:
                out["workers"][wid] = {"error":
                                       f"{type(e).__name__}: {e}"}
        return out

    async def h_kill_worker(self, conn, worker_id: str, reason: str = ""):
        w = self.workers.get(worker_id)
        if w is None:
            return False
        w.state = "dead"
        if w.lease_id is not None:
            # releases the actor's resource reservation (lease id is the
            # actor-scoped key set in h_create_actor)
            self._release_lease(w.lease_id, worker_dead=True)
            w.lease_id = None
        if w.conn is not None and not w.conn.closed:
            try:
                await w.conn.call("exit", reason=reason, timeout=1.0)
            except Exception:
                pass
        await asyncio.sleep(0.1)
        self._kill_proc(w)
        self.workers.pop(worker_id, None)
        if w.actor_id is not None:
            # this handler removes the worker before the reaper can see
            # it die, so the actor-failure report (which drives restart
            # when max_restarts remain) must come from here
            try:
                await self.gcs.call("report_actor_failure",
                                    actor_id=w.actor_id,
                                    reason=f"worker killed: {reason}",
                                    worker_id=w.worker_id)
            except (rpc.RpcError, rpc.ConnectionLost):
                pass
        return True

    # --------------------------------------------------------------- bundles
    def h_prepare_bundle(self, conn, pg_id: str, bundle_index: int,
                         resources: Dict[str, float]):
        if not scheduling_fits(self.available, resources):
            return False
        scheduling_sub(self.available, resources)
        self.bundles[(pg_id, bundle_index)] = {
            "resources": dict(resources), "available": dict(resources),
            "committed": False}
        return True

    def h_commit_bundle(self, conn, pg_id: str, bundle_index: int):
        b = self.bundles.get((pg_id, bundle_index))
        if b is not None:
            b["committed"] = True
        return True

    def h_return_bundle(self, conn, pg_id: str, bundle_index: int):
        b = self.bundles.pop((pg_id, bundle_index), None)
        if b is not None:
            scheduling_addback(self.available, b["resources"])
            self._wake_lease_waiters()
        return True

    # ------------------------------------------------------- object transfer
    # Push-based, reference-shaped (pull_manager.h:52, push_manager.h:30):
    # a "pull" is a request for the holder to PUSH — chunks stream one-way
    # with a bounded in-flight window instead of a request/response round
    # trip per chunk, and inbound transfers pass a node-wide byte-budget
    # admission gate so gang arg feeding can't blow out store memory.

    async def _node_addr(self, node_id: str) -> str:
        view = self.cluster_view.get(node_id)
        if view is None:
            self.cluster_view = await self.gcs.call("get_cluster_view")
            view = self.cluster_view.get(node_id)
        if view is None:
            raise RuntimeError(f"unknown node {node_id}")
        return view["address"]

    async def _pull_admit(self, size: int):
        cap = max(cfg.pull_inflight_bytes, size)   # one pull always fits
        while self._pull_bytes_inflight > 0 and \
                self._pull_bytes_inflight + size > cap:
            ev = asyncio.Event()
            self._pull_waiters.append(ev)
            await ev.wait()
        self._pull_bytes_inflight += size

    def _pull_release(self, size: int):
        self._pull_bytes_inflight -= size
        while self._pull_waiters:
            self._pull_waiters.popleft().set()

    async def h_pull_object(self, conn, oid: bytes, node_id: str):
        """Ensure `oid` is in the local store, requesting a push from the
        holder node (deduplicated; admission-controlled)."""
        if self.store.contains(oid):
            return True
        inflight = self._pulls_inflight.get(oid)
        if inflight is not None:
            return await asyncio.shield(inflight)
        fut = asyncio.get_event_loop().create_future()
        self._pulls_inflight[oid] = fut
        admitted = 0
        try:
            addr = await self._node_addr(node_id)
            meta = await self.pool.call(addr, "fetch_object", oid=oid,
                                        part="meta")
            if meta is None:
                raise RuntimeError(
                    f"{oid.hex()[:16]} not on node {node_id[:12]}")
            size = meta["data_size"]
            await self._pull_admit(size)
            admitted = size
            for attempt in (0, 1):    # one retry after a reaped receive
                if self.store.contains(oid):    # re-check post-admission
                    break
                done = asyncio.get_event_loop().create_future()
                self._recv_done[oid] = done
                try:
                    await self.pool.call(addr, "request_push", oid=oid,
                                         to_node=self.node_id)
                    if not self.store.contains(oid):
                        await asyncio.wait_for(done, timeout=300)
                    break
                except Exception:
                    if attempt:
                        raise
                finally:
                    self._recv_done.pop(oid, None)
            fut.set_result(True)
            return True
        except Exception as e:
            # do NOT abort the receive state here: a concurrent broadcast
            # may own it (push_begin "have" path); stale half-received
            # buffers are reaped by the idle sweep in _view_refresh_loop
            fut.set_exception(e)
            raise
        finally:
            if admitted:
                self._pull_release(admitted)
            self._pulls_inflight.pop(oid, None)
            if not fut.done():
                fut.cancel()

    async def h_request_push(self, conn, oid: bytes, to_node: str,
                             relay: Optional[List[str]] = None,
                             bcast: bool = False):
        """Holder side: stream `oid` to `to_node` with a bounded chunk
        window. `relay` rides along for tree broadcast — the receiver
        re-broadcasts to its half of the target list after sealing;
        `bcast` tags the transfer as part of a broadcast so arrival
        instrumentation fires on every node of the tree.

        Control plane (`push_begin`) negotiates over the RPC connection;
        chunk bytes move on the binary data plane when the peer
        advertises one (striped across the adaptive stream count — see
        data_plane.adaptive_streams), falling back to msgpack chunks on
        the RPC connection for peers that predate the data-plane
        advertisement."""
        if relay:
            # chaos: a relay node dying mid-subtree (the broadcast
            # root's await must surface this and retry via survivors)
            rpc._maybe_inject_failure("relay_push")
        buf = self.store.get(oid)
        if buf is None and oid in self.spilled:
            await self.h_restore_object(conn, oid)
            buf = self.store.get(oid)
        if buf is None:
            raise RuntimeError(f"{oid.hex()[:16]} not on this node")
        try:
            addr = await self._node_addr(to_node)
            view = self.cluster_view.get(to_node) or {}
            dp_addr = view.get("data_plane_address")
            peer = await self.pool.get(addr)
            size = len(buf.data)
            status = await peer.call("push_begin", oid=oid, data_size=size,
                                     meta=bytes(buf.metadata),
                                     relay=relay or [], bcast=bcast)
            if status == "full":
                raise RuntimeError(
                    f"receiver {to_node[:12]} has no room for "
                    f"{oid.hex()[:16]} ({size} bytes)")
            if status != "ok":
                return True     # receiver already has it (or is receiving)
            use_dp = (self._data_client is not None and dp_addr
                      and cfg.data_plane_enabled and size > 0)
            from ray_tpu._private import events
            from ray_tpu._private.data_plane import (DataPlaneError,
                                                     DataPlaneUnavailable)
            with events.record_span(
                    "store.transfer", category="store",
                    object_id=oid.hex()[:16], bytes=size,
                    to_node=to_node[:12], relay=len(relay or [])) as span:
                if use_dp:
                    try:
                        stripes = await self._data_client.push(
                            dp_addr, oid, buf.data, size)
                        span.set(path="data_plane", streams=len(stripes),
                                 stripe_bytes=stripes)
                        return True
                    except DataPlaneUnavailable as e:
                        # nothing moved; the negotiated receive state is
                        # still clean — downgrade to the msgpack path
                        logger.warning(
                            "data plane to %s unavailable (%s); falling "
                            "back to msgpack chunks", to_node[:12], e)
                        use_dp = False
                    except DataPlaneError:
                        # half-delivered: tell the receiver to reap its
                        # poisoned state NOW so parked pulls retry fast
                        try:
                            await peer.notify("push_abort", oid=oid)
                        except (rpc.ConnectionLost, rpc.RpcError):
                            pass
                        raise
                span.set(path="msgpack", streams=1, stripe_bytes=[size])
                await self._push_msgpack(peer, oid, buf, size, to_node)
            return True
        finally:
            buf.close()

    async def _push_msgpack(self, peer, oid: bytes, buf, size: int,
                            to_node: str):
        """Legacy chunk path: msgpack-framed chunks on the control-plane
        RPC connection (kept as the negotiation fallback for peers that
        advertise no data plane)."""
        chunk = cfg.transfer_chunk_bytes
        window = __import__("collections").deque()
        off = 0

        def _check(accepted):
            if accepted is False:
                raise RuntimeError(
                    f"receiver {to_node[:12]} aborted transfer of "
                    f"{oid.hex()[:16]} mid-stream")

        while off < size:
            n = min(chunk, size - off)
            f = peer.call_start_nowait(
                "push_chunk", {"oid": oid, "offset": off,
                               "data": bytes(buf.data[off:off + n])})
            window.append(f)
            off += n
            if len(window) >= cfg.push_window_chunks:
                _check(await window.popleft())
        for f in window:
            _check(await f)

    def h_push_begin(self, conn, oid: bytes, data_size: int, meta: bytes,
                     relay: Optional[List[str]] = None,
                     bcast: bool = False):
        """Receiver side: allocate the arena region for an incoming push.
        Status: "ok" (send chunks), "have" (already present/receiving),
        "full" (no arena room — the pusher must error, not silently skip).

        A weight-sized incoming object lands in a SPANNING arena
        allocation transparently (store.create routes by size), so the
        data plane's recv_into writes straight into the multi-stripe
        region — zero staging copies end to end."""
        if self.store.contains(oid) or oid in self._receiving:
            return "have"
        try:
            bufs = self.store.create(oid, data_size, len(meta))
        except MemoryError:
            # arena (or span window) exhausted even after eviction: the
            # documented "full" status, not a raw remote error
            return "full"
        if bufs is None:
            return "full"
        data, meta_view = bufs
        meta_view[:] = meta
        # `ctrl` is the pusher's control connection: if the pusher node
        # dies mid-stream, its disconnect reaps this receive immediately
        # (the 60s idle sweep stays as the backstop for silent stalls)
        self._receiving[oid] = {"data": data, "remaining": data_size,
                                "relay": list(relay or []),
                                "bcast": bool(bcast), "size": data_size,
                                "t0": time.monotonic(),
                                "ctrl": conn, "t": time.monotonic()}
        if data_size == 0:
            self._finish_receive(oid)
        return "ok"

    def h_push_chunk(self, conn, oid: bytes, offset: int, data: bytes):
        st = self._receiving.get(oid)
        if st is None or st.get("aborted"):
            return False
        st["t"] = time.monotonic()
        view = st["data"][offset:offset + len(data)]
        # big chunks land through the GIL-free native copy pool
        # (RAY_TPU_PUT_COPY_THREADS) instead of a GIL-held slice assign
        if len(data) < (1 << 20) or not parallel_write(view,
                                                       memoryview(data)):
            view[:] = data
        st["remaining"] -= len(data)
        if st["remaining"] <= 0:
            # the LAST chunk's response resolves only after this node's
            # relay subtree completes — the broadcast root's await covers
            # the whole tree, and a subtree failure surfaces at the root
            return self._finish_receive(oid)
        return True

    def h_push_abort(self, conn, oid: bytes):
        """Pusher-initiated abort (its data-plane stream died half-way):
        reap the poisoned receive state so parked pulls retry at once."""
        st = self._receiving.get(oid)
        if st is None:
            return True
        st["aborted"] = True
        if not st.get("writers"):
            self._abort_receive(oid, "pusher aborted transfer mid-stream")
        return True

    def _abort_receive(self, oid: bytes, reason: str):
        """Drop a half-received object: free its unsealed arena buffer
        and fail pulls parked on it so they retry immediately."""
        self._receiving.pop(oid, None)
        try:
            self.store.abort(oid)
        except Exception:
            pass
        done = self._recv_done.get(oid)
        if done is not None and not done.done():
            done.set_exception(RuntimeError(
                f"push of {oid.hex()[:16]} failed: {reason}"))

    def _finish_receive(self, oid: bytes):
        st = self._receiving.pop(oid)
        self.store.seal(oid)
        # a transfer arrival extends the object's location set (size and
        # placement reconcile via the census; this makes the new copy
        # visible to `ray_tpu memory` within a flush, not a census tick)
        ledger.record(oid, "location_add", node_id=self.node_id,
                      size=st.get("size", 0))
        if st.get("bcast"):
            # per-node arrival instrumentation: one instant per tree
            # node, carrying bytes + the relay fan-out it now owns
            try:
                from ray_tpu._private import events
                dt = time.monotonic() - st.get("t0", st["t"])
                size = st.get("size", 0)
                events.record_instant(
                    "store.broadcast.arrival", category="store",
                    object_id=oid.hex()[:16], bytes=size,
                    recv_s=round(dt, 6),
                    gb_per_s=round(size / dt / 1e9, 3) if dt > 0 else None,
                    relay_targets=len(st["relay"]))
            except Exception:
                pass
        done = self._recv_done.get(oid)
        if done is not None and not done.done():
            done.set_result(True)
        if st["relay"]:
            relay_task = asyncio.ensure_future(
                self.h_broadcast_object(None, oid, st["relay"],
                                        bcast=st.get("bcast", False)))
            self._tasks.append(relay_task)
            relay_task.add_done_callback(
                lambda t: self._tasks.remove(t)
                if t in self._tasks else None)
            return relay_task
        return True

    async def h_broadcast_object(self, conn, oid: bytes,
                                 targets: List[str], bcast: bool = True):
        """Binomial-tree broadcast: push to the head of each half with the
        rest of that half delegated as `relay` — the source sends
        O(log n) copies instead of n (reference pattern:
        release object_store broadcast benchmarks; reference core is
        point-to-point only). A relay failure anywhere in the subtree
        propagates to this await (the completing chunk's ack defers past
        the subtree), so the broadcast root observes partial delivery
        and can retry via the surviving holders."""
        from ray_tpu._private.data_plane import binomial_split
        targets = [t for t in targets if t != self.node_id]
        pushes = [self.h_request_push(None, oid, head, relay=rest,
                                      bcast=bcast)
                  for head, rest in binomial_split(targets)]
        results = await asyncio.gather(*pushes, return_exceptions=True)
        errs = [r for r in results if isinstance(r, BaseException)]
        if errs:
            raise errs[0]
        return True

    def h_has_object(self, conn, oid: bytes):
        """Cheap holder probe (no restore side effects): does this node
        hold `oid` sealed in its arena, or spilled on its disk? The
        broadcast retry path uses it to census survivors after a relay
        death."""
        return {"in_store": self.store.contains(oid),
                "spilled": oid in self.spilled}

    async def h_fetch_object(self, conn, oid: bytes, part: str = "meta",
                             offset: int = 0, length: int = 0):
        buf = self.store.get(oid)
        if buf is None and oid in self.spilled:
            await self.h_restore_object(conn, oid)
            buf = self.store.get(oid)
        if buf is None:
            return None
        try:
            if part == "meta":
                return {"data_size": len(buf.data), "meta": buf.metadata}
            return bytes(buf.data[offset:offset + length])
        finally:
            buf.close()

    # -------------------------------------------------------- object ledger
    def _ledger_census_payload(self) -> Optional[Dict]:
        """One arena census for the GCS object ledger: every sealed
        resident object's pins, size, and stripe/span placement, plus
        the spilled set. Runs on an executor thread (object_info takes
        one stripe lock per object). The census is the ledger's
        authority for the location set — LRU eviction and crash repair
        reclaim objects without any event firing, and this reconciles
        them."""
        if self.store is None:
            return None
        now = self.store.now_sec()
        objects = {}
        for oid in self.store.list_objects():
            info = self.store.object_info(oid)
            if info is None or not info["sealed"]:
                continue
            objects[oid.hex()] = {
                "pins": info["pins"],
                "size": info["data_size"] + info["meta_size"],
                "is_span": info["is_span"], "stripe": info["stripe"],
                "age_s": max(0, now - info["ctime_sec"])}
        return {"objects": objects,
                "spilled": [o.hex() for o in self.spilled]}

    async def _ledger_census_loop(self):
        loop = asyncio.get_event_loop()
        while True:
            interval = cfg.ledger_report_interval_s
            if interval <= 0 or not ledger.enabled():
                await asyncio.sleep(5.0)
                continue
            await asyncio.sleep(interval)
            try:
                census = await loop.run_in_executor(
                    None, self._ledger_census_payload)
                if census is not None:
                    await self.gcs.notify(
                        "update_object_ledger", census=census,
                        node_id=self.node_id)
            # rtlint: disable=RT004 — best-effort census on a fixed
            # cadence; the next tick re-reports the full arena state
            # (no data loss) and the heartbeat loop owns GCS reconnect
            except Exception:
                pass

    def h_ledger_evict_hint(self, conn, oids):
        """GCS leak sweep → this node: `oids` (hex) are leaked objects
        resident here. They are NOT reclaimed eagerly — the pressured-
        stripe spill pass consumes them first, so a false positive
        costs nothing unless the arena is actually short on bytes."""
        for o in oids or ():
            try:
                self._evict_hints.add(bytes.fromhex(o))
            except ValueError:
                pass
        return True

    def _consume_evict_hints(self, pressured: set, global_hot: bool) -> int:
        """Reclaim leaked objects from pressured stripes before spilling
        healthy ones: deleting a leaked object frees bytes with no disk
        IO, and nobody can read it again (owner gone, zero pins — the
        sweep re-verifies pins here in case it was re-pinned since
        flagging). Returns bytes freed."""
        if not self._evict_hints:
            return 0
        freed = 0
        for oid in list(self._evict_hints):
            try:
                info = self.store.object_info(oid)
            except OSError:   # store closed under shutdown race
                return freed
            if info is None:
                self._evict_hints.discard(oid)   # already gone
                continue
            if info["pins"]:
                continue
            if not global_hot and not info["is_span"] \
                    and info["stripe"] not in pressured:
                continue   # the hint waits for ITS stripe's pressure
            try:
                self.store.delete(oid)
            except Exception:
                continue
            self._evict_hints.discard(oid)
            nbytes = info["data_size"] + info["meta_size"]
            freed += nbytes
            ledger.record(oid, "evicted", node_id=self.node_id,
                          reason="leak_hint", size=nbytes)
        return freed

    # --------------------------------------------------------------- spilling
    async def _spill_loop(self):
        """The node-manager arena sweep: spill LRU sealed objects to disk
        under memory pressure (reference: LocalObjectManager spill through
        IO workers, src/ray/raylet/local_object_manager.h:110; here the
        daemon itself writes — the store is directly mapped, a read is a
        memcpy) and reap orphaned never-sealed creations. Pressure is
        tracked PER STRIPE via the lock-free stripe snapshots, so one hot
        stripe gets relieved before client creates are forced into inline
        eviction — the sweep contends only with that stripe's clients."""
        loop = asyncio.get_event_loop()
        while True:
            await asyncio.sleep(cfg.spill_check_interval_s)
            try:
                # disk writes run in a thread: a multi-hundred-MB pass must
                # not stall heartbeats (reference: dedicated IO workers,
                # local_object_manager.h)
                await loop.run_in_executor(
                    None, self._spill_pass,
                    cfg.spill_high_watermark, cfg.spill_low_watermark)
                await loop.run_in_executor(
                    None, self.store.gc_unsealed)
            except Exception:
                logger.exception("spill iteration failed")

    def _spill_pass(self, trigger_frac: float = 0.8,
                    target_frac: float = 0.6) -> int:
        """One spill pass (runs on an executor thread): write sealed
        objects to disk and delete them from the store until usage drops
        below target_frac. Returns the number of objects spilled."""
        with self._spill_mutex:
            return self._spill_pass_locked(trigger_frac, target_frac)

    def _spill_pass_locked(self, trigger_frac: float,
                           target_frac: float) -> int:
        import os as _os
        st = self.store.stats()
        cap = st["capacity"] or 1
        nstripes = int(st.get("num_stripes") or 1)
        # Per-stripe accounting (lock-free snapshots): a single full
        # stripe must be relieved even while aggregate usage looks
        # healthy, or its clients' creates degrade into inline eviction.
        global_hot = st["bytes_in_use"] >= trigger_frac * cap
        if global_hot:
            pressured = list(range(nstripes))
        else:
            pressured = []
            for i in range(nstripes):
                ss = self.store.stripe_stats(i)
                if ss["bytes_in_use"] >= trigger_frac * (ss["capacity"] or 1):
                    pressured.append(i)
        if not pressured:
            return 0
        if not self._spill_remote:
            _os.makedirs(self.spill_dir, exist_ok=True)
        n = 0
        spilled_bytes = 0
        t0 = time.time()
        # leak hints first: reclaimed leaked bytes may relieve the
        # pressure before any healthy object pays disk IO
        hint_freed = self._consume_evict_hints(set(pressured), global_hot)
        # idle spanning objects next (ROADMAP item 4 leftover): spans
        # live outside every stripe's entry segment, so the per-stripe
        # walk below can NEVER reach them — before this pass a multi-GB
        # idle blob sat unspillable while its claimed stripes read as
        # 100% full forever. One span spill frees whole stripes at once,
        # so run it before any healthy per-stripe object pays disk IO.
        span_n = 0
        if global_hot:
            span_n, span_bytes = self._spill_idle_spans(
                _os, target_frac * cap)
            n += span_n
            spilled_bytes += span_bytes
            if span_n:
                st = self.store.stats()
                if st["bytes_in_use"] < target_frac * cap:
                    self._record_spill_span(t0, n, spilled_bytes, cap,
                                            len(pressured), hint_freed,
                                            span_n)
                    return n
        for si in pressured:
            for oid in self.store.list_stripe(si):
                freed = self._spill_one(oid, _os)
                if freed is None:
                    continue
                n += 1
                spilled_bytes += freed
                ss = self.store.stripe_stats(si)
                if ss["bytes_in_use"] < target_frac * (ss["capacity"] or 1):
                    break
            if global_hot:
                st = self.store.stats()
                if st["bytes_in_use"] < target_frac * cap:
                    break
        if n:
            self._record_spill_span(t0, n, spilled_bytes, cap,
                                    len(pressured), hint_freed, span_n)
        return n

    def _record_spill_span(self, t0, n, spilled_bytes, cap, stripes,
                           hint_freed, span_n):
        # the span is recorded only for passes that moved something
        # — the 1s poll's no-op passes would be pure timeline noise
        from ray_tpu._private import events
        st = self.store.stats()
        events.record_complete(
            "store.spill", t0, time.time(), category="store",
            objects=n, bytes=spilled_bytes,
            bytes_in_use=st["bytes_in_use"], capacity=cap,
            stripes=stripes, leak_hint_bytes=hint_freed,
            spans=span_n)

    def _spill_idle_spans(self, _os, target_bytes: float = 0.0):
        """Spill idle spanning objects under GLOBAL pressure: sealed,
        zero pins, older than cfg.span_spill_min_idle_s. Global-only on
        purpose — a span's claimed stripes always read as full, so
        per-stripe pressure would spill every idle span on every sweep
        even in an otherwise empty arena; global bytes_in_use (which
        counts claimed stripes whole) is the signal that the normal
        allocator actually needs those stripes back. Whole-span delete
        frees every member stripe atomically; restore reloads through
        the ordinary size-aware create (spanning route included)."""
        n = freed = 0
        try:
            spans = self.store.list_spans()
        except OSError:
            return 0, 0
        if not spans:
            return 0, 0
        rows = []
        now = self.store.now_sec()
        for oid in spans:
            info = self.store.object_info(oid)
            if info is None or not info["sealed"] or info["pins"]:
                continue
            age = now - info["ctime_sec"]
            if age < cfg.span_spill_min_idle_s:
                continue
            rows.append((age, oid))
        rows.sort(reverse=True)           # oldest (idlest) first
        for _age, oid in rows:
            b = self._spill_one(oid, _os)
            if b is None:
                continue
            n += 1
            freed += b
            if target_bytes and \
                    self.store.stats()["bytes_in_use"] < target_bytes:
                break
        return n, freed

    def _spill_one(self, oid: bytes, _os) -> Optional[int]:
        """Spill one sealed object (or drop the resident copy of an
        already-spilled one). Returns bytes newly written to disk, or
        None if the object was skipped."""
        if oid in self.spilled:
            # already on disk (a restored copy) — just drop the resident
            # copy; the native store defers the delete if clients pin it
            self.store.delete(oid)
            ledger.record(oid, "location_remove", node_id=self.node_id,
                          reason="spill_drop")
            return 0
        buf = self.store.get(oid)
        if buf is None:
            return None
        try:
            meta = bytes(buf.metadata)
            nbytes = len(buf.data) + len(meta)
            if self._spill_remote:
                from ray_tpu.util import storage as _storage
                path = _storage.join(self.spill_dir, oid.hex())
                _storage.write_bytes(
                    path, len(meta).to_bytes(8, "little") + meta
                    + bytes(buf.data))
            else:
                path = _os.path.join(self.spill_dir, oid.hex())
                with open(path, "wb") as f:
                    f.write(len(meta).to_bytes(8, "little"))
                    f.write(meta)
                    f.write(buf.data)
        finally:
            buf.close()
        self.spilled[oid] = path
        self.store.delete(oid)
        ledger.record(oid, "spilled", node_id=self.node_id, size=nbytes)
        return nbytes

    async def h_spill_now(self, conn):
        """Spill under client-side memory pressure: a worker about to
        create a large object calls this so sealed LRU objects move to
        disk instead of being evicted (reference: plasma's
        CreateRequestQueue blocks creates while LocalObjectManager spills,
        create_request_queue.h)."""
        return await asyncio.get_event_loop().run_in_executor(
            None, self._spill_pass, 0.7, 0.5)

    async def h_restore_object(self, conn, oid: bytes):
        """Restore a spilled object into the store (reference:
        spilled_object_reader.cc restore path). File IO runs on an
        executor thread."""
        return await asyncio.get_event_loop().run_in_executor(
            None, self._restore_sync, oid)

    def _restore_sync(self, oid: bytes):
        if self.store.contains(oid):
            return True
        path = self.spilled.get(oid)
        if path is None:
            return False
        from ray_tpu._private import events
        rspan = events.start_span("store.restore", category="store",
                                  object_id=oid.hex()[:16])
        try:
            if self._spill_remote:
                from ray_tpu.util import storage as _storage
                raw = _storage.read_bytes(path)
                mlen = int.from_bytes(raw[:8], "little")
                meta, data = raw[8:8 + mlen], raw[8 + mlen:]
            else:
                with open(path, "rb") as f:
                    mlen = int.from_bytes(f.read(8), "little")
                    meta = f.read(mlen)
                    data = f.read()
            # make room by spilling, not by evicting un-spilled objects
            self._spill_pass(trigger_frac=0.7, target_frac=0.5)
            bufs = self.store.create(oid, len(data), len(meta))
            if bufs is None:
                rspan.end(ok=False, bytes=0)
                return False
            dview, mview = bufs
            import numpy as np
            np.frombuffer(dview, np.uint8)[:] = np.frombuffer(
                data, np.uint8)
            if meta:
                mview[:] = meta
            self.store.seal(oid)
            rspan.end(ok=True, bytes=len(data) + len(meta))
            ledger.record(oid, "restored", node_id=self.node_id,
                          size=len(data) + len(meta))
            return True
        except Exception:
            logger.exception("restore of %s failed", oid.hex()[:16])
            rspan.end(ok=False, error="restore_failed")
            return False

    def h_free_object(self, conn, oid: bytes):
        try:
            self.store.delete(oid)
        except Exception:
            pass
        path = self.spilled.pop(oid, None)
        if path is not None:
            try:
                os.unlink(path)
            except OSError:
                pass
        ledger.record(oid, "freed", node_id=self.node_id)
        self._evict_hints.discard(oid)
        return True

    async def h_free_remote_object(self, conn, oid: bytes, node_id: str):
        if node_id == self.node_id:
            return self.h_free_object(conn, oid)
        view = self.cluster_view.get(node_id)
        if view is not None and view.get("alive", True):
            try:
                await self.pool.call(view["address"], "free_object", oid=oid)
            except Exception:
                pass
        return True

    def h_get_node_info(self, conn):
        info = {"node_id": self.node_id, "address": self.address,
                "store_path": self.store_path, "total": self.total,
                "available": self._reported_available(),
                "num_workers": len(self.workers)}
        if self.store is not None:
            st = self.store.stats()
            info["store"] = {"bytes_in_use": st["bytes_in_use"],
                             "num_objects": st.get("num_objects"),
                             "capacity": st.get("capacity"),
                             "num_stripes": st.get("num_stripes"),
                             "num_spans": st.get("num_spans"),
                             "spilled_objects": len(self.spilled),
                             "evict_hints": len(self._evict_hints)}
            # per-stripe live/free/largest-hole + span residency: the
            # machine-readable occupancy view (`ray_tpu memory --nodes`,
            # dashboard /api/memory)
            try:
                info["store"]["fragmentation"] = self.store.fragmentation()
            except Exception:
                pass
        if self._data_server is not None:
            info["data_plane"] = {
                "address": self.data_plane_address,
                "bytes_in": self._data_server.bytes_in,
                "chunks_in": self._data_server.chunks_in,
                "bytes_out": self._data_client.bytes_out,
                "chunks_out": self._data_client.chunks_out,
                "active_conns": self._data_server.active_conns,
                "receiving": len(self._receiving)}
        return info


# thin aliases so the handler bodies read clearly
scheduling_fits = scheduling.fits
scheduling_sub = scheduling.subtract
scheduling_addback = scheduling.add_back


def scheduling_pick(view, resources, sched_opts, self_node_id):
    return scheduling.pick_node(view, resources,
                                strategy=sched_opts.get("strategy", "DEFAULT"),
                                preferred_node=self_node_id,
                                strategy_args=sched_opts)


def scheduling_feasible_anywhere(view, resources, self_total):
    if scheduling.feasible(self_total, resources):
        return True
    return any(scheduling.feasible(v["total"], resources)
               for v in view.values() if v.get("alive", True))


def main():
    import argparse
    import json
    from ray_tpu._private.proc_util import set_pdeathsig_from_env
    set_pdeathsig_from_env()
    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--node-id", default=None)
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--labels", default="{}")
    parser.add_argument("--session-name", default="session")
    parser.add_argument("--store-bytes", type=int, default=0)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--gcs-address-source", default=None,
                        help="GCS persist path/URI whose published "
                             "address is re-read on reconnect (GCS-FT)")
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="[node] %(asctime)s %(levelname)s %(message)s")

    async def run():
        from ray_tpu.util import sanitizers
        sanitizers.maybe_install()
        nm = NodeManager(gcs_address=args.gcs_address, node_id=args.node_id,
                         resources=json.loads(args.resources),
                         labels=json.loads(args.labels),
                         session_name=args.session_name,
                         store_bytes=args.store_bytes, port=args.port,
                         gcs_address_source=args.gcs_address_source)
        addr = await nm.start()
        print(f"NODE_ADDRESS={addr}", flush=True)
        print(f"NODE_ID={nm.node_id}", flush=True)
        print(f"STORE_PATH={nm.store_path}", flush=True)
        # a terminated node manager must reap its workers (round-4 leak:
        # default SIGTERM killed the nm mid-flight, orphaning the pool)
        stop_evt = asyncio.Event()
        loop = asyncio.get_running_loop()
        import signal as _signal
        for s in (_signal.SIGTERM, _signal.SIGINT):
            try:
                loop.add_signal_handler(s, stop_evt.set)
            except (NotImplementedError, OSError):
                pass
        await stop_evt.wait()
        from ray_tpu._private import blackbox as _blackbox
        _blackbox.seal("sigterm")
        await asyncio.wait_for(nm.stop(), timeout=5)

    try:
        asyncio.run(run())
    except (KeyboardInterrupt, asyncio.TimeoutError):
        pass


if __name__ == "__main__":
    main()
