"""Opt-out usage stats (reference: python/ray/_private/usage/usage_lib.py —
record_extra_usage_tag :220, library usage tracking; reported by the
dashboard). Here tags accumulate in the GCS KV under the "usage" namespace;
nothing leaves the cluster (the reference's remote reporting endpoint has
no analogue), so this records *which* framework features a session used —
surfaced via `usage_report()` and the dashboard.
"""

from __future__ import annotations

import os
from typing import Dict

_local_tags: Dict[str, str] = {}


def usage_stats_enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1") != "0"


def record_library_usage(library: str) -> None:
    record_extra_usage_tag(f"library_{library}", "1")


def record_extra_usage_tag(key: str, value: str) -> None:
    if not usage_stats_enabled():
        return
    _local_tags[key] = value
    try:
        import ray_tpu
        if ray_tpu.is_initialized():
            ray_tpu._get_worker().gcs_call(
                "kv_put", ns="usage", key=key.encode(),
                value=str(value).encode(), overwrite=True)
    except Exception:
        pass


def usage_report() -> Dict[str, str]:
    """All tags recorded cluster-wide this session."""
    out = dict(_local_tags)
    try:
        import ray_tpu
        if ray_tpu.is_initialized():
            keys = ray_tpu._get_worker().gcs_call("kv_keys", ns="usage",
                                                  prefix=b"")
            for k in keys:
                v = ray_tpu._get_worker().gcs_call("kv_get", ns="usage",
                                                   key=k)
                if v is not None:
                    out[k.decode()] = v.decode()
    except Exception:
        pass
    return out
