"""GCS time-series metrics plane.

``report_metrics`` pushes used to overwrite a latest-snapshot table, so
every consumer saw only an instant — nothing in the cluster could answer
"what was p95 TTFT over the last 30s" (the signal ROADMAP's
metrics-driven autoscaling and the SLO engine both need). This module
keeps a bounded ring of ``(ts, value)`` samples per
``(metric, tags, worker)`` series, fed by the existing 2s registry
pushes (reference shape: the per-node MetricsAgent exporting OpenCensus
views to Prometheus, python/ray/_private/metrics_agent.py:483 — here the
GCS itself retains a short Prometheus-style window so queries need no
external TSDB).

Storage discipline — pushes are *cumulative* per process, the ring
stores *increments*:

- counters arrive as per-worker cumulative totals; the ring stores the
  per-push delta (a restart / counter reset is detected as a value
  decrease and the new total is taken as the delta, the Prometheus
  ``rate()`` convention);
- histograms arrive as cumulative bucket counts + sum; the ring stores
  per-push bucket deltas, so any time window's distribution is the
  elementwise sum of the deltas inside it and percentiles reconstruct
  by linear interpolation within a bucket;
- gauges are stored as-is (one sample per push).

Every query is windowed ``(now - window_s, now]``: the left edge is
exclusive, the right edge inclusive, so two adjacent windows partition
the samples exactly (tested in tests/test_metrics_plane.py).

All methods are synchronous and run on the GCS event loop; ingest is
O(samples in push), query is O(samples in window).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

PERCENTILE_AGGS = {"p50": 0.50, "p90": 0.90, "p95": 0.95, "p99": 0.99}


def _tags_key(tags) -> Tuple[Tuple[str, str], ...]:
    """Normalize a tag list/dict (msgpack delivers [[k, v], ...]) into a
    sorted hashable tuple."""
    if not tags:
        return ()
    if isinstance(tags, dict):
        items = tags.items()
    else:
        items = ((k, v) for k, v in tags)
    return tuple(sorted((str(k), str(v)) for k, v in items))


def _tags_match(series_tags: Tuple[Tuple[str, str], ...],
                want: Optional[Dict[str, str]]) -> bool:
    """Subset match: every requested tag must be present with the same
    value; extra series tags are fine."""
    if not want:
        return True
    have = dict(series_tags)
    return all(have.get(str(k)) == str(v) for k, v in want.items())


def percentile_from_buckets(boundaries: List[float], counts: List[float],
                            q: float) -> Optional[float]:
    """Reconstruct the q-quantile from bucket counts (len(boundaries)+1,
    last bucket is the +Inf overflow). Linear interpolation inside the
    containing bucket, the Prometheus ``histogram_quantile`` convention;
    observations in the overflow bucket clamp to the highest boundary
    (the reconstruction can't know how far past it they landed)."""
    total = sum(counts)
    if total <= 0:
        return None
    target = q * total
    cum = 0.0
    lo = 0.0
    for b, c in zip(boundaries, counts):
        if cum + c >= target and c > 0:
            return lo + (b - lo) * (target - cum) / c
        cum += c
        lo = b
    return boundaries[-1] if boundaries else None


def fraction_over(boundaries: List[float], counts: List[float],
                  threshold: float) -> Optional[float]:
    """Fraction of observations with value > threshold (the SLO "bad
    event" fraction). Buckets wholly above the threshold count in full;
    the bucket containing it contributes its interpolated tail."""
    total = sum(counts)
    if total <= 0:
        return None
    over = 0.0
    lo = 0.0
    for b, c in zip(boundaries, counts):
        if lo >= threshold:
            over += c
        elif b > threshold and b > lo:
            over += c * (b - threshold) / (b - lo)
        lo = b
    # overflow bucket spans (last boundary, +inf): its observations are
    # strictly above the top boundary, so they count as over whenever
    # the threshold is at or below it; past it the reconstruction can't
    # know and leaves them out
    if not boundaries or threshold <= boundaries[-1]:
        over += counts[-1]
    return min(1.0, over / total)


class _Series:
    __slots__ = ("kind", "boundaries", "samples")

    def __init__(self, kind: str, max_samples: int,
                 boundaries: Optional[List[float]] = None):
        self.kind = kind
        self.boundaries = boundaries
        # counter/gauge sample: (ts, value). histogram sample:
        # (ts, bucket_deltas, sum_delta). deque maxlen gives
        # deterministic oldest-first eviction.
        self.samples: deque = deque(maxlen=max_samples)


class MetricsTimeSeries:
    def __init__(self, retention_s: float = 600.0, max_samples: int = 600,
                 max_series: int = 4096):
        self.retention_s = float(retention_s)
        self.max_samples = int(max_samples)
        self.max_series = int(max_series)
        # name -> {(tags_key, worker_id): _Series}
        self.series: Dict[str, Dict[Tuple, _Series]] = {}
        # (name, tags_key, worker_id) -> last cumulative value
        self._last: Dict[Tuple, Any] = {}
        self.dropped_series = 0
        self._n_series = 0
        self._ingests = 0

    # ------------------------------------------------------------- ingest
    def ingest(self, worker_id: str, metrics: List[Dict],
               ts: Optional[float] = None) -> None:
        now = time.time() if ts is None else float(ts)
        for m in metrics:
            kind = m.get("type")
            name = m.get("name")
            if not name:
                continue
            if kind == "histogram":
                self._ingest_histogram(worker_id, name, m, now)
            elif kind in ("counter", "gauge"):
                self._ingest_scalar(worker_id, name, kind, m, now)
        self._ingests += 1
        if self._ingests % 128 == 0:
            self.prune(now)

    def _get_series(self, name: str, tags_key: Tuple, worker_id: str,
                    kind: str, boundaries=None) -> Optional[_Series]:
        per = self.series.setdefault(name, {})
        s = per.get((tags_key, worker_id))
        if s is None:
            if self._n_series >= self.max_series:
                self.dropped_series += 1
                return None
            s = _Series(kind, self.max_samples, boundaries)
            per[(tags_key, worker_id)] = s
            self._n_series += 1
        if boundaries is not None:
            s.boundaries = list(boundaries)
        return s

    def _ingest_scalar(self, worker_id: str, name: str, kind: str,
                       m: Dict, now: float) -> None:
        for tags, value in m.get("samples", []):
            tk = _tags_key(tags)
            s = self._get_series(name, tk, worker_id, kind)
            if s is None:
                continue
            value = float(value)
            if kind == "counter":
                lk = (name, tk, worker_id)
                prev = self._last.get(lk)
                self._last[lk] = value
                delta = value if (prev is None or value < prev) \
                    else value - prev
                if delta == 0.0 and prev is not None:
                    continue        # idle counter: don't burn ring slots
                s.samples.append((now, delta))
            else:
                s.samples.append((now, value))
            self._trim(s, now)

    def _ingest_histogram(self, worker_id: str, name: str, m: Dict,
                          now: float) -> None:
        boundaries = m.get("boundaries") or []
        for tags, counts, total in m.get("samples", []):
            tk = _tags_key(tags)
            s = self._get_series(name, tk, worker_id, "histogram",
                                 boundaries)
            if s is None:
                continue
            counts = [float(c) for c in counts]
            lk = (name, tk, worker_id)
            prev = self._last.get(lk)
            self._last[lk] = (counts, float(total))
            if prev is None or any(c < p for c, p in zip(counts, prev[0])) \
                    or len(prev[0]) != len(counts):
                deltas, dsum = counts, float(total)      # first push / reset
            else:
                deltas = [c - p for c, p in zip(counts, prev[0])]
                dsum = float(total) - prev[1]
            if not any(deltas):
                continue
            s.samples.append((now, deltas, dsum))
            self._trim(s, now)

    def _trim(self, s: _Series, now: float) -> None:
        cutoff = now - self.retention_s
        while s.samples and s.samples[0][0] < cutoff:
            s.samples.popleft()

    def prune(self, now: Optional[float] = None) -> None:
        """Drop series whose newest sample has aged out entirely (dead
        workers' gauges stop polluting list_series past retention)."""
        now = time.time() if now is None else now
        cutoff = now - self.retention_s
        for name in list(self.series):
            per = self.series[name]
            for key in list(per):
                samples = per[key].samples
                if not samples or samples[-1][0] < cutoff:
                    del per[key]
                    self._n_series -= 1
                    self._last.pop((name, key[0], key[1]), None)
            if not per:
                del self.series[name]

    def drop_worker(self, worker_id: str) -> None:
        """Forget a worker's delta state (its history ages out via
        retention; only the cumulative baselines must go so a reused id
        doesn't produce a phantom negative-delta reset)."""
        for lk in [k for k in self._last if k[2] == worker_id]:
            del self._last[lk]

    # -------------------------------------------------------------- query
    def query(self, name: str, window_s: float = 60.0, agg: str = "avg",
              tags: Optional[Dict[str, str]] = None,
              threshold: Optional[float] = None,
              now: Optional[float] = None) -> Dict:
        now = time.time() if now is None else float(now)
        window_s = min(float(window_s), self.retention_s)
        t0 = now - window_s
        out = {"name": name, "agg": agg, "window_s": window_s,
               "value": None, "n_samples": 0}
        per = self.series.get(name)
        if not per:
            return out
        matching = [((tk, wid), s) for (tk, wid), s in per.items()
                    if _tags_match(tk, tags)]
        if not matching:
            return out
        kind = matching[0][1].kind
        out["kind"] = kind

        if agg == "series":
            rows = []
            for (tk, wid), s in matching:
                samples = []
                for rec in s.samples:
                    if t0 < rec[0] <= now:
                        samples.append(
                            [rec[0], rec[1]] if len(rec) == 2
                            else [rec[0], list(rec[1]), rec[2]])
                if samples:
                    rows.append({"tags": dict(tk), "worker_id": wid,
                                 "kind": s.kind, "samples": samples})
            out["series"] = rows
            out["n_samples"] = sum(len(r["samples"]) for r in rows)
            return out

        if kind == "histogram":
            return self._query_histogram(out, matching, t0, now, agg,
                                         threshold)

        values = []
        latest = None
        for _key, s in matching:
            for ts, v in s.samples:
                if t0 < ts <= now:
                    values.append(v)
                    if latest is None or ts >= latest[0]:
                        latest = (ts, v)
        out["n_samples"] = len(values)
        if not values:
            return out
        if agg == "rate":
            out["value"] = (sum(values) / window_s if kind == "counter"
                            else None)
        elif agg == "sum":
            out["value"] = sum(values)
        elif agg == "avg":
            out["value"] = sum(values) / len(values)
        elif agg == "max":
            out["value"] = max(values)
        elif agg == "min":
            out["value"] = min(values)
        elif agg == "latest":
            out["value"] = latest[1]
        return out

    def _query_histogram(self, out: Dict, matching, t0: float, now: float,
                         agg: str, threshold: Optional[float]) -> Dict:
        boundaries: Optional[List[float]] = None
        counts: Optional[List[float]] = None
        total_sum = 0.0
        n = 0
        for _key, s in matching:
            if s.boundaries is None:
                continue
            if boundaries is None:
                boundaries = list(s.boundaries)
                counts = [0.0] * (len(boundaries) + 1)
            if s.boundaries != boundaries:
                continue        # mixed-boundary registrations don't merge
            for ts, deltas, dsum in s.samples:
                if t0 < ts <= now:
                    n += 1
                    total_sum += dsum
                    for i, d in enumerate(deltas[:len(counts)]):
                        counts[i] += d
        out["n_samples"] = n
        if counts is None:
            return out
        count_total = sum(counts)
        if agg == "buckets":
            out["value"] = count_total
            out["buckets"] = {"boundaries": boundaries, "counts": counts,
                              "sum": total_sum, "count": count_total}
            return out
        if count_total <= 0:
            return out
        if agg in PERCENTILE_AGGS:
            out["value"] = percentile_from_buckets(
                boundaries, counts, PERCENTILE_AGGS[agg])
        elif agg == "frac_over":
            if threshold is not None:
                out["value"] = fraction_over(boundaries, counts,
                                             float(threshold))
        elif agg == "rate":
            out["value"] = count_total / out["window_s"]
        elif agg == "sum":
            out["value"] = total_sum
        elif agg == "avg":
            out["value"] = total_sum / count_total
        elif agg == "max":
            # best effort: upper edge of the highest non-empty bucket
            hi = None
            lo = 0.0
            for b, c in zip(boundaries, counts):
                if c > 0:
                    hi = b
                lo = b
            if counts[-1] > 0:
                hi = lo
            out["value"] = hi
        return out

    def list_series(self, now: Optional[float] = None) -> List[Dict]:
        now = time.time() if now is None else now
        rows = []
        for name in sorted(self.series):
            per = self.series[name]
            if not per:
                continue
            kinds = {s.kind for s in per.values()}
            newest = max((s.samples[-1][0] for s in per.values()
                          if s.samples), default=None)
            rows.append({
                "name": name,
                "kind": sorted(kinds)[0] if kinds else "untyped",
                "n_series": len(per),
                "n_samples": sum(len(s.samples) for s in per.values()),
                "age_s": (round(now - newest, 3)
                          if newest is not None else None),
            })
        return rows

    def dump_series(self, window_s: float = 600.0,
                    names: Optional[List[str]] = None,
                    kinds: Optional[List[str]] = None,
                    now: Optional[float] = None) -> List[Dict]:
        """Raw sample dump (gauges by default the interesting case: the
        chrome-trace exporter renders them as counter tracks)."""
        now = time.time() if now is None else now
        t0 = now - min(float(window_s), self.retention_s)
        rows = []
        for name, per in sorted(self.series.items()):
            if names is not None and name not in names:
                continue
            for (tk, wid), s in per.items():
                if kinds is not None and s.kind not in kinds:
                    continue
                if s.kind == "histogram":
                    samples = [[ts, sum(d)] for ts, d, _ in s.samples
                               if t0 < ts <= now]
                else:
                    samples = [[ts, v] for ts, v in s.samples
                               if t0 < ts <= now]
                if samples:
                    rows.append({"name": name, "kind": s.kind,
                                 "tags": dict(tk), "worker_id": wid,
                                 "samples": samples})
        return rows

    def stats(self) -> Dict:
        return {"n_series": self._n_series,
                "dropped_series": self.dropped_series,
                "n_names": len(self.series)}
