"""Binary identifiers for jobs, nodes, workers, actors, tasks and objects.

Design follows the reference's embedded-lineage scheme (reference:
src/ray/common/id.h): an ObjectID embeds the TaskID that creates it plus a
return/put index, so ownership and lineage can be derived from the id itself.

Sizes: JobID 4, ActorID 12 (job + unique), TaskID 16 (actor/job prefix +
unique), ObjectID 20 (task + 4-byte index), NodeID/WorkerID 16 random.
"""

from __future__ import annotations

import os
import struct

# Measured on this kernel: os.urandom is vDSO-fast (~0.5us for 12
# bytes) and beats a lock+counter scheme — keep the plain random ids.
def _unique(n: int) -> bytes:
    return os.urandom(n)


def span_id() -> str:
    """Unique span id for trace propagation."""
    return os.urandom(8).hex()

JOB_ID_LEN = 4
ACTOR_ID_LEN = 12
TASK_ID_LEN = 16
OBJECT_ID_LEN = 20
UNIQUE_LEN = 16

NIL_JOB = b"\x00" * JOB_ID_LEN
NIL_ACTOR = b"\x00" * ACTOR_ID_LEN
NIL_TASK = b"\x00" * TASK_ID_LEN
NIL_OBJECT = b"\x00" * OBJECT_ID_LEN
NIL_ID = b"\x00" * UNIQUE_LEN


def random_unique() -> bytes:
    return os.urandom(UNIQUE_LEN)


def job_id_from_int(n: int) -> bytes:
    return struct.pack(">I", n)


def new_task_id(job_id: bytes, actor_id: bytes = NIL_ACTOR) -> bytes:
    """TaskID = 4-byte job | 12 unique (normal task) or actor-scoped."""
    if actor_id != NIL_ACTOR:
        return actor_id[:ACTOR_ID_LEN] + _unique(TASK_ID_LEN - ACTOR_ID_LEN)
    return job_id + _unique(TASK_ID_LEN - JOB_ID_LEN)


def new_actor_id(job_id: bytes) -> bytes:
    return job_id + os.urandom(ACTOR_ID_LEN - JOB_ID_LEN)


def actor_creation_task_id(actor_id: bytes) -> bytes:
    """Deterministic TaskID for an actor's creation task."""
    return actor_id + b"\xff" * (TASK_ID_LEN - ACTOR_ID_LEN)


def object_id_for_return(task_id: bytes, index: int) -> bytes:
    """Return values use indices 1..n; index 0 is reserved."""
    return task_id + struct.pack(">I", index)


def object_id_for_put(task_id: bytes, put_index: int) -> bytes:
    """Puts use the high bit of the index word to avoid collision."""
    return task_id + struct.pack(">I", 0x80000000 | put_index)


def task_id_of_object(object_id: bytes) -> bytes:
    return object_id[:TASK_ID_LEN]


def job_id_of(any_id: bytes) -> bytes:
    return any_id[:JOB_ID_LEN]


def new_node_id() -> bytes:
    return os.urandom(UNIQUE_LEN)


def new_worker_id() -> bytes:
    return os.urandom(UNIQUE_LEN)


def new_placement_group_id(job_id: bytes) -> bytes:
    return job_id + os.urandom(UNIQUE_LEN - JOB_ID_LEN)


def hex_short(b: bytes) -> str:
    return b.hex()[:12]
