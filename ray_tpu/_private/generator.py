"""Streaming generator returns (reference: num_returns="streaming" and
ObjectRefGenerator, python/ray/_raylet.pyx:281; the executor reports
items incrementally via ReportGeneratorItemReturns,
src/ray/protobuf/core_worker.proto:400).

TPU-runtime design: the item stream rides the SAME PARTIAL-frame
mechanism every other streamed reply uses (rpc.py call_start_parts) —
one request out (`push_task_streaming`), one PARTIAL back per yielded
item, one final RESPONSE when the generator is exhausted. Each PARTIAL
carries the item's encoded return (inline wire bytes or a shm location),
which the owner materializes into a brand-new owned ObjectRef.
Backpressure is executor-side: at most K unconsumed items in flight
(cfg.streaming_backpressure / per-call override); the consumer's
`next()` sends a consumption ack that opens the window.

Divergence from the reference (stated): streaming tasks don't retry and
their items aren't lineage-reconstructable — a lost item fails the read
instead of re-running the generator (re-running a partially-consumed
generator would double its side effects; the reference only supports
this for idempotent tasks, and Data/Serve here never rely on it).
"""

from __future__ import annotations

import asyncio
from collections import deque
from typing import Optional

from ray_tpu._private.object_ref import ObjectRef


class ObjectRefGenerator:
    """Iterator over the ObjectRefs of a streaming task's yields.

    Sync iteration (driver threads)::

        gen = f.options(num_returns="streaming").remote()
        for ref in gen:              # blocks until the next item lands
            block = ray_tpu.get(ref)

    Async iteration (inside async actors): ``async for ref in gen``.

    ``completed()`` returns the task-level ref that resolves to the item
    count once the generator finishes (and carries the task error if the
    generator itself failed to start).
    """

    def __init__(self, core, task_id: bytes, completed_ref: ObjectRef):
        self._core = core
        self._task_id = task_id
        self._completed_ref = completed_ref
        self._items: deque = deque()
        self._event = asyncio.Event()
        self._done = False
        self._exc: Optional[BaseException] = None
        self._consumed = 0
        self._closed = False
        self._worker_address: Optional[str] = None   # set at dispatch

    # ---------------------------------------------------------- loop side
    def _push(self, ref: ObjectRef) -> None:
        self._items.append(ref)
        self._event.set()

    def _finish(self) -> None:
        self._done = True
        self._event.set()

    def _fail(self, exc: BaseException) -> None:
        self._exc = exc
        self._done = True
        self._event.set()

    # ------------------------------------------------------ consumer side
    async def _next_async(self, timeout: Optional[float] = None):
        deadline = None if timeout is None else \
            asyncio.get_event_loop().time() + timeout
        while True:
            if self._items:
                ref = self._items.popleft()
                self._consumed += 1
                self._core._gen_send_ack(self)
                return ref
            if self._done:
                if self._exc is not None:
                    raise self._exc
                raise StopAsyncIteration
            self._event.clear()
            if deadline is None:
                await self._event.wait()
            else:
                remaining = deadline - asyncio.get_event_loop().time()
                if remaining <= 0:
                    raise TimeoutError("ObjectRefGenerator.next timed out")
                try:
                    await asyncio.wait_for(self._event.wait(), remaining)
                except asyncio.TimeoutError:
                    raise TimeoutError(
                        "ObjectRefGenerator.next timed out") from None

    def __aiter__(self):
        return self

    def __anext__(self):
        return self._next_async()

    def _guard_loop_thread(self):
        import threading
        if threading.get_ident() == getattr(
                self._core, "_loop_thread_ident", None):
            raise RuntimeError(
                "blocking ObjectRefGenerator iteration on the core event "
                "loop thread would deadlock; use `async for ref in gen`")

    def __iter__(self):
        return self

    def __next__(self):
        self._guard_loop_thread()
        try:
            return asyncio.run_coroutine_threadsafe(
                self._next_async(), self._core.loop).result()
        except StopAsyncIteration:
            raise StopIteration from None

    def next(self, timeout: Optional[float] = None) -> ObjectRef:
        """Blocking next with an explicit timeout."""
        self._guard_loop_thread()
        try:
            return asyncio.run_coroutine_threadsafe(
                self._next_async(timeout), self._core.loop).result()
        except StopAsyncIteration:
            raise StopIteration from None

    def completed(self) -> ObjectRef:
        return self._completed_ref

    def close(self) -> None:
        """Stop the producer and drop any unconsumed items (the owner
        frees them; the executor's generator is closed)."""
        if self._closed:
            return
        self._closed = True
        import threading
        if threading.get_ident() == getattr(
                self._core, "_loop_thread_ident", None):
            asyncio.ensure_future(self._core._gen_close_async(self))
            return
        asyncio.run_coroutine_threadsafe(
            self._core._gen_close_async(self), self._core.loop).result()

    def __del__(self):
        # best-effort: dropping the generator cancels the producer
        if not self._closed and not self._done:
            try:
                self._closed = True
                self._core.loop.call_soon_threadsafe(
                    lambda: asyncio.ensure_future(
                        self._core._gen_close_async(self)))
            except Exception:
                pass
