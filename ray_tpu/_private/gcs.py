"""GCS — global control service: the cluster-singleton control plane.

Re-design of the reference's GCS server (reference:
src/ray/gcs/gcs_server/gcs_server.cc, gcs_actor_manager.h:308,
gcs_node_manager.h, gcs_placement_group_manager, gcs_kv_manager.h,
gcs_health_check_manager.h:39). One asyncio process holding authoritative
tables for nodes, actors, jobs, placement groups and a namespaced KV store,
plus pubsub. Differences from the reference, deliberately:

- Transport is the symmetric rpc.py protocol; node managers hold one
  persistent bidirectional connection, so GCS→raylet commands (create actor
  worker, reserve bundle) and pubsub pushes reuse it — no per-service gRPC
  stubs or long-poll channels (reference: src/ray/pubsub/publisher.h:296).
- The cluster resource view (the reference's ray_syncer gossip,
  src/ray/common/ray_syncer/ray_syncer.h:88) is piggybacked on node
  heartbeats and re-broadcast to subscribers on change.
- Persistence is a pluggable snapshot (in-memory by default; file-backed
  snapshot for GCS restart) instead of Redis.

Actor scheduling follows the reference's GCS-based actor scheduling: GCS
picks the node (shared policy in scheduling.py) and leases a worker from
that node's manager (reference: gcs_actor_scheduler.cc:49).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional

from ray_tpu._private import rpc
from ray_tpu._private import scheduling
from ray_tpu._private.config import cfg

logger = logging.getLogger(__name__)

# tunables live in config.py (health_check_interval_s,
# node_death_timeout_s, gcs_snapshot_interval_s)

# Actor states (reference: src/ray/protobuf/gcs.proto ActorTableData.ActorState)
DEPENDENCIES_UNREADY = "DEPENDENCIES_UNREADY"
PENDING_CREATION = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


class GcsServer:
    def __init__(self, port: int = 0, session_name: str = "session",
                 persist_path: Optional[str] = None):
        self.port = port
        self.session_name = session_name
        self.persist_path = persist_path
        # persistence behind the store-client interface (reference:
        # gcs/store_client/ — file impl today, external URI impl for
        # off-node durability; see _private/store_client.py)
        from ray_tpu._private.store_client import store_client_for
        self.store_client = store_client_for(
            persist_path, fsync=cfg.gcs_wal_fsync) if persist_path else None
        self._wal_actors: set = set()   # actors whose full row is in WAL
        self.address: Optional[str] = None

        self.kv: Dict[str, Dict[bytes, bytes]] = {}          # namespace -> {k: v}
        self.nodes: Dict[str, Dict] = {}                     # node_id -> info
        self._view_version = 0        # bumps on any node-state change
        self.node_conns: Dict[str, rpc.Connection] = {}      # node_id -> conn
        self.actors: Dict[str, Dict] = {}                    # actor_id -> table row
        self.named_actors: Dict[tuple, str] = {}             # (ns, name) -> actor_id
        self.jobs: Dict[int, Dict] = {}
        self.placement_groups: Dict[str, Dict] = {}
        self.subscribers: Dict[str, set] = {}                # channel -> {conn}
        self._next_job_id = 1
        self._death_checker: Optional[asyncio.Task] = None
        self._pending_actor_queue: List[str] = []
        # task-event sink: ring buffer of merged per-task rows (reference:
        # GcsTaskManager, src/ray/gcs/gcs_server/gcs_task_manager.h:86)
        self.task_events: Dict[str, Dict] = {}
        # runtime events (the flight recorder's kind="runtime_event"
        # rows) share this ring with task rows; sized so a burst of
        # engine-step spans can't evict the whole task timeline
        self.max_task_events = 20000
        # object-lifetime ledger (ledger.py write side): one provenance
        # row per object id, merged from worker event deltas and node-
        # manager arena censuses. Bounded like the task-event ring —
        # freed rows retire first, then the oldest.
        self.object_ledger: Dict[str, Dict] = {}
        self._ledger_exited: set = set()   # worker ids that died/exited
        self._ledger_sweeper: Optional[asyncio.Task] = None
        # cluster-wide prefix routing (serve/disagg.py): one compact trie
        # summary per serving replica (top-K path fingerprints), expiring
        # after cfg.prefix_summary_ttl_s so dead replicas fall out of
        # routing within one TTL without explicit teardown
        self.prefix_summaries: Dict[str, Dict] = {}
        # serve tenancy (serve/fleet.py TenantAdmission): per-tenant
        # concurrency quota + DRR weight rows; the "__default__" tenant
        # row moves the fleet-wide defaults. Proxies refresh ~5s.
        self.tenant_quotas: Dict[str, Dict] = {}
        # cluster-edge shared fair share (serve/fleet.py
        # QuotaLeaseClient): one lease row per ingress proxy. The epoch
        # bumps on every membership change (join/leave/expire/revoke) so
        # a proxy can tell its rate shares are stale from the renew
        # response alone; burn deltas pushed on the renew cadence feed
        # the per-tenant cluster burn totals. Leases are ephemeral —
        # never snapshotted; proxies re-acquire after a GCS restart.
        self.quota_leases: Dict[str, Dict] = {}
        self.quota_lease_epoch = 1
        self.tenant_burn: Dict[str, int] = {}
        # time-series plane over report_metrics pushes (metrics_ts.py):
        # bounded per-series rings answering windowed queries (rate /
        # percentiles) that the latest-snapshot table cannot
        from ray_tpu._private.metrics_ts import MetricsTimeSeries
        self.metrics_ts = MetricsTimeSeries(
            retention_s=cfg.metrics_ts_retention_s,
            max_samples=cfg.metrics_ts_max_samples,
            max_series=cfg.metrics_ts_max_series)
        # hot-path observability: per-handler latency/inflight, pubsub
        # deliver latency, table sizes (gcs_obs.py); self-ingested into
        # metrics_ts on the _obs_loop cadence as worker "gcs"
        from ray_tpu._private.gcs_obs import GcsObservability
        self.obs = GcsObservability(self)
        self._obs_task: Optional[asyncio.Task] = None
        # in-flight launch table (node managers notify launch_phase):
        # actor_id -> {name, phase, phase_ts, started, node_id} — the
        # `ray_tpu status` control-plane pane reads this; completed
        # launches retire into the _launch_done ring
        self.launches: Dict[str, Dict] = {}
        self._launch_done: List[Dict] = []
        self.server = None

    # ------------------------------------------------------------- lifecycle
    async def start(self) -> str:
        handlers = {
            "kv_put": self.h_kv_put, "kv_get": self.h_kv_get,
            "kv_del": self.h_kv_del, "kv_exists": self.h_kv_exists,
            "kv_keys": self.h_kv_keys,
            "register_node": self.h_register_node,
            "get_system_config": lambda conn: cfg.snapshot(),
            "heartbeat": self.h_heartbeat,
            "drain_node": self.h_drain_node,
            "get_all_nodes": self.h_get_all_nodes,
            "get_cluster_view": self.h_get_cluster_view,
            "get_cluster_view_delta": self.h_get_cluster_view_delta,
            "register_job": self.h_register_job,
            "finish_job": self.h_finish_job,
            "get_all_jobs": self.h_get_all_jobs,
            "create_actor": self.h_create_actor,
            "get_actor_info": self.h_get_actor_info,
            "get_named_actor": self.h_get_named_actor,
            "list_named_actors": self.h_list_named_actors,
            "get_all_actors": self.h_get_all_actors,
            "report_actor_failure": self.h_report_actor_failure,
            "kill_actor": self.h_kill_actor,
            "subscribe": self.h_subscribe,
            "publish": self.h_publish,
            "create_placement_group": self.h_create_placement_group,
            "remove_placement_group": self.h_remove_placement_group,
            "get_placement_group": self.h_get_placement_group,
            "get_all_placement_groups": self.h_get_all_placement_groups,
            "add_task_events": self.h_add_task_events,
            "report_metrics": self.h_report_metrics,
            "get_metrics": self.h_get_metrics,
            "drop_worker_metrics": self.h_drop_worker_metrics,
            "query_metrics": self.h_query_metrics,
            "list_metric_series": self.h_list_metric_series,
            "dump_metric_series": self.h_dump_metric_series,
            "list_task_events": self.h_list_task_events,
            "update_object_ledger": self.h_update_object_ledger,
            "list_object_ledger": self.h_list_object_ledger,
            "ledger_sweep": self.h_ledger_sweep,
            "ledger_stats": self.h_ledger_stats,
            "publish_prefix_summary": self.h_publish_prefix_summary,
            "get_prefix_summaries": self.h_get_prefix_summaries,
            "set_tenant_quota": self.h_set_tenant_quota,
            "get_tenant_quotas": self.h_get_tenant_quotas,
            "quota_lease_acquire": self.h_quota_lease_acquire,
            "quota_lease_renew": self.h_quota_lease_renew,
            "quota_lease_release": self.h_quota_lease_release,
            "quota_lease_revoke": self.h_quota_lease_revoke,
            "quota_lease_status": self.h_quota_lease_status,
            "launch_phase": self.h_launch_phase,
            "control_plane_stats": self.h_control_plane_stats,
            "ping": lambda conn: "pong",
        }
        handlers = self.obs.wrap_handlers(handlers)
        self.server = rpc.Server(handlers, name="gcs")
        self.server.on_disconnect = self._on_disconnect
        self._load_snapshot()
        self._replay_wal()
        self.address = await self.server.listen_tcp("0.0.0.0", self.port)
        if self.store_client is not None:
            # discovery channel: raylets that lose the GCS re-read this
            # before reconnecting, so a restart on a new port/host heals
            # the cluster (reference: raylets re-resolve the GCS address
            # from Redis under GCS-FT)
            try:
                self.store_client.write_address(self.address)
            except Exception:
                logger.exception("address publish failed")
        # restart path: snapshot-restored actors that never reached ALIVE
        # must be (re)scheduled — the client's retried create_actor hits
        # the idempotent early-return and will wait forever otherwise
        # (reference: gcs_actor_manager.cc reconstruct-on-restart)
        for aid, row in self.actors.items():
            if row["state"] in (PENDING_CREATION, RESTARTING,
                                DEPENDENCIES_UNREADY):
                asyncio.ensure_future(self._schedule_actor(aid, delay=1.0))
        self._death_checker = asyncio.ensure_future(self._check_node_deaths())
        if cfg.ledger_sweep_interval_s > 0:
            self._ledger_sweeper = asyncio.ensure_future(
                self._ledger_sweep_loop())
        self._snapshot_task = None
        if self.persist_path:
            self._snapshot_task = asyncio.ensure_future(self._snapshot_loop())
        if cfg.gcs_obs_interval_s > 0:
            self._obs_task = asyncio.ensure_future(self._obs_loop())
        logger.info("GCS listening at %s", self.address)
        return self.address

    # ------------------------------------------------------- persistence
    # File-backed snapshot instead of the reference's Redis store client
    # (reference: RedisStoreClient redis_store_client.h:106, gcs_init_data
    # rebuild on restart). Nodes re-register via their heartbeat reconnect
    # path; KV / jobs / named actors / PGs / actor specs survive.
    def _snapshot_state(self) -> Dict:
        return {
            "kv": {ns: list(t.items()) for ns, t in self.kv.items()},
            "jobs": self.jobs,
            "next_job_id": self._next_job_id,
            "named_actors": [[ns, name, aid] for (ns, name), aid
                             in self.named_actors.items()],
            "actors": {aid: dict(row) for aid, row in self.actors.items()},
            "placement_groups": self.placement_groups,
            "tenant_quotas": self.tenant_quotas,
        }

    def _save_snapshot(self):
        if self.store_client is None:
            return
        import msgpack
        # msgpack, not json: actor specs and KV entries embed raw bytes
        # (function-table ids, pickled args) that json would stringify
        self.store_client.save_snapshot(
            msgpack.packb(self._snapshot_state(), use_bin_type=True))
        # the snapshot covers everything the WAL recorded: start it fresh
        self._wal_actors.clear()
        self.store_client.wal_reset()

    def _log_op(self, op: str, data: Dict):
        """Append one mutation to the write-ahead log. Closes the
        durability window between periodic snapshots: a GCS that dies
        right after registering an actor/PG/KV entry replays it on
        restart (reference: every mutation goes through the Redis store
        client synchronously, redis_store_client.h:106).

        Durability grade: the file store flush()es by default — survives
        a process kill, NOT a host crash (cfg.gcs_wal_fsync upgrades
        that); external URI stores are snapshot-interval only (see
        ExternalStoreClient)."""
        if self.store_client is None or not self.store_client.wal_enabled:
            return
        import msgpack
        try:
            self.store_client.wal_append(
                msgpack.packb([op, data], use_bin_type=True))
        except Exception:
            logger.exception("WAL append failed")

    def _replay_wal(self):
        import msgpack
        if self.store_client is None:
            return
        n = 0
        try:
            for rec in self.store_client.wal_records():
                op, data = msgpack.unpackb(rec, raw=False,
                                           strict_map_key=False)
                self._apply_op(op, data)
                n += 1
        except Exception:
            logger.exception("WAL replay failed at record %d", n)
        if n:
            logger.info("replayed %d WAL records", n)

    def _apply_op(self, op: str, d: Dict):
        if op == "kv_put":
            self.kv.setdefault(d["ns"], {})[d["key"]] = d["value"]
        elif op == "kv_del":
            self.kv.get(d["ns"], {}).pop(d["key"], None)
        elif op == "actor":
            self.actors[d["aid"]] = d["row"]
        elif op == "actor_delta":
            # spec-less transition record; ignore if the full row never
            # made it (snapshot already covers it then)
            if d["aid"] in self.actors:
                self.actors[d["aid"]].update(d["delta"])
        elif op == "named_actor":
            self.named_actors[(d["ns"], d["name"])] = d["aid"]
        elif op == "job":
            self.jobs[int(d["job_id"])] = d["row"]
            self._next_job_id = max(self._next_job_id, int(d["job_id"]) + 1)
        elif op == "pg":
            self.placement_groups[d["pg_id"]] = d["row"]

    def _load_snapshot(self):
        import msgpack
        if self.store_client is None:
            return
        try:
            raw = self.store_client.load_snapshot()
            if raw is None:
                return
            snap = msgpack.unpackb(raw, raw=False, strict_map_key=False)
        except Exception:
            logger.exception("snapshot load failed; starting fresh")
            return
        for ns, pairs in snap.get("kv", {}).items():
            self.kv[ns] = {k: v for k, v in pairs}
        self.jobs = {int(k): v for k, v in snap.get("jobs", {}).items()}
        self._next_job_id = snap.get("next_job_id", 1)
        for ns, name, aid in snap.get("named_actors", []):
            self.named_actors[(ns, name)] = aid
        self.actors.update(snap.get("actors", {}))
        self.placement_groups.update(snap.get("placement_groups", {}))
        self.tenant_quotas.update(snap.get("tenant_quotas", {}))
        logger.info("restored GCS snapshot from %s (%d kv namespaces, "
                    "%d actors)", self.persist_path, len(self.kv),
                    len(self.actors))

    async def _snapshot_loop(self):
        while True:
            await asyncio.sleep(cfg.gcs_snapshot_interval_s)
            try:
                self._save_snapshot()
            except Exception:
                logger.exception("snapshot save failed")

    async def _obs_loop(self):
        """Self-ingest the control plane's own metrics (same pattern as
        the ledger sweep's gauges): the GCS is its own metrics agent,
        pushing as worker 'gcs' with no pusher thread."""
        while True:
            await asyncio.sleep(cfg.gcs_obs_interval_s)
            try:
                self.obs.refresh_config()
                self.h_report_metrics(None, "gcs", self.obs.metric_rows())
            except Exception:
                logger.exception("gcs self-metrics export failed")

    async def stop(self):
        if self._death_checker:
            self._death_checker.cancel()
        if self._ledger_sweeper:
            self._ledger_sweeper.cancel()
        if self._obs_task:
            self._obs_task.cancel()
            self._obs_task = None
        if getattr(self, "_snapshot_task", None):
            self._snapshot_task.cancel()
            self._snapshot_task = None
            try:
                self._save_snapshot()   # final flush of acknowledged state
            except Exception:
                logger.exception("final snapshot failed")
        await self.server.close()

    def _on_disconnect(self, conn: rpc.Connection):
        for subs in self.subscribers.values():
            subs.discard(conn)
        node_id = conn.peer_info.get("node_id")
        if node_id and self.node_conns.get(node_id) is conn:
            # grace: let heartbeat timeout decide (node manager may reconnect)
            info = self.nodes.get(node_id)
            if info is not None:
                info["last_heartbeat"] = min(
                    info["last_heartbeat"],
                    time.monotonic() - cfg.node_death_timeout_s / 2)

    # ------------------------------------------------------------------- kv
    def h_kv_put(self, conn, ns: str, key: bytes, value: bytes,
                 overwrite: bool = True):
        table = self.kv.setdefault(ns, {})
        if not overwrite and key in table:
            return False
        table[key] = value
        self._log_op("kv_put", {"ns": ns, "key": key, "value": value})
        return True

    def h_kv_get(self, conn, ns: str, key: bytes):
        return self.kv.get(ns, {}).get(key)

    def h_kv_del(self, conn, ns: str, key: bytes):
        existed = self.kv.get(ns, {}).pop(key, None) is not None
        if existed:
            self._log_op("kv_del", {"ns": ns, "key": key})
        return existed

    def h_kv_exists(self, conn, ns: str, key: bytes):
        return key in self.kv.get(ns, {})

    def h_kv_keys(self, conn, ns: str, prefix: bytes = b""):
        return [k for k in self.kv.get(ns, {}) if k.startswith(prefix)]

    # ---------------------------------------------------------------- nodes
    def h_register_node(self, conn, node_id: str, address: str,
                        object_store_address: str, resources: Dict[str, float],
                        labels: Dict[str, str], node_ip: str,
                        data_plane_address: Optional[str] = None):
        conn.peer_info["node_id"] = node_id
        self.node_conns[node_id] = conn
        self.nodes[node_id] = {
            "node_id": node_id,
            "address": address,
            "object_store_address": object_store_address,
            # raw-stream socket for bulk object chunks; None for nodes
            # that predate (or disabled) the binary data plane — peers
            # then fall back to msgpack chunks on `address`
            "data_plane_address": data_plane_address,
            "node_ip": node_ip,
            "total": dict(resources),
            "available": dict(resources),
            "labels": labels,
            "alive": True,
            "draining": False,
            "last_heartbeat": time.monotonic(),
            "start_time": time.time(),
        }
        self._touch_node(node_id)
        logger.info("node %s registered at %s (%s)", node_id[:12], address, resources)
        self._publish("NODE", node_id, {"state": "ALIVE", **_node_public(self.nodes[node_id])})
        # gcs_ts lets the registering node measure its wall-clock offset
        # vs the GCS (local - gcs, half-RTT error bound) — the black box
        # records it so cross-node stitches can de-skew
        return {"node_id": node_id, "cluster_view": self._cluster_view(),
                "view_version": self._view_version,
                "system_config": cfg.snapshot(),
                "gcs_ts": time.time()}

    def h_heartbeat(self, conn, node_id: str,
                    available: Optional[Dict[str, float]] = None,
                    total: Optional[Dict[str, float]] = None,
                    pending: Optional[List[Dict[str, float]]] = None):
        """available=None is a liveness-only beat: the node's resource view
        is unchanged since its last report, so the payload stays constant
        size under idle (reference: versioned delta gossip instead of full
        resource broadcast, src/ray/common/ray_syncer/ray_syncer.h:88)."""
        info = self.nodes.get(node_id)
        if info is None or not info["alive"]:
            return {"ok": False, "reason": "unknown or dead node"}
        info["last_heartbeat"] = time.monotonic()
        changed = False
        if available is not None and available != info["available"]:
            info["available"] = available
            changed = True
        if pending is not None and pending != info.get("pending_demand", []):
            info["pending_demand"] = pending
            changed = True
        if total is not None and total != info["total"]:
            info["total"] = total
            changed = True
        if changed:
            self._touch_node(node_id)
        return {"ok": True}

    def h_drain_node(self, conn, node_id: str):
        info = self.nodes.get(node_id)
        if info:
            info["draining"] = True
            self._touch_node(node_id)
        return True

    def h_get_all_nodes(self, conn):
        return [_node_public(n) for n in self.nodes.values()]

    def h_get_cluster_view(self, conn):
        return self._cluster_view()

    def _touch_node(self, node_id: str):
        self._view_version += 1
        info = self.nodes.get(node_id)
        if info is not None:
            info["_ver"] = self._view_version

    def h_get_cluster_view_delta(self, conn, since: Optional[int] = None):
        """Versioned view sync (reference: RaySyncer, ray_syncer.h:88).
        since=None -> full view; otherwise only nodes whose state changed
        after `since`. Payload is empty when nothing changed."""
        if since is None:
            return {"version": self._view_version,
                    "full": self._cluster_view()}
        # build entries only for changed nodes: with N pollers at steady
        # state this handler must be O(changes), not O(nodes)
        delta = {nid: _node_view(n) for nid, n in self.nodes.items()
                 if n.get("_ver", 0) > since}
        return {"version": self._view_version, "delta": delta}

    def _cluster_view(self) -> Dict[str, Dict]:
        return {nid: _node_view(n) for nid, n in self.nodes.items()}

    async def _check_node_deaths(self):
        while True:
            await asyncio.sleep(cfg.health_check_interval_s)
            now = time.monotonic()
            for node_id, info in list(self.nodes.items()):
                if info["alive"] and now - info["last_heartbeat"] > cfg.node_death_timeout_s:
                    await self._mark_node_dead(node_id, "heartbeat timeout")

    async def _mark_node_dead(self, node_id: str, reason: str):
        info = self.nodes.get(node_id)
        if info is None or not info["alive"]:
            return
        info["alive"] = False
        self._touch_node(node_id)
        logger.warning("node %s dead: %s", node_id[:12], reason)
        self.node_conns.pop(node_id, None)
        self._drop_node_metrics(node_id)
        self._publish("NODE", node_id, {"state": "DEAD", "reason": reason,
                                        **_node_public(info)})
        # fail/restart actors that lived there
        for actor_id, row in list(self.actors.items()):
            if row.get("node_id") == node_id and row["state"] in (ALIVE, PENDING_CREATION):
                await self._handle_actor_failure(
                    actor_id, f"node {node_id[:12]} died: {reason}")

    # ----------------------------------------------------------------- jobs
    def h_register_job(self, conn, driver_address: str, metadata: Dict):
        job_id = self._next_job_id
        self._next_job_id += 1
        self.jobs[job_id] = {"job_id": job_id, "driver_address": driver_address,
                             "metadata": metadata, "start_time": time.time(),
                             "finished": False}
        self._log_op("job", {"job_id": job_id, "row": self.jobs[job_id]})
        return job_id

    def h_finish_job(self, conn, job_id: int):
        job = self.jobs.get(job_id)
        if job:
            job["finished"] = True
            job["end_time"] = time.time()
            self._log_op("job", {"job_id": job_id, "row": job})
        self._publish("JOB", str(job_id), {"state": "FINISHED"})
        return True

    def h_get_all_jobs(self, conn):
        return list(self.jobs.values())

    # --------------------------------------------------------------- actors
    async def h_create_actor(self, conn, spec: Dict):
        """Register + schedule an actor. spec: actor_id, job_id, name,
        namespace, resources, max_restarts, scheduling (strategy dict),
        owner_address, definition (bytes key into KV function table),
        init_args (serialized), options."""
        actor_id = spec["actor_id"]
        # idempotent on actor_id: clients retry through GCS reconnects, and
        # a retried registration (reply lost, or the actor was already in
        # the restart snapshot) must not double-schedule or trip the
        # named-actor check (reference: GcsActorManager dedupes
        # RegisterActor on actor id, gcs_actor_manager.cc)
        if actor_id in self.actors and self.actors[actor_id]["state"] != DEAD:
            return True
        name = spec.get("name")
        ns = spec.get("namespace", "default")
        if name:
            existing = self.named_actors.get((ns, name))
            if (existing is not None and existing != actor_id
                    and self.actors[existing]["state"] != DEAD):
                raise ValueError(f"actor name {name!r} already taken in namespace {ns!r}")
            self.named_actors[(ns, name)] = actor_id
            self._log_op("named_actor", {"ns": ns, "name": name,
                                         "aid": actor_id})
        row = {
            "actor_id": actor_id, "spec": spec, "state": PENDING_CREATION,
            "name": name, "namespace": ns, "node_id": None, "address": None,
            "restarts_remaining": spec.get("max_restarts", 0),
            "death_cause": None, "num_restarts": 0,
        }
        self.actors[actor_id] = row
        asyncio.ensure_future(self._schedule_actor(actor_id))
        return True

    # ------------------------------------------------- launch attribution
    # One actor.launch root span per launch, decomposed phase-by-phase:
    # the GCS owns placement; the node manager and worker record their
    # phases (resource_wait / worker_obtain / become_actor /
    # callable_init) as children under the trace ctx forwarded with the
    # create_actor call. The in-flight table feeds `ray_tpu status`.
    def _launch_begin(self, actor_id: str, spec: Dict) -> Optional[Dict]:
        if not cfg.launch_trace_enabled:
            return None
        ent = self.launches.get(actor_id)
        if ent is None:
            from ray_tpu._private import events as _events
            now = time.time()
            ent = self.launches[actor_id] = {
                "actor_id": actor_id,
                "name": (spec.get("name")
                         or spec.get("class_name") or "actor"),
                "trace_id": _events.new_trace_id(),
                "root_span_id": _events.new_span_id(),
                "started": now, "phase": "placement", "phase_ts": now,
                "retries": 0, "node_id": None,
            }
        return ent

    def _launch_phase(self, ent: Optional[Dict], phase: str,
                      ts: Optional[float] = None):
        if ent is not None:
            ent["phase"] = phase
            ent["phase_ts"] = time.time() if ts is None else ts

    def _launch_span_row(self, ent: Dict, name: str, start: float,
                         end: float, parent: Optional[str],
                         **attrs) -> None:
        """One launch-phase span row straight into this GCS's own
        task-event ring (category 'launch' -> its own timeline track)."""
        from ray_tpu._private import events as _events
        span_id = _events.new_span_id()
        self.h_add_task_events(None, [{
            "task_id": span_id, "kind": "runtime_event",
            "type": "RUNTIME_EVENT", "event_kind": "span",
            "name": name, "category": "launch",
            "trace_id": ent["trace_id"], "span_id": span_id,
            "parent_span_id": parent, "node_id": ent.get("node_id"),
            "worker_id": "gcs",
            "attrs": {"actor_id": ent["actor_id"],
                      "actor": ent["name"], **attrs},
            "state": "RUNNING", "ts": start,
        }, {"task_id": span_id, "state": "FINISHED", "ts": end}])

    def _launch_finish(self, actor_id: str, ok: bool,
                       error: Optional[str] = None):
        ent = self.launches.pop(actor_id, None)
        if ent is None:
            return
        now = time.time()
        total_ms = (now - ent["started"]) * 1e3
        # the root span row reuses the pre-minted root_span_id so the
        # children recorded remotely already parent under it
        from ray_tpu._private import events as _events  # noqa: F401
        self.h_add_task_events(None, [{
            "task_id": ent["root_span_id"], "kind": "runtime_event",
            "type": "RUNTIME_EVENT", "event_kind": "span",
            "name": "actor.launch", "category": "launch",
            "trace_id": ent["trace_id"], "span_id": ent["root_span_id"],
            "parent_span_id": None, "node_id": ent.get("node_id"),
            "worker_id": "gcs",
            "attrs": {"actor_id": actor_id, "actor": ent["name"],
                      "ok": ok, "retries": ent["retries"],
                      "total_ms": round(total_ms, 3),
                      **({"error": error} if error else {})},
            "state": "RUNNING", "ts": ent["started"],
        }, {"task_id": ent["root_span_id"],
            "state": "FINISHED" if ok else "FAILED", "ts": now}])
        self._launch_done.append({
            "actor_id": actor_id, "actor": ent["name"], "ok": ok,
            "total_ms": round(total_ms, 3), "finished": now})
        del self._launch_done[:-100]

    async def h_launch_phase(self, conn, actor_id: str, phase: str,
                             ts: Optional[float] = None,
                             node_id: Optional[str] = None):
        """Node managers report phase transitions of an in-flight launch
        (resource_wait / worker_obtain / become_actor) so the status
        pane shows WHERE a slow launch currently sits."""
        ent = self.launches.get(actor_id)
        if ent is not None:
            self._launch_phase(ent, phase, ts)
            if node_id:
                ent["node_id"] = node_id
        return True

    def h_control_plane_stats(self, conn, top_n: int = 3):
        """One-call snapshot for the `ray_tpu status` control-plane
        pane: hottest handlers by p99, pubsub backlog, in-flight
        launches with their current phase, black boxes on disk."""
        now = time.time()
        inflight = [{"actor_id": e["actor_id"][:12], "actor": e["name"],
                     "phase": e["phase"],
                     "phase_age_s": round(now - e["phase_ts"], 3),
                     "age_s": round(now - e["started"], 3),
                     "node_id": (e.get("node_id") or "")[:12]}
                    for e in self.launches.values()]
        inflight.sort(key=lambda e: -e["age_s"])
        done = self._launch_done[-20:]
        from ray_tpu._private import blackbox as _bb
        return {
            "handlers": self.obs.top_handlers(top_n),
            "rpc_inflight": self.obs.inflight_total,
            "pubsub": {"backlog": self.obs.pubsub_pending,
                       "delivered": self.obs.pubsub_delivered,
                       "failed": self.obs.pubsub_failed},
            "launches": inflight,
            "launches_done": len(self._launch_done),
            "recent_launch_ms": [d["total_ms"] for d in done],
            "blackboxes": _bb.count_boxes(self._blackbox_dir()),
        }

    def _blackbox_dir(self) -> str:
        return (cfg.blackbox_dir
                or f"/tmp/raytpu/{self.session_name}/blackbox")

    async def _schedule_actor(self, actor_id: str, delay: float = 0.0):
        if delay:
            await asyncio.sleep(delay)
        row = self.actors.get(actor_id)
        if row is None or row["state"] == DEAD:
            return
        spec = row["spec"]
        launch = self._launch_begin(actor_id, spec)
        attempt_t0 = time.time()
        req = dict(spec.get("resources") or {})
        sched = spec.get("scheduling") or {}
        pg_id = sched.get("placement_group_id")
        target = None
        if pg_id:
            pg = self.placement_groups.get(pg_id)
            if pg is None or pg["state"] != "CREATED":
                row["state"] = DEAD
                row["death_cause"] = f"placement group {pg_id} not ready"
                self._persist_actor(actor_id)
                self._publish("ACTOR", actor_id, _actor_public(row))
                self._launch_finish(actor_id, ok=False,
                                    error="placement group not ready")
                return
            idx = sched.get("placement_group_bundle_index", 0)
            if idx < 0:
                idx = 0
            target = pg["node_ids"][idx]
        else:
            alive = {nid: n for nid, n in self.nodes.items() if n["alive"]
                     and not n["draining"]}
            target = scheduling.pick_node(
                alive, req, strategy=sched.get("strategy", "DEFAULT"),
                strategy_args=sched)
        if target is None:
            # infeasible right now: retry until resources appear
            if launch is not None:
                launch["retries"] += 1
            asyncio.ensure_future(self._schedule_actor(actor_id, delay=0.5))
            return
        node_conn = self.node_conns.get(target)
        if node_conn is None or node_conn.closed:
            if launch is not None:
                launch["retries"] += 1
            asyncio.ensure_future(self._schedule_actor(actor_id, delay=0.2))
            return
        launch_trace = None
        if launch is not None:
            launch["node_id"] = target
            self._launch_span_row(
                launch, "launch.placement", attempt_t0, time.time(),
                launch["root_span_id"], node=target[:12],
                strategy=sched.get("strategy", "DEFAULT"),
                pg=bool(pg_id))
            self._launch_phase(launch, "node_create")
            launch_trace = {"trace_id": launch["trace_id"],
                            "parent_span_id": launch["root_span_id"],
                            "actor_id": actor_id}
        try:
            result = await node_conn.call("create_actor", spec=spec,
                                          pg_id=pg_id,
                                          bundle_index=sched.get(
                                              "placement_group_bundle_index", 0),
                                          launch_trace=launch_trace)
        except (rpc.RpcError, rpc.ConnectionLost) as e:
            logger.warning("actor %s creation on %s failed: %s",
                           actor_id[:12], target[:12], e)
            await self._handle_actor_failure(actor_id,
                                             f"creation failed: {e}",
                                             from_scheduler=True)
            return
        row = self.actors.get(actor_id)
        if row is None or row["state"] == DEAD:
            return
        row["state"] = ALIVE
        row["node_id"] = target
        row["address"] = result["worker_address"]
        row["worker_id"] = result["worker_id"]
        self._persist_actor(actor_id)
        self._publish("ACTOR", actor_id, _actor_public(row))
        self._launch_finish(actor_id, ok=True)

    async def _handle_actor_failure(self, actor_id: str, reason: str,
                                    from_scheduler: bool = False):
        row = self.actors.get(actor_id)
        if row is None or row["state"] == DEAD:
            return
        if row["state"] == RESTARTING and not from_scheduler:
            # a restart is already scheduled (kill/death race); the
            # scheduler's own failure reports must pass through or a
            # failed re-creation would strand the actor in RESTARTING
            return
        if row["restarts_remaining"] != 0:
            if row["restarts_remaining"] > 0:
                row["restarts_remaining"] -= 1
            row["num_restarts"] += 1
            row["state"] = RESTARTING
            row["address"] = None
            row["node_id"] = None
            self._persist_actor(actor_id)
            self._publish("ACTOR", actor_id, _actor_public(row))
            asyncio.ensure_future(self._schedule_actor(actor_id))
        else:
            row["state"] = DEAD
            row["death_cause"] = reason
            self._persist_actor(actor_id)
            self._publish("ACTOR", actor_id, _actor_public(row))
            self._launch_finish(actor_id, ok=False, error=reason)

    def h_get_actor_info(self, conn, actor_id: str):
        row = self.actors.get(actor_id)
        return _actor_public(row) if row else None

    def h_get_named_actor(self, conn, name: str, namespace: str = "default"):
        actor_id = self.named_actors.get((namespace, name))
        if actor_id is None:
            return None
        row = self.actors[actor_id]
        if row["state"] == DEAD:
            return None
        return _actor_public(row)

    def h_list_named_actors(self, conn, namespace: Optional[str] = None):
        out = []
        for (ns, name), aid in self.named_actors.items():
            if namespace is not None and ns != namespace:
                continue
            if self.actors.get(aid, {}).get("state") != DEAD:
                out.append({"name": name, "namespace": ns, "actor_id": aid})
        return out

    def h_get_all_actors(self, conn):
        return [_actor_public(r) for r in self.actors.values()]

    async def h_report_actor_failure(self, conn, actor_id: str,
                                     reason: str,
                                     worker_id: Optional[str] = None):
        row = self.actors.get(actor_id)
        if (row is not None and worker_id is not None
                and row.get("worker_id") not in (None, worker_id)):
            # stale report about a PREVIOUS incarnation's worker (e.g. the
            # kill_worker death race): the current instance is healthy
            return True
        await self._handle_actor_failure(actor_id, reason)
        return True

    async def h_kill_actor(self, conn, actor_id: str, no_restart: bool = True):
        """no_restart=False kills the running instance but lets the
        normal restart path bring it back if max_restarts remain
        (reference: ray.kill(no_restart=False) semantics,
        gcs_actor_manager.cc DestroyActor vs RestartActor)."""
        row = self.actors.get(actor_id)
        if row is None:
            return False
        node_conn = self.node_conns.get(row.get("node_id"))
        if no_restart or row["restarts_remaining"] == 0:
            row["restarts_remaining"] = 0
            row["state"] = DEAD
            row["death_cause"] = "ray_tpu.kill"
            if row.get("name"):
                self.named_actors.pop((row["namespace"], row["name"]), None)
            self._persist_actor(actor_id)
            self._publish("ACTOR", actor_id, _actor_public(row))
            self._launch_finish(actor_id, ok=False, error="killed")
        if node_conn is not None and not node_conn.closed:
            try:
                await node_conn.call("kill_worker", worker_id=row.get("worker_id"),
                                     reason="actor killed")
            except (rpc.RpcError, rpc.ConnectionLost):
                pass
        # no_restart=False: the worker's death report (incarnation-aware)
        # drives the restart; restarting here directly would double-
        # schedule a PENDING_CREATION actor or one whose kill RPC failed
        return True

    # ---------------------------------------------------------- task events
    def h_add_task_events(self, conn, events: List[Dict]):
        for ev in events:
            tid = ev["task_id"]
            row = self.task_events.get(tid)
            if row is None:
                if len(self.task_events) >= self.max_task_events:
                    # drop oldest (dict preserves insertion order)
                    self.task_events.pop(next(iter(self.task_events)))
                row = self.task_events[tid] = {"task_id": tid,
                                               "state_times": {}}
            order = {"PENDING": 0, "RUNNING": 1, "FINISHED": 2, "FAILED": 2}
            for k, v in ev.items():
                if k == "state":
                    row["state_times"][v] = ev.get("ts", time.time())
                    # events from caller and executor arrive out of order;
                    # state only moves forward
                    if order.get(v, 0) >= order.get(row.get("state"), -1):
                        row["state"] = v
                elif k != "ts":
                    row[k] = v
        return True

    def h_list_task_events(self, conn, limit: int = 1000,
                           job_id: Optional[int] = None,
                           kind: Optional[str] = None,
                           category: Optional[str] = None):
        """kind=None returns everything (the unified timeline);
        kind="task" excludes runtime events; kind="runtime_event"
        returns only the flight recorder's rows, optionally filtered by
        subsystem category ("engine", "store", "data", "serve")."""
        out = []
        for row in reversed(list(self.task_events.values())):
            if job_id is not None and row.get("job_id") != job_id:
                continue
            row_kind = row.get("kind") or "task"
            if kind is not None and row_kind != kind:
                continue
            if category is not None and row.get("category") != category:
                continue
            out.append(row)
            if len(out) >= limit:
                break
        return out

    # -------------------------------------------------------- object ledger
    # Provenance table keyed by object id (reference: `ray memory` joins
    # the plasma store view with per-worker reference tables; the state
    # observability tables keep object rows in the GCS the same way).
    # Writers: worker put/free event deltas (ledger.py ring) and node-
    # manager arena censuses. The census is authoritative for the
    # location set — LRU eviction and crash repair emit no event.
    _LEDGER_ROW_DEFAULTS = {
        "owner": None, "owner_worker": None, "creator_worker": None,
        "creator_task": None, "size": 0, "meta_size": 0,
        "is_span": False, "stripe": None,
        "created_ts": None, "sealed_ts": None, "spilled_ts": None,
        "restored_ts": None, "evicted_ts": None, "freed_ts": None,
        "owner_refs": None, "leaked": False, "leak_ts": None,
        "last_seq": 0, "dropped": 0,
    }

    def _ledger_row(self, oid: str) -> Dict:
        led = self.object_ledger
        row = led.get(oid)
        if row is None:
            if len(led) >= cfg.ledger_max_entries:
                # retire a freed row if one sits near the front; else the
                # oldest row goes (bounded-ring discipline, task-event
                # sink style)
                victim = None
                for n, k in enumerate(led):
                    if led[k].get("freed_ts") is not None:
                        victim = k
                        break
                    if n >= 64:
                        break
                led.pop(victim if victim is not None else next(iter(led)))
            row = led[oid] = {"object_id": oid, "locations": {},
                              **self._LEDGER_ROW_DEFAULTS}
        return row

    def h_update_object_ledger(self, conn, records: Optional[List[Dict]] = None,
                               census: Optional[Dict] = None,
                               node_id: Optional[str] = None,
                               worker_id: Optional[str] = None):
        """Merge per-process lifecycle deltas and/or one node's arena
        census into the object_ledger table. Records apply in seq order
        per object (stale duplicates from a re-flushed batch are
        idempotent); the census reconciles presence + pins for
        `node_id`, including silent removals (LRU eviction)."""
        for rec in records or ():
            self._ledger_apply(rec, node_id, worker_id)
        if census is not None and node_id:
            self._ledger_census(census, node_id)
        return True

    def _ledger_apply(self, rec: Dict, node_id: Optional[str],
                      worker_id: Optional[str]):
        ev = rec.get("event")
        ts = rec.get("ts")
        if ts is None:     # 0.0 is a valid (test-pinned) timestamp
            ts = time.time()
        if ev == "worker_exit":
            wid = rec.get("worker_id") or worker_id
            if wid:
                self._ledger_exited.add(wid)
            return
        oid = rec.get("object_id")
        if not oid:
            return
        row = self._ledger_row(oid)
        row["last_seq"] = max(row["last_seq"], int(rec.get("seq") or 0))
        if rec.get("dropped"):
            row["dropped"] += int(rec["dropped"])
        node = rec.get("node_id") or node_id
        if ev == "created":
            row["size"] = int(rec.get("size") or row["size"])
            row["meta_size"] = int(rec.get("meta_size") or row["meta_size"])
            row["owner"] = rec.get("owner") or row["owner"]
            row["owner_worker"] = (rec.get("owner_worker") or worker_id
                                   or row["owner_worker"])
            row["creator_worker"] = (rec.get("owner_worker") or worker_id
                                     or row["creator_worker"])
            row["creator_task"] = rec.get("task_id") or row["creator_task"]
            if rec.get("is_span"):
                row["is_span"] = True
            row["created_ts"] = row["created_ts"] or ts
            if rec.get("sealed"):
                row["sealed_ts"] = row["sealed_ts"] or ts
            if node:
                row["locations"].setdefault(node, {"pins": 0, "since": ts})
        elif ev == "sealed":
            row["sealed_ts"] = row["sealed_ts"] or ts
        elif ev == "location_add":
            if node:
                row["locations"].setdefault(node, {"pins": 0, "since": ts})
        elif ev == "location_remove":
            if node:
                row["locations"].pop(node, None)
        elif ev == "spilled":
            row["spilled_ts"] = ts
            if node:
                row["locations"].pop(node, None)
                row.setdefault("spilled_on", [])
                if node not in row["spilled_on"]:
                    row["spilled_on"].append(node)
        elif ev == "restored":
            row["restored_ts"] = ts
            if node:
                row["locations"].setdefault(node, {"pins": 0, "since": ts})
                if node in row.get("spilled_on", ()):
                    row["spilled_on"].remove(node)
        elif ev == "evicted":
            row["evicted_ts"] = ts
            if node:
                row["locations"].pop(node, None)
        elif ev == "freed":
            row["freed_ts"] = ts
            row["leaked"] = False
            if node:
                row["locations"].pop(node, None)
        elif ev == "refs":
            row["owner_refs"] = rec.get("refs")

    def _ledger_census(self, census: Dict, node_id: str):
        now = time.time()
        present = census.get("objects") or {}
        for oid, info in present.items():
            row = self._ledger_row(oid)
            loc = row["locations"].setdefault(node_id, {"pins": 0,
                                                        "since": now})
            loc["pins"] = int(info.get("pins") or 0)
            if not row["size"]:
                row["size"] = int(info.get("size") or 0)
            if info.get("is_span"):
                row["is_span"] = True
            if row.get("stripe") is None and info.get("stripe") is not None:
                row["stripe"] = int(info["stripe"])
            if row["sealed_ts"] is None and info.get("sealed", True):
                # pre-ledger or foreign-writer object: census discovers
                # it; age then counts from first sighting, not creation
                row["sealed_ts"] = now - float(info.get("age_s") or 0.0)
        for oid, row in self.object_ledger.items():
            if node_id in row["locations"] and oid not in present:
                row["locations"].pop(node_id, None)
                if row["freed_ts"] is None and row["spilled_ts"] is None:
                    # silent removal: LRU eviction / crash repair
                    row["evicted_ts"] = now
        spilled = census.get("spilled") or ()
        for oid in spilled:
            row = self.object_ledger.get(oid)
            if row is not None:
                row.setdefault("spilled_on", [])
                if node_id not in row["spilled_on"]:
                    row["spilled_on"].append(node_id)

    def h_list_object_ledger(self, conn, limit: int = 1000,
                             node_id: Optional[str] = None,
                             leaked: Optional[bool] = None,
                             live_only: bool = False):
        """Dump provenance rows, newest-first. Filters: node_id (appears
        in the row's location set or spilled_on), leaked=True (flagged
        by the sweep), live_only (resident somewhere, not freed)."""
        out = []
        for row in reversed(list(self.object_ledger.values())):
            if node_id is not None and node_id not in row["locations"] \
                    and node_id not in row.get("spilled_on", ()):
                continue
            if leaked is not None and bool(row.get("leaked")) != leaked:
                continue
            if live_only and (row["freed_ts"] is not None
                              or not row["locations"]):
                continue
            out.append(row)
            if len(out) >= limit:
                break
        return out

    def h_ledger_stats(self, conn):
        leaked = [r for r in self.object_ledger.values() if r.get("leaked")]
        return {"entries": len(self.object_ledger),
                "exited_workers": len(self._ledger_exited),
                "leaked_objects": len(leaked),
                "leaked_bytes": sum(
                    (r.get("size") or 0) * max(1, len(r["locations"]))
                    for r in leaked)}

    async def h_ledger_sweep(self, conn, now: Optional[float] = None):
        """One leak-detector pass: a sealed, resident object with zero
        pins whose owner exited (or reports zero references), older than
        cfg.ledger_leak_after_s, is flagged. Exports store_leaked_bytes /
        store_leaked_objects gauges, emits a `store.leak` runtime-event
        instant per newly flagged object, and sends the holding nodes an
        eviction hint their pressured-stripe sweep consumes first.
        `now` pins the clock for deterministic tests."""
        now = time.time() if now is None else now
        leak_after = cfg.ledger_leak_after_s
        leaked_bytes = 0
        leaked_count = 0
        newly: List[Dict] = []
        for row in self.object_ledger.values():
            if row["freed_ts"] is not None or not row["locations"]:
                row["leaked"] = False
                continue
            sealed = row["sealed_ts"]
            if sealed is None:
                continue   # unsealed orphans are gc_unsealed's problem
            if any(int(l.get("pins") or 0) > 0
                   for l in row["locations"].values()):
                row["leaked"] = False
                continue
            owner_gone = (row.get("owner_worker") in self._ledger_exited
                          if row.get("owner_worker") else False)
            if not owner_gone and row.get("owner_refs") != 0:
                continue
            if now - sealed < leak_after:
                continue
            nbytes = (row.get("size") or 0) * max(1, len(row["locations"]))
            leaked_bytes += nbytes
            leaked_count += 1
            if not row.get("leaked"):
                row["leaked"] = True
                row["leak_ts"] = now
                newly.append(row)
        try:
            from ray_tpu.util.metrics import gauge_snapshot
            self.h_report_metrics(None, "gcs-ledger", [
                gauge_snapshot("store_leaked_bytes", float(leaked_bytes),
                               "bytes held by leaked objects (sealed, "
                               "ownerless, unpinned past "
                               "ledger_leak_after_s)"),
                gauge_snapshot("store_leaked_objects", float(leaked_count),
                               "objects currently flagged as leaked"),
            ], ts=now)
        except Exception:
            logger.exception("leak gauge export failed")
        hints: Dict[str, List[str]] = {}
        for row in newly:
            import os as _os
            self.h_add_task_events(None, [{
                "task_id": f"leak-{row['object_id'][:16]}-{int(now)}",
                "kind": "runtime_event", "event_kind": "instant",
                "type": "RUNTIME_EVENT", "name": "store.leak",
                "category": "store", "state": "RUNNING", "ts": now,
                "trace_id": _os.urandom(16).hex(),
                "span_id": _os.urandom(8).hex(), "parent_span_id": None,
                "node_id": next(iter(row["locations"]), None),
                "worker_id": "gcs-ledger",
                "attrs": {"object_id": row["object_id"],
                          "bytes": row.get("size") or 0,
                          "owner": row.get("owner"),
                          "owner_worker": row.get("owner_worker"),
                          "age_s": round(now - row["sealed_ts"], 3),
                          "nodes": list(row["locations"])}}])
            for node in row["locations"]:
                hints.setdefault(node, []).append(row["object_id"])
        for node, oids in hints.items():
            node_conn = self.node_conns.get(node)
            if node_conn is not None and not node_conn.closed:
                asyncio.ensure_future(
                    self._safe_evict_hint(node_conn, oids))
        return {"leaked_objects": leaked_count,
                "leaked_bytes": leaked_bytes,
                "newly_flagged": [r["object_id"] for r in newly]}

    async def _safe_evict_hint(self, conn, oids: List[str]):
        try:
            await conn.notify("ledger_evict_hint", oids=oids)
        except Exception:
            logger.debug("evict hint to node failed", exc_info=True)

    async def _ledger_sweep_loop(self):
        while True:
            await asyncio.sleep(cfg.ledger_sweep_interval_s)
            if not self.object_ledger:
                continue
            try:
                await self.h_ledger_sweep(None)
            except Exception:
                logger.exception("ledger sweep failed")

    # ------------------------------------------------- prefix summaries
    def h_publish_prefix_summary(self, conn, replica_id: str, fps: list,
                                 chunk: int, blocks: Optional[int] = None,
                                 deployment: Optional[str] = None):
        """One serving replica's trie summary (serve/disagg.py): top-K
        path fingerprints of its radix prefix cache. Last write wins per
        replica; rows expire at read time after cfg.prefix_summary_ttl_s
        so a dead replica stops attracting routes within one TTL. The
        table is bounded: past 1024 replicas the stalest rows retire."""
        if not replica_id:
            return False
        self.prefix_summaries[replica_id] = {
            "replica_id": replica_id,
            "fps": [int(f) for f in (fps or [])][:cfg.prefix_summary_top_k],
            "chunk": int(chunk), "blocks": blocks,
            "deployment": deployment, "ts": time.time()}
        if len(self.prefix_summaries) > 1024:
            for rid in sorted(self.prefix_summaries,
                              key=lambda r:
                              self.prefix_summaries[r]["ts"])[:64]:
                self.prefix_summaries.pop(rid, None)
        return True

    def h_get_prefix_summaries(self, conn, ids: Optional[list] = None,
                               deployment: Optional[str] = None):
        """Live (non-expired) summary rows, optionally filtered to the
        replica ids a router currently routes to. Expired rows are
        pruned here — publication is the only other write path."""
        now = time.time()
        ttl = cfg.prefix_summary_ttl_s
        for rid in [r for r, row in self.prefix_summaries.items()
                    if now - row["ts"] > ttl]:
            self.prefix_summaries.pop(rid, None)
        rows = list(self.prefix_summaries.values())
        if ids is not None:
            want = set(ids)
            rows = [r for r in rows if r["replica_id"] in want]
        if deployment:
            rows = [r for r in rows if r.get("deployment") == deployment]
        return rows

    # ------------------------------------------------- tenant quotas
    def h_set_tenant_quota(self, conn, tenant: str,
                           quota: Optional[int] = None,
                           weight: Optional[float] = None,
                           rate: Optional[float] = None,
                           burst: Optional[float] = None):
        """One tenant's fair-share admission row (serve/fleet.py):
        `quota` caps concurrent in-flight requests at the serve ingress
        (<= 0 = unlimited), `weight` sets the tenant's DRR share while
        queued, `rate` is the tenant's CLUSTER-WIDE admission rate
        (requests/s, <= 0 = unlimited) that the quota-lease layer splits
        across proxies, and `burst` the token-bucket depth backing that
        rate. Partial updates merge; the "__default__" tenant moves the
        fleet-wide defaults. Bounded at 4096 tenants (stalest rows
        retire — same discipline as prefix_summaries). A rate change
        bumps the lease epoch so every proxy re-splits within one renew
        interval."""
        if not tenant:
            return False
        row = self.tenant_quotas.setdefault(tenant, {"tenant": tenant})
        if quota is not None:
            row["quota"] = int(quota)
        if weight is not None:
            row["weight"] = float(weight)
        if rate is not None:
            row["rate"] = float(rate)
            self.quota_lease_epoch += 1
        if burst is not None:
            row["burst"] = float(burst)
            self.quota_lease_epoch += 1
        row["ts"] = time.time()
        if len(self.tenant_quotas) > 4096:
            for t in sorted(self.tenant_quotas,
                            key=lambda t: self.tenant_quotas[t]["ts"])[:64]:
                self.tenant_quotas.pop(t, None)
        return True

    def h_get_tenant_quotas(self, conn):
        return list(self.tenant_quotas.values())

    # ------------------------------------------------- quota leases
    # Shared tenant fair share across N ingress proxies (ROADMAP item
    # 2a): the GCS owns each tenant's cluster-wide token-bucket RATE
    # (tenant_quotas rows) and leases every proxy a share of it. The
    # epoch bumps on any membership or rate change, so a renew response
    # carrying a newer epoch tells the proxy to adopt the re-split
    # shares atomically. A REVOKED proxy's share is escrowed — held out
    # of the live split until the lease expires or re-acquires — so the
    # revoked proxy's conservative local admission (a fraction of its
    # old share, serve/fleet.py) can never combine with the survivors'
    # shares into cluster-wide over-admission.
    def _prune_quota_leases(self):
        now = time.time()
        ttl = cfg.quota_lease_ttl_s
        dead = [p for p, row in self.quota_leases.items()
                if now - row["ts"] > ttl]
        for p in dead:
            self.quota_leases.pop(p, None)
        if dead:
            self.quota_lease_epoch += 1

    def _quota_shares(self, proxy_id: str) -> Dict[str, Dict]:
        """This proxy's per-tenant bucket parameters under the current
        split: every live (non-revoked, non-expired) proxy gets an equal
        proportional share of each rated tenant's cluster rate; escrowed
        (revoked) proxies still count in the denominator."""
        n = max(1, len(self.quota_leases))
        shares = {}
        for t, row in self.tenant_quotas.items():
            rate = float(row.get("rate") or 0.0)
            if rate <= 0:
                continue
            burst = float(row.get("burst") or max(1.0, rate))
            shares[t] = {"rate": rate / n, "burst": max(1.0, burst / n),
                         "cluster_rate": rate}
        return shares

    def h_quota_lease_acquire(self, conn, proxy_id: str):
        """Join (or re-join after revocation) the proxy membership.
        Bumps the epoch — every other proxy picks up its smaller share
        at its next renew — and returns this proxy's split."""
        if not proxy_id:
            return None
        self._prune_quota_leases()
        row = self.quota_leases.get(proxy_id)
        if row is None or row.get("revoked"):
            self.quota_lease_epoch += 1
        self.quota_leases[proxy_id] = {
            "proxy_id": proxy_id, "ts": time.time(), "revoked": False}
        return {"epoch": self.quota_lease_epoch,
                "n_proxies": len(self.quota_leases),
                "shares": self._quota_shares(proxy_id),
                "quotas": list(self.tenant_quotas.values())}

    def h_quota_lease_renew(self, conn, proxy_id: str, epoch: int,
                            burn: Optional[Dict[str, int]] = None):
        """Heartbeat + burn-delta push on the metrics cadence. Burn
        deltas aggregate into per-tenant cluster totals (the edge bench
        and per-tenant SLO read them); a stale epoch gets the fresh
        split back; a revoked/unknown lease gets {revoked: True} so the
        proxy degrades to its conservative local quota and re-acquires."""
        self._prune_quota_leases()
        for t, n in (burn or {}).items():
            self.tenant_burn[t] = self.tenant_burn.get(t, 0) + int(n)
        row = self.quota_leases.get(proxy_id)
        if row is None or row.get("revoked"):
            return {"revoked": True, "epoch": self.quota_lease_epoch}
        row["ts"] = time.time()
        out = {"revoked": False, "epoch": self.quota_lease_epoch}
        if int(epoch) != self.quota_lease_epoch:
            out["shares"] = self._quota_shares(proxy_id)
            out["quotas"] = list(self.tenant_quotas.values())
        return out

    def h_quota_lease_release(self, conn, proxy_id: str):
        if self.quota_leases.pop(proxy_id, None) is not None:
            self.quota_lease_epoch += 1
        return True

    def h_quota_lease_revoke(self, conn, proxy_id: str):
        """Chaos/test hook (util/chaos.py QuotaLeaseRevoker): mark the
        lease revoked WITHOUT re-splitting its share — the share stays
        escrowed (the revoked proxy still counts in the split
        denominator) until the lease TTLs out or re-acquires, which is
        what makes conservative local admission provably safe."""
        row = self.quota_leases.get(proxy_id)
        if row is None:
            return False
        row["revoked"] = True
        self.quota_lease_epoch += 1
        return True

    def h_quota_lease_status(self, conn):
        self._prune_quota_leases()
        return {"epoch": self.quota_lease_epoch,
                "leases": [dict(r) for r in self.quota_leases.values()],
                "tenant_burn": dict(self.tenant_burn)}

    # --------------------------------------------------------------- pubsub
    def h_report_metrics(self, conn, worker_id: str, metrics: list,
                         node_id: Optional[str] = None,
                         ts: Optional[float] = None):
        """Per-process metric snapshots (reference: the per-node metrics
        agent collecting OpenCensus exports, metrics_agent.py:483).
        node_id tags the snapshot's host so a node death can retire it
        — a dead worker's gauges would otherwise sit in /metrics
        forever. Counters flushed by a CLEAN worker shutdown survive
        (the node is still alive then). Each push also feeds the
        time-series plane (ts overrides the sample timestamp — tests
        drive deterministic windows with it)."""
        if not hasattr(self, "metrics"):
            self.metrics = {}
            self.metrics_node: Dict[str, Optional[str]] = {}
        self.metrics[worker_id] = metrics
        self.metrics_node[worker_id] = node_id
        try:
            self.metrics_ts.ingest(worker_id, metrics, ts=ts)
        except Exception:
            logger.exception("metrics time-series ingest failed")
        return True

    def h_get_metrics(self, conn):
        return getattr(self, "metrics", {})

    def h_query_metrics(self, conn, name: str, window: float = 60.0,
                        agg: str = "avg",
                        tags: Optional[Dict[str, str]] = None,
                        threshold: Optional[float] = None,
                        now: Optional[float] = None):
        """Windowed aggregate over the time-series plane. agg: rate /
        sum / avg / max / min / latest, p50 / p90 / p95 / p99 /
        frac_over (histograms, reconstructed from bucket deltas),
        buckets (raw merged window), series (raw samples)."""
        return self.metrics_ts.query(name, window_s=window, agg=agg,
                                     tags=tags, threshold=threshold,
                                     now=now)

    def h_list_metric_series(self, conn):
        return self.metrics_ts.list_series()

    def h_dump_metric_series(self, conn, window: float = 600.0,
                             names: Optional[List[str]] = None,
                             kinds: Optional[List[str]] = None,
                             now: Optional[float] = None):
        return self.metrics_ts.dump_series(window_s=window, names=names,
                                           kinds=kinds, now=now)

    def _drop_node_metrics(self, node_id: str):
        node_of = getattr(self, "metrics_node", {})
        for wid in [w for w, n in node_of.items() if n == node_id]:
            getattr(self, "metrics", {}).pop(wid, None)
            node_of.pop(wid, None)
            self.metrics_ts.drop_worker(wid)
            # objects owned by this node's workers just lost their owner
            # — the ledger sweep treats them as leak candidates
            self._ledger_exited.add(wid)

    def h_drop_worker_metrics(self, conn, worker_id: str):
        """Node managers report crashed/killed workers here so their
        gauges don't sit in /metrics forever. Clean DRIVER shutdowns
        never route through this — their final counter flush persists.
        The worker's time-series HISTORY stays (it is history; retention
        ages it out) but its delta baselines go, so a reused worker id
        can't fake a counter reset."""
        getattr(self, "metrics", {}).pop(worker_id, None)
        getattr(self, "metrics_node", {}).pop(worker_id, None)
        self.metrics_ts.drop_worker(worker_id)
        # crashed/killed worker: its owned-table died with it, so its
        # sealed objects have zero owner references by definition
        self._ledger_exited.add(worker_id)
        return True

    def h_subscribe(self, conn, channel: str):
        self.subscribers.setdefault(channel, set()).add(conn)
        return True

    def h_publish(self, conn, channel: str, key: str, payload: Any):
        self._publish(channel, key, payload)
        return True

    def _persist_actor(self, actor_id: str):
        """Full row (incl. pickled spec) only on the first WAL record per
        actor per WAL generation; state transitions afterwards log a
        spec-less delta so churny actors can't balloon the WAL between
        snapshots."""
        row = self.actors.get(actor_id)
        if row is None:
            return
        if actor_id not in self._wal_actors:
            self._wal_actors.add(actor_id)
            self._log_op("actor", {"aid": actor_id, "row": row})
        else:
            delta = {k: v for k, v in row.items() if k != "spec"}
            self._log_op("actor_delta", {"aid": actor_id, "delta": delta})

    def _persist_pg(self, pg_id: str):
        row = self.placement_groups.get(pg_id)
        if row is not None:
            self._log_op("pg", {"pg_id": pg_id, "row": row})

    def _publish(self, channel: str, key: str, payload: Any):
        for sub in list(self.subscribers.get(channel, ())):
            if sub.closed:
                self.subscribers[channel].discard(sub)
                continue
            # t0 stamped at accept: deliver latency includes event-loop
            # queueing, which is the signal (a backed-up GCS loop shows
            # up here before anywhere else)
            asyncio.ensure_future(self._safe_notify(
                sub, channel, key, payload, self.obs.note_publish()))

    async def _safe_notify(self, conn, channel, key, payload, t0=None):
        try:
            await conn.notify("pubsub", channel=channel, key=key, payload=payload)
        except Exception:
            self.subscribers.get(channel, set()).discard(conn)
            if t0 is not None:
                self.obs.note_deliver(t0, ok=False)
            return
        if t0 is not None:
            self.obs.note_deliver(t0, ok=True)

    # ----------------------------------------------------- placement groups
    async def h_create_placement_group(self, conn, pg_id: str,
                                       bundles: List[Dict[str, float]],
                                       strategy: str = "PACK",
                                       name: str = ""):
        """Two-phase bundle reservation (reference:
        gcs_placement_group_scheduler Prepare/Commit)."""
        alive = {nid: n for nid, n in self.nodes.items()
                 if n["alive"] and not n["draining"]}
        placement = scheduling.schedule_bundles(alive, bundles, strategy)
        row = {"pg_id": pg_id, "bundles": bundles, "strategy": strategy,
               "name": name, "state": "PENDING", "node_ids": None}
        self.placement_groups[pg_id] = row
        self._persist_pg(pg_id)
        if placement is None:
            row["state"] = "PENDING"   # infeasible now; retried by caller wait
            return {"state": "PENDING"}
        # phase 1: prepare on every node
        prepared = []
        ok = True
        for idx, (nid, bundle) in enumerate(zip(placement, bundles)):
            node_conn = self.node_conns.get(nid)
            if node_conn is None or node_conn.closed:
                ok = False
                break
            try:
                good = await node_conn.call("prepare_bundle", pg_id=pg_id,
                                            bundle_index=idx, resources=bundle)
            except (rpc.RpcError, rpc.ConnectionLost):
                good = False
            if not good:
                ok = False
                break
            prepared.append((nid, idx))
        if not ok:
            for nid, idx in prepared:
                node_conn = self.node_conns.get(nid)
                if node_conn and not node_conn.closed:
                    try:
                        await node_conn.call("return_bundle", pg_id=pg_id,
                                             bundle_index=idx)
                    except (rpc.RpcError, rpc.ConnectionLost):
                        pass
            return {"state": "PENDING"}
        # phase 2: commit
        for nid, idx in prepared:
            node_conn = self.node_conns.get(nid)
            try:
                await node_conn.call("commit_bundle", pg_id=pg_id, bundle_index=idx)
            except (rpc.RpcError, rpc.ConnectionLost):
                pass
        row["state"] = "CREATED"
        row["node_ids"] = placement
        self._persist_pg(pg_id)
        self._publish("PG", pg_id, {"state": "CREATED", "node_ids": placement})
        return {"state": "CREATED", "node_ids": placement}

    async def h_remove_placement_group(self, conn, pg_id: str):
        row = self.placement_groups.get(pg_id)
        if row is None:
            return False
        if row.get("node_ids"):
            for idx, nid in enumerate(row["node_ids"]):
                node_conn = self.node_conns.get(nid)
                if node_conn and not node_conn.closed:
                    try:
                        await node_conn.call("return_bundle", pg_id=pg_id,
                                             bundle_index=idx)
                    except (rpc.RpcError, rpc.ConnectionLost):
                        pass
        row["state"] = "REMOVED"
        self._publish("PG", pg_id, {"state": "REMOVED"})
        return True

    def h_get_placement_group(self, conn, pg_id: str):
        row = self.placement_groups.get(pg_id)
        if row is None:
            return None
        return {k: row[k] for k in ("pg_id", "bundles", "strategy", "name",
                                    "state", "node_ids")}

    def h_get_all_placement_groups(self, conn):
        return [self.h_get_placement_group(conn, pid)
                for pid in self.placement_groups]


def _node_view(n: Dict) -> Dict:
    """One node's entry in the cluster resource view."""
    return {"total": n["total"], "available": n["available"],
            "alive": n["alive"], "draining": n["draining"],
            "address": n["address"],
            "object_store_address": n["object_store_address"],
            "data_plane_address": n.get("data_plane_address"),
            "node_ip": n["node_ip"], "labels": n["labels"]}


def _node_public(n: Dict) -> Dict:
    out = {k: n[k] for k in ("node_id", "address", "object_store_address",
                             "node_ip", "total", "available", "labels",
                             "alive")}
    out["data_plane_address"] = n.get("data_plane_address")
    out["pending_demand"] = n.get("pending_demand", [])
    return out


def _actor_public(row: Dict) -> Dict:
    return {"actor_id": row["actor_id"], "state": row["state"],
            "name": row.get("name"), "namespace": row.get("namespace"),
            "node_id": row.get("node_id"), "address": row.get("address"),
            "death_cause": row.get("death_cause"),
            "num_restarts": row.get("num_restarts", 0),
            "method_names": (row.get("spec") or {}).get("method_names") or [],
            "resources": (row.get("spec") or {}).get("resources") or {}}


def main():
    import argparse
    import sys
    from ray_tpu._private.proc_util import set_pdeathsig_from_env
    set_pdeathsig_from_env()
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--session-name", default="session")
    parser.add_argument("--persist-path", default=None)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="[gcs] %(asctime)s %(levelname)s %(message)s")

    async def run():
        from ray_tpu.util import sanitizers
        sanitizers.maybe_install()
        gcs = GcsServer(port=args.port, session_name=args.session_name,
                        persist_path=args.persist_path)
        addr = await gcs.start()
        # crash black box: continuous event/metrics mirror + seal on
        # SIGTERM / clean exit (SIGKILL leaves the continuous appends)
        from ray_tpu._private import blackbox as _bb
        _bb.configure(gcs._blackbox_dir(), "gcs",
                      worker_id="gcs")
        import signal

        def _on_term(signum, frame):
            _bb.seal(f"signal_{signum}")
            raise SystemExit(0)

        try:
            signal.signal(signal.SIGTERM, _on_term)
        except (ValueError, OSError):
            pass
        # announce the bound address on stdout for the supervisor
        print(f"GCS_ADDRESS={addr}", flush=True)
        await asyncio.Event().wait()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        sys.exit(0)


if __name__ == "__main__":
    main()
