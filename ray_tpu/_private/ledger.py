"""Object-lifetime ledger: per-process provenance deltas for the store.

The arena is the system's center of gravity (striped sub-heaps, spanning
allocations, zero-copy transfers), yet nothing answered "what is in the
store, who owns it, why won't it evict, where did the bytes go" without
gdb. This module is the WRITE side of that answer: every object-lifecycle
edge a process observes — create+seal (with creator worker/task, owner,
size, placement), transfer arrival, spill/restore, eviction, free — is
recorded as a small delta and lazily flushed into the GCS
``object_ledger`` table, which merges per-node deltas into one provenance
row per object id (read side: ``util/state.list_objects`` joins these
rows with live arena truth; ``ray_tpu memory`` renders them).

The ring reuses the flight-recorder discipline (events.py, PR 4), in
order of importance:

1. **Hot-path cost**: a disabled ledger is one global-flag read; an
   enabled one is a dict build plus a locked list append. No
   serialization, no RPC, no native calls beyond what the caller already
   paid. The acceptance bench (`bench.py observability_overhead`) holds
   the enabled put path under the same 5% guard as the recorder.
2. **Bounded memory with deterministic drop accounting**: the ring keeps
   the NEWEST `capacity` records; overwrites are counted and shipped
   in-band as a ``dropped`` field on the next flushed batch, so a
   truncated provenance trail says so in the table itself.
3. **No hard runtime coupling**: records just rotate in a bare process;
   the flusher thread starts lazily and ships batches only once a sink
   exists (the connected worker, or the node manager's `set_sink`).

Ordering: each record carries a per-process monotonically increasing
``seq`` so the GCS merge can ignore stale duplicates from one process
without trusting wall clocks across processes.

Node managers additionally push a periodic arena CENSUS (presence, pin
counts, placement) through the same GCS handler — the census, not the
event stream, is the authority for "current location set", because LRU
eviction and crash repair reclaim objects without any event firing.
"""

from __future__ import annotations

import itertools
import logging
import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

__all__ = [
    "record", "record_put", "enabled", "set_enabled", "configure",
    "stats", "drain", "flush", "set_sink", "set_identity",
]

_lock = threading.Lock()
_buf: List[Dict] = []
_dropped_total = 0
_dropped_unreported = 0
_capacity = int(os.environ.get("RAY_TPU_LEDGER_BUFFER", "4096"))
_enabled = os.environ.get("RAY_TPU_OBJECT_LEDGER", "1") != "0"
_sink: Optional[Callable[[List[Dict]], None]] = None
_identity: Dict[str, str] = {}
_flusher_started = False
_seq = itertools.count(1)


def enabled() -> bool:
    return _enabled


def set_enabled(value: bool) -> None:
    """Flip the ledger (worker connect applies cfg.ledger_enabled here
    after the head's config snapshot lands, so one head-side setting
    governs the cluster; tests and the overhead bench flip it too)."""
    global _enabled
    _enabled = bool(value)


def configure(capacity: Optional[int] = None) -> None:
    global _capacity, _dropped_total, _dropped_unreported
    if capacity is not None:
        with _lock:
            _capacity = max(1, int(capacity))
            while len(_buf) > _capacity:
                del _buf[0]
                _dropped_total += 1
                _dropped_unreported += 1


def stats() -> Dict[str, int]:
    with _lock:
        return {"buffered": len(_buf), "capacity": _capacity,
                "dropped_total": _dropped_total,
                "dropped_unreported": _dropped_unreported}


def set_sink(fn: Optional[Callable[[List[Dict]], None]]) -> None:
    """Install an explicit flush target (a callable taking a batch of
    ledger records). The node manager ships through its own GCS
    connection this way; workers use the default worker sink."""
    global _sink
    _sink = fn


def set_identity(node_id: Optional[str] = None,
                 worker_id: Optional[str] = None) -> None:
    if node_id:
        _identity["node_id"] = node_id
    if worker_id:
        _identity["worker_id"] = worker_id


def _process_identity():
    node_id = _identity.get("node_id")
    worker_id = _identity.get("worker_id")
    if node_id and worker_id:
        return node_id, worker_id
    w = sys.modules.get("ray_tpu._private.worker")
    core = getattr(getattr(w, "global_worker", None), "core", None) \
        if w is not None else None
    if core is not None:
        return (node_id or getattr(core, "node_id", None)
                or f"pid-{os.getpid()}",
                worker_id or getattr(core, "worker_id", None)
                or f"pid-{os.getpid()}")
    pid = f"pid-{os.getpid()}"
    return node_id or pid, worker_id or pid


# --------------------------------------------------------------- recording
def record(object_id: bytes, event: str, ts: Optional[float] = None,
           **fields) -> None:
    """Append one lifecycle delta. `event` is one of: created, sealed,
    location_add, location_remove, spilled, restored, evicted, freed,
    refs, worker_exit (object_id ignored for worker_exit). Extra fields
    ride verbatim into the GCS row merge."""
    if not _enabled:
        return
    rec = {"object_id": object_id.hex() if isinstance(object_id, bytes)
           else object_id,
           "event": event, "ts": time.time() if ts is None else ts,
           "seq": next(_seq)}
    if fields:
        rec.update(fields)
    _append(rec)


def record_put(object_id: bytes, size: int, meta_size: int = 0,
               owner: Optional[str] = None,
               owner_worker: Optional[str] = None,
               node_id: Optional[str] = None,
               task_id: Optional[str] = None,
               is_span: bool = False,
               sealed: bool = True) -> None:
    """One-record create+seal provenance for the put fast path (two
    separate records would double the hot-path append for an edge pair
    that is atomic from the caller's perspective)."""
    if not _enabled:
        return
    now = time.time()
    _append({"object_id": object_id.hex(), "event": "created", "ts": now,
             "seq": next(_seq), "size": int(size),
             "meta_size": int(meta_size), "owner": owner,
             "owner_worker": owner_worker, "node_id": node_id,
             "task_id": task_id, "is_span": bool(is_span),
             "sealed": bool(sealed)})


def _append(rec: Dict) -> None:
    global _dropped_total, _dropped_unreported
    with _lock:
        if len(_buf) >= _capacity:
            # drop OLDEST: censuses reconcile lost presence deltas, and
            # the newest provenance is what a post-mortem needs
            del _buf[0]
            _dropped_total += 1
            _dropped_unreported += 1
        _buf.append(rec)
    if not _flusher_started:
        _ensure_flusher()


# ------------------------------------------------------------ flush plumbing
def drain(max_records: Optional[int] = None) -> List[Dict]:
    """Pop buffered records (the flusher and shutdown paths ship the
    result through the sink). The unreported-drop counter resets only
    when a non-empty batch leaves, so drops are always reported."""
    global _dropped_unreported
    with _lock:
        n = len(_buf) if max_records is None else min(max_records,
                                                      len(_buf))
        batch, dropped = _buf[:n], _dropped_unreported
        del _buf[:n]
        if batch:
            _dropped_unreported = 0
    if batch and dropped:
        batch[0] = dict(batch[0], dropped=dropped)
    return batch


def _default_sink() -> Optional[Callable[[List[Dict]], None]]:
    if _sink is not None:
        return _sink
    try:
        import ray_tpu
        if not ray_tpu.is_initialized():
            return None
        w = ray_tpu._get_worker()
        node_id, worker_id = _process_identity()
        return lambda batch: w.gcs_call(
            "update_object_ledger", records=batch, node_id=node_id,
            worker_id=worker_id)
    except Exception:
        return None


def flush() -> int:
    """Synchronous flush (shutdown paths, tests). Returns records
    shipped; 0 when no sink is reachable (records stay buffered)."""
    sink = _default_sink()
    if sink is None:
        return 0
    batch = drain()
    if not batch:
        return 0
    try:
        sink(batch)
    except Exception:
        return 0
    return len(batch)


_flush_err_logged = False


def _flush_loop():
    global _flush_err_logged
    while True:
        time.sleep(1.0)
        try:
            flush()
        except Exception:
            # flush() swallows sink errors; reaching here means the
            # ledger itself broke — say so once, don't spam a 1 Hz log
            if not _flush_err_logged:
                _flush_err_logged = True
                logging.getLogger(__name__).warning(
                    "ledger flush loop error (logged once)", exc_info=True)


def _ensure_flusher():
    global _flusher_started
    with _lock:
        if _flusher_started:
            return
        _flusher_started = True
    threading.Thread(target=_flush_loop, name="ledger-flush",
                     daemon=True).start()
