"""CoreWorker — the in-process runtime linked into every driver and worker.

Re-design of the reference's CoreWorker (reference:
src/ray/core_worker/core_worker.h:271, core_worker.cc — Put :1245, Get :1550,
SubmitTask :2165, SubmitActorTask :2488) and its transport layer
(transport/normal_task_submitter.h:75, actor_task_submitter.h:75,
task_receiver.h:51). Differences, deliberately:

- One asyncio loop per process is the only event engine (the reference runs
  multiple dedicated C++ io_services + a fiber layer). Sync user code runs in
  executor threads; the public API bridges with run_coroutine_threadsafe.
- Worker↔worker task push is a plain RPC *call* whose response carries the
  task's results, so pipelining = concurrent calls on one ordered connection
  (the reference needs explicit seq-nos + reply callbacks).
- The lease protocol is kept (amortizes scheduling like the reference's
  NormalTaskSubmitter lease cache) but leases are granted by the node
  manager over the caller's persistent connection, and spillback is a
  redirect reply rather than a raylet-internal hop.
- Objects: small values live in the owner's memory store and are served to
  borrowers over owner RPC; large values are sealed into the node-local shm
  arena (object_store.py) and fetched node-to-node via the node managers.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import functools
import hashlib
import heapq
import itertools
import logging
import os
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu._private import ids, ledger, rpc, serialization
from ray_tpu._private.config import cfg
from ray_tpu._private.markers import off_loop
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.object_store import ObjectStoreClient
from ray_tpu._private.serialization import (ActorDiedError, ObjectLostError,
                                            TaskCancelledError, TaskError,
                                            WorkerCrashedError)

logger = logging.getLogger(__name__)

DRIVER = "driver"
WORKER = "worker"

# tunables live in config.py (lease_idle_timeout_s, task_max_retries,
# max_dispatchers_per_sig, actor_restart_probe_s)


def _import_ref(ref: str):
    """Resolve a cross-language "module:attr" reference."""
    import importlib
    mod_name, sep, attr = ref.partition(":")
    if not sep or not attr:
        raise ValueError(f"bad cross-language ref {ref!r}; "
                         f"expected 'module:attr'")
    target = importlib.import_module(mod_name)
    for part in attr.split("."):
        target = getattr(target, part)
    return target


def _encode_arg(arg, ref_hook, core=None) -> list:
    if isinstance(arg, ObjectRef):
        if ref_hook is not None:
            ref_hook(arg)
        return ["r", arg.id, arg.owner_address]
    s = serialization.serialize(arg, ref_hook=ref_hook)
    if core is not None and core.store is not None and not s.is_inline():
        # Large argument: seal it into the local shm arena on THIS thread
        # and pass by reference (the reference promotes >100KB args to
        # plasma the same way, put_arg path). The payload stays out of
        # every RPC frame it would otherwise ride — GCS actor specs,
        # per-retry task pushes — and its copy never occupies the owner
        # loop. The implicit ref is pinned like any explicit ref arg for
        # the task's duration via ref_hook.
        core._spill_pressure_sync(s)
        ref = core._put_serialized(s)
        if ref_hook is not None:
            ref_hook(ref)
        return ["r", ref.id, ref.owner_address]
    kind, pkl, bufs = s.to_wire()
    return ["v", kind, pkl, bufs]


class _InlineBridgeError(BaseException):
    """Raised when inline-executed task code calls a blocking sync API
    (which bridges onto the event loop it is already running on).
    BaseException so user-level `except Exception` can't swallow it and
    complete the task with wrong results."""


# execution-thread context: which method is running (bridge-use tracking)
_exec_tls = threading.local()

# (trace_id, span_id) of the task running on the current loop context —
# async actor methods execute as coroutines, where a contextvar is the
# per-task store; sync methods run on executor threads and use _exec_tls
_trace_ctx: "contextvars.ContextVar" = __import__(
    "contextvars").ContextVar("ray_tpu_trace", default=None)


class PendingTask:
    __slots__ = ("spec", "return_ids", "retries_left", "arg_refs", "done",
                 "cancelled", "current_worker", "seq")

    def __init__(self, spec, return_ids, retries_left, arg_refs):
        self.spec = spec
        self.return_ids = return_ids
        self.retries_left = retries_left
        self.arg_refs = arg_refs
        self.done = False
        self.cancelled = False
        self.current_worker = None
        self.seq = 0          # per-actor submission order (actor tasks)


class Lease:
    __slots__ = ("lease_id", "worker_address", "node_address", "signature",
                 "last_used", "resource_ids")

    def __init__(self, lease_id, worker_address, node_address, signature,
                 resource_ids=None):
        self.lease_id = lease_id
        self.worker_address = worker_address
        self.node_address = node_address
        self.signature = signature
        self.last_used = time.monotonic()
        self.resource_ids = resource_ids or {}


class ActorHandleState:
    def __init__(self, actor_id: str):
        self.actor_id = actor_id
        self.state = "PENDING_CREATION"
        self.address: Optional[str] = None
        self.ready = asyncio.Event()
        self.death_cause: Optional[str] = None
        # submission-ordered pipeline: fresh sends carry a sequence
        # number; retries of in-flight calls that died with a connection
        # re-enter by seq AHEAD of later submissions (the reference keeps
        # the same guarantee with explicit seq-nos,
        # sequential_actor_submit_queue.cc)
        self.pending = __import__("collections").deque()
        self.retry: list = []          # heap of (seq, PendingTask)
        self.work = asyncio.Event()
        self.seq_counter = 0
        self.sender: Optional[asyncio.Task] = None


class CoreWorker:
    """Async runtime. All methods ending in _async run on self.loop."""

    def __init__(self, mode: str, gcs_address: str, node_address: str,
                 store_path: str, node_id: str, job_id: int = 0,
                 namespace: str = "default", worker_id: Optional[str] = None):
        self.mode = mode
        self.gcs_address = gcs_address
        self.node_address = node_address
        self.node_id = node_id
        self.job_id = job_id
        self.namespace = namespace
        self.worker_id = worker_id or os.urandom(16).hex()
        self.store = ObjectStoreClient(store_path) if store_path else None
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self.address: Optional[str] = None

        # Amortized spill-pressure probe state: capacity is cached at
        # attach, bytes_in_use is refreshed every spill_probe_interval_puts
        # puts (or on MemoryError); between refreshes this worker accounts
        # its own put bytes locally so a burst of large puts still trips
        # the check. Avoids a per-put cross-process store.stats() call.
        self._spill_capacity: Optional[int] = None
        self._spill_bytes_in_use = 0
        self._spill_local_bytes = 0
        self._spill_probe_left = 0

        self.gcs: Optional[rpc.Connection] = None
        self.node_conn: Optional[rpc.Connection] = None
        self.pool = rpc.ConnectionPool(name=f"w-{self.worker_id[:8]}")
        self.server: Optional[rpc.Server] = None

        # object state
        self.memory_store: Dict[bytes, tuple] = {}   # oid -> ("wire",k,p,b)|("loc",node_id)|("shm",)
        self.object_events: Dict[bytes, asyncio.Event] = {}
        self.owned: Dict[bytes, Dict] = {}
        self.borrowed_counts: Dict[bytes, int] = {}
        self._local_refs: Dict[bytes, int] = {}
        self._pending_unrefs: List[bytes] = []
        # put ids are drawn on the CALLING thread (off-loop put path);
        # itertools.count is a single C-level op, safe under the GIL
        self._put_counter = itertools.count(1)
        # guards read-modify-write of _local_refs / borrowed_counts —
        # ObjectRef hooks fire from user threads, executor threads and
        # the loop alike
        self._ref_lock = threading.Lock()

        # tasks
        self.pending_tasks: Dict[bytes, PendingTask] = {}
        # streaming generators: owner-side live generators by task id;
        # executor-side flow-control windows by task id (+ tombstones for
        # closes that raced ahead of execution)
        self._generators: Dict[bytes, object] = {}
        self._gen_flow: Dict[bytes, Dict] = {}
        self._gen_tombstones: set = set()
        # LRU of live function objects (closures can capture large
        # arrays; evicted entries reload from _func_blobs / GCS KV)
        self._func_cache = __import__("collections").OrderedDict()
        self._func_cache_cap = 512
        # byte-capped LRU of shipped function pickles (served to executors
        # if the GCS KV copy is lost to a restart; eviction only risks the
        # rare restart-from-stale-snapshot window, while an unbounded dict
        # would grow with every distinct closure a long-lived driver ships)
        self._func_blobs: "__import__('collections').OrderedDict" = \
            __import__("collections").OrderedDict()
        self._func_blob_bytes = 0
        self._func_blob_cap = 256 * 1024 * 1024

        # leases
        self._idle_leases: Dict[tuple, List[Lease]] = {}
        self._lease_reaper: Optional[asyncio.Task] = None
        self._sig_queues: Dict[tuple, Dict] = {}   # per-signature dispatch

        # actor handles (submission side)
        self.actor_handles: Dict[str, ActorHandleState] = {}

        # execution side
        self.executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="task-exec")
        self._exec_queue: Optional[asyncio.Queue] = None
        self._consumers: List[asyncio.Task] = []
        self._group_queues: Dict[str, asyncio.Queue] = {}
        self._method_groups: Dict[str, str] = {}
        self.actor_instance = None
        self.actor_id: Optional[str] = None
        self.actor_spec: Optional[Dict] = None
        self.current_task_name: Optional[str] = None
        self.current_task_id: Optional[bytes] = None
        # trace root (reference: tracing_helper.py:34 — spans wrap
        # remote calls with the context riding in task metadata). The
        # ACTIVE context lives in _exec_tls / _trace_ctx, not here:
        # multi-consumer workers run tasks concurrently and instance
        # attributes would cross-contaminate their traces
        self._root_trace_id = os.urandom(8).hex()
        self._orig_visible: Dict[str, Optional[str]] = {}
        self._visible_dirty: set = set()
        self._cancelled_tasks: set = set()
        self._exec_ema: Dict[str, float] = {}   # method -> avg duration
        self._exec_streak: Dict[str, int] = {}  # consecutive fast runs
        self._inline_ok = True    # off for max_concurrency>1 actors
        self._inline_unsafe: set = set()   # methods seen using sync APIs
        self._loop_thread_ident: Optional[int] = None
        self._shutdown = False
        # every fire-and-forget coroutine is tracked here so stop_async can
        # cancel-and-await it — shutdown must leave zero pending tasks
        # (the asyncio analogue of the reference's tsan-clean shutdown)
        self._bg: set = set()
        # submissions from user threads coalesce here: N bursts become one
        # loop wakeup instead of N call_soon_threadsafe socketpair writes
        self._submit_buf: List[tuple] = []
        self._submit_scheduled = False
        self._submit_lock = threading.Lock()

    @off_loop(lock="_submit_lock")
    def _enqueue_submit(self, fn, *args):
        with self._submit_lock:
            self._submit_buf.append((fn, args))
            if self._submit_scheduled:
                return
            self._submit_scheduled = True
        try:
            self.loop.call_soon_threadsafe(self._drain_submits)
        except BaseException:
            # loop closing: reset so later submits fail loudly here
            # instead of queueing behind a flag nobody will drain
            with self._submit_lock:
                self._submit_scheduled = False
            raise

    def _drain_submits(self):
        while True:
            with self._submit_lock:
                buf, self._submit_buf = self._submit_buf, []
                if not buf:
                    self._submit_scheduled = False
                    return
            for fn, args in buf:
                try:
                    fn(*args)
                except Exception:
                    logger.exception("deferred submit failed")

    def _spawn(self, coro) -> "asyncio.Task":
        t = asyncio.ensure_future(coro)
        self._bg.add(t)
        t.add_done_callback(self._bg.discard)
        return t

    # -------------------------------------------------------------- startup
    async def start_async(self):
        handlers = {
            "push_task": self.h_push_task,
            "push_tasks": self.h_push_tasks,
            "push_task_streaming": self.h_push_task_streaming,
            "generator_ack": self.h_generator_ack,
            "generator_close": self.h_generator_close,
            "become_actor": self.h_become_actor,
            "wait_object": self.h_wait_object,
            "cancel_task": self.h_cancel_task,
            "add_borrow": self.h_add_borrow,
            "fetch_function": self.h_fetch_function,
            "remove_borrow": self.h_remove_borrow,
            "object_located": self.h_object_located,
            "exit": self.h_exit,
            "dump_stacks": self.h_dump_stacks,
            "ping": lambda conn: "pong",
        }
        self.loop = asyncio.get_event_loop()
        self._loop_thread_ident = threading.get_ident()
        self.server = rpc.Server(handlers, name=f"worker-{self.worker_id[:8]}")
        self.address = await self.server.listen_tcp("0.0.0.0", 0)
        self.gcs = await rpc.connect(self.gcs_address,
                                     handlers={"pubsub": self.h_pubsub},
                                     name="->gcs", retries=10)
        try:
            cfg.apply(await self.gcs.call("get_system_config") or {})
        except rpc.RpcError:
            pass   # older GCS without the handler
        # one head-side ledger_enabled governs the cluster; identity is
        # pinned so flushes from executor threads never guess it
        ledger.set_enabled(cfg.ledger_enabled)
        ledger.set_identity(node_id=self.node_id, worker_id=self.worker_id)
        if self.node_address:
            self.node_conn = await rpc.connect(
                self.node_address, handlers={
                    "pubsub": self.h_pubsub,
                    "free_object": self.h_free_object,
                    "become_actor": self.h_become_actor,
                    "exit": self.h_exit,
                    "dump_stacks": self.h_dump_stacks,
                }, name="->node", retries=10)
            await self.node_conn.call(
                "register_worker", worker_id=self.worker_id,
                address=self.address, pid=os.getpid(), mode=self.mode)
            if self.mode == WORKER:
                # fate-sharing with the node manager (reference: workers die
                # when their raylet dies)
                def _nm_lost(_conn):
                    logger.warning("node manager connection lost; exiting")
                    os._exit(1)
                self.node_conn.on_close = _nm_lost
        self._exec_queue = asyncio.Queue()
        self._consumers = [self._spawn(self._exec_consumer())]
        self._lease_reaper = self._spawn(self._reap_leases())
        self._task_events: List[Dict] = []
        self._task_events_dropped = 0
        self._ev_window_t0 = 0.0
        self._ev_window_n = 0
        self._ev_budget = 10**9   # refreshed from cfg each window
        self._event_flusher = self._spawn(self._flush_task_events())
        self._install_ref_hooks()
        self._subscribed_actor_channel = False
        self._subscribed_channels = set()
        self._gcs_reconnect_lock = None   # created lazily on the loop
        if (self.mode == DRIVER
                and os.environ.get("RAY_TPU_LOG_TO_DRIVER", "1") != "0"):
            self._subscribed_channels.add("LOGS")
            await self.gcs.call("subscribe", channel="LOGS")

    def _install_ref_hooks(self):
        loop = self.loop

        def local_ref(ref: ObjectRef):
            # fires from any thread (refs are created on caller threads)
            with self._ref_lock:
                self._local_refs[ref.id] = self._local_refs.get(ref.id, 0) + 1

        def local_unref(ref: ObjectRef):
            # may fire from any thread / late interpreter shutdown
            try:
                loop.call_soon_threadsafe(self._dec_local_ref, ref.id,
                                          ref.owner_address)
            except Exception:
                pass

        def deser_hook(ref: ObjectRef):
            with self._ref_lock:
                self._local_refs[ref.id] = self._local_refs.get(ref.id, 0) + 1
                first_borrow = False
                if ref.owner_address and ref.owner_address != self.address:
                    cnt = self.borrowed_counts.get(ref.id, 0)
                    first_borrow = cnt == 0
                    self.borrowed_counts[ref.id] = cnt + 1
            if first_borrow:
                asyncio.run_coroutine_threadsafe(self._send_borrow(ref), loop)

        ObjectRef._local_ref_hook = staticmethod(local_ref)
        ObjectRef._local_unref_hook = staticmethod(local_unref)
        ObjectRef._deserialization_hook = staticmethod(deser_hook)

    async def _send_borrow(self, ref: ObjectRef):
        try:
            await self.pool.call(ref.owner_address, "add_borrow",
                                 oid=ref.id, borrower=self.address)
        except Exception:
            pass

    def _dec_local_ref(self, oid: bytes, owner_address: str):
        with self._ref_lock:
            n = self._local_refs.get(oid, 0) - 1
            if n > 0:
                self._local_refs[oid] = n
                return
            self._local_refs.pop(oid, None)
        if oid in self.owned:
            self._maybe_free(oid)
        elif owner_address and owner_address != self.address:
            with self._ref_lock:
                cnt = self.borrowed_counts.pop(oid, 0)
            if cnt > 0:
                self._spawn(self._send_remove_borrow(oid, owner_address))
            self.memory_store.pop(oid, None)

    async def _send_remove_borrow(self, oid, owner_address):
        try:
            await self.pool.call(owner_address, "remove_borrow",
                                 oid=oid, borrower=self.address)
        except Exception:
            pass

    def _maybe_free(self, oid: bytes):
        entry = self.owned.get(oid)
        if entry is None:
            return
        if (self._local_refs.get(oid, 0) == 0 and not entry["borrowers"]
                and entry.get("submitted", 0) == 0 and entry.get("complete", True)):
            self.owned.pop(oid, None)
            self.memory_store.pop(oid, None)
            self.object_events.pop(oid, None)
            entry.pop("contained", None)  # drops nested refs -> their unrefs
            loc = entry.get("location")
            if ledger.enabled() and entry.get("complete"):
                # the owner released its last reference: close the
                # object's provenance row (leak sweep skips freed rows)
                ledger.record(oid, "freed", node_id=loc)
            if loc == self.node_id and self.store is not None:
                try:
                    self.store.delete(oid)
                except Exception:
                    pass
            elif loc is not None:
                self._spawn(self._free_remote(oid, loc))

    async def _free_remote(self, oid: bytes, node_id: str):
        try:
            await self.node_conn.notify("free_remote_object", oid=oid,
                                        node_id=node_id)
        except Exception:
            pass

    # ------------------------------------------------------------ task events
    def _record_task_event(self, task_id: bytes, state: str, **extra):
        """Buffered task state transitions, flushed to the GCS task-event
        sink. Bounded two ways: a size cap (old events drop rather than
        letting the buffer grow without limit — reference:
        TaskEventBuffer max size + dropped counter,
        task_event_buffer.h:220) and a RATE budget — past
        cfg.task_events_per_s the recorder keeps only a deterministic
        1-in-8 sample keyed by task id, so every process samples the
        SAME tasks and sampled rows still get all their states (the
        timeline stays representative instead of eating ~3 events/call
        of control-plane CPU at full throughput)."""
        now = time.monotonic()
        if now - self._ev_window_t0 >= 1.0:
            self._ev_window_t0 = now
            self._ev_window_n = 0
            self._ev_budget = cfg.task_events_per_s
        self._ev_window_n += 1
        if self._ev_window_n > self._ev_budget and task_id[-1] & 7:
            self._task_events_dropped += 1
            return
        ev = self._task_events
        if len(ev) >= 10000:
            del ev[:5000]
            self._task_events_dropped += 5000
        ev.append({"task_id": task_id.hex(), "state": state,
                   "ts": time.time(), **extra})

    async def _flush_task_events(self):
        while not self._shutdown:
            await asyncio.sleep(1.0)
            if not self._task_events or self.gcs is None or self.gcs.closed:
                continue
            batch, self._task_events = self._task_events, []
            try:
                await self.gcs.notify("add_task_events", events=batch)
            except Exception:
                # the batch is gone — account it so the observability
                # plane shows the gap instead of looking quietly healthy
                self._task_events_dropped += len(batch)


    async def _reconnect_gcs(self):
        """Re-establish the GCS connection after a GCS restart and
        re-subscribe (reference: NotifyGCSRestart + client reconnection,
        node_manager.proto:383, gcs_client_reconnection_test.cc).
        Serialized: concurrent failed callers piggyback on one reconnect
        instead of racing N connections (and N pubsub registrations)."""
        if self._gcs_reconnect_lock is None:
            self._gcs_reconnect_lock = asyncio.Lock()
        async with self._gcs_reconnect_lock:
            if self.gcs is not None and not self.gcs.closed:
                return   # a concurrent caller already reconnected
            if self._shutdown:
                raise rpc.ConnectionLost("worker is shutting down")
            logger.warning("GCS connection lost; reconnecting")
            self.gcs = await rpc.connect(self.gcs_address,
                                         handlers={"pubsub": self.h_pubsub},
                                         name="->gcs", retries=30)
            for ch in sorted(self._subscribed_channels):
                try:
                    await self.gcs.call("subscribe", channel=ch)
                except Exception:
                    logger.exception("resubscribe %s failed", ch)

    async def gcs_call_async(self, method, **kw):
        """GCS call that survives one GCS restart (drivers buffer through
        a restart instead of failing)."""
        try:
            return await self.gcs.call(method, **kw)
        except (rpc.ConnectionLost, ConnectionError):
            await self._reconnect_gcs()
            return await self.gcs.call(method, **kw)

    # -------------------------------------------------- ownership bookkeeping
    @off_loop(lock="_ref_lock")
    def _register_owned(self, oid: bytes, lineage=None, complete=False,
                        contained=None):
        """Publish a fully-built owned entry in ONE dict store. Callers run
        on user threads as well as the loop (off-loop puts, threadsafe task
        submission); a single assignment is atomic under the GIL, so
        loop-side readers never observe a half-initialized entry."""
        entry = {"borrowers": set(), "submitted": 0,
                 "lineage": lineage, "location": None,
                 "complete": complete}
        if contained is not None:
            entry["contained"] = contained
        # rtlint: disable=RT003 — single GIL-atomic publish of a fully
        # built entry (see docstring); taking _ref_lock here would put a
        # lock on every put's hot path for no added safety
        self.owned[oid] = entry
        return entry

    def h_add_borrow(self, conn, oid: bytes, borrower: str):
        entry = self.owned.get(oid)
        if entry is not None:
            entry["borrowers"].add(borrower)
        return True

    def h_remove_borrow(self, conn, oid: bytes, borrower: str):
        entry = self.owned.get(oid)
        if entry is not None:
            entry["borrowers"].discard(borrower)
            self._maybe_free(oid)
        return True

    def h_object_located(self, conn, oid: bytes, node_id: str):
        entry = self.owned.get(oid)
        if entry is not None:
            entry["location"] = node_id
        return True

    # ----------------------------------------------------------------- put
    # The put hot path runs ENTIRELY on the calling thread (reference:
    # plasma writes happen on the caller with pickle-5 out-of-band buffers,
    # ray paper §4.2): cloudpickle serialization, the spill-pressure check,
    # store.create, the (GIL-free, chunked) arena copy and seal never touch
    # the owner event loop. The loop is only involved for the rare blocking
    # spill RPC and for waking any asyncio waiters on the object event.
    @off_loop(lock="_ref_lock")
    def put_local(self, value) -> ObjectRef:
        """Synchronous put (callable from user threads AND from task code
        executing inline on the loop — nothing here blocks on the loop)."""
        s = serialization.serialize(value)
        self._spill_pressure_sync(s)
        return self._put_serialized(s)

    async def put_async(self, value) -> ObjectRef:
        s = serialization.serialize(value)
        await self._spill_pressure_async(s)
        return self._put_serialized(s)

    @off_loop(lock="_ref_lock")
    def _put_serialized(self, s: serialization.SerializedObject) -> ObjectRef:
        task_id = ids.new_task_id(ids.job_id_from_int(self.job_id))
        oid = ids.object_id_for_put(task_id, next(self._put_counter))
        # pin objects referenced from inside the stored value for the stored
        # value's lifetime (the reference pins nested refs the same way,
        # reference_count.h AddNestedObjectIds)
        self._register_owned(oid, complete=True,
                             contained=list(s.contained_refs))
        self._store_serialized(oid, s)
        return ObjectRef(oid, self.address)

    @off_loop(lock="_ref_lock")
    def _refresh_spill_probe(self) -> None:  # rtlint: disable=RT003 — amortized probe: a racing refresh only re-reads store stats; fields are advisory
        """Re-read store usage for the spill-pressure check (the native
        read is a lock-free seqlock snapshot, but even the ctypes hop is
        too much per put — so it runs every N puts, not every put)."""
        st = self.store.stats()
        self._spill_capacity = st["capacity"]
        self._spill_bytes_in_use = st["bytes_in_use"]
        self._spill_local_bytes = 0
        self._spill_probe_left = cfg.spill_probe_interval_puts

    @off_loop(lock="_ref_lock")
    def _needs_spill(self, s: serialization.SerializedObject) -> bool:
        """Under memory pressure, spill sealed objects to disk before this
        create LRU-evicts them irrecoverably (reference: plasma creates
        wait on spilling, create_request_queue.h). The probe is amortized:
        capacity is cached at first use and bytes_in_use refreshed every
        spill_probe_interval_puts puts, with this worker's own put bytes
        accounted locally in between."""
        if s.is_inline() or self.store is None or self.node_conn is None:
            return False
        try:
            size = s.data_size()
            cap = self._spill_capacity
            if cap is None or self._spill_probe_left <= 0 or \
                    self._spill_local_bytes > 0.1 * (cap or 1):
                self._refresh_spill_probe()
                cap = self._spill_capacity
            self._spill_probe_left -= 1
            self._spill_local_bytes += size
            est = self._spill_bytes_in_use + self._spill_local_bytes
            return bool(cap) and est + size > 0.7 * cap
        except Exception:
            return False

    def _spill_pressure_sync(self, s: serialization.SerializedObject):
        if not self._needs_spill(s):
            return
        try:
            if threading.get_ident() == self._loop_thread_ident:
                # on the loop (inline-executed task code): blocking on our
                # own loop would deadlock — kick the spill and let this
                # create ride LRU eviction if it still can't fit
                self._spawn(self.node_conn.call("spill_now"))
            else:
                asyncio.run_coroutine_threadsafe(
                    self.node_conn.call("spill_now"), self.loop).result()
        except Exception:
            pass

    async def _spill_pressure_async(self, s: serialization.SerializedObject):
        if not self._needs_spill(s):
            return
        try:
            await self.node_conn.call("spill_now")
        except Exception:
            pass

    def _create_with_spill_retry(self, oid: bytes, data_size: int,
                                 meta_size: int):
        """store.create with one spill-backed second chance: an
        arena-full MemoryError asks the node manager to spill sealed
        objects to disk and retries, so workloads larger than the object
        store (streaming shuffle sub-blocks) land via spill instead of
        falling back to unbounded worker-heap copies."""
        try:
            return self.store.create(oid, data_size, meta_size)
        except MemoryError:
            # arena full: the cached pressure snapshot is clearly stale
            try:
                self._refresh_spill_probe()
            except Exception:
                pass
            if self.node_conn is not None:
                try:
                    if threading.get_ident() == self._loop_thread_ident:
                        # executing ON the loop: blocking would deadlock —
                        # kick the spill and retry on LRU eviction alone
                        self._spawn(self.node_conn.call("spill_now"))
                    else:
                        asyncio.run_coroutine_threadsafe(
                            self.node_conn.call("spill_now"),
                            self.loop).result(timeout=30)
                except Exception:
                    pass
            return self.store.create(oid, data_size, meta_size)

    @off_loop(lock="_ref_lock")
    def _store_serialized(self, oid: bytes, s: serialization.SerializedObject):
        # memory_store publishes below are single GIL-atomic dict stores of
        # fully built tuples — loop-side readers see old-or-new, never torn
        if s.is_inline() or self.store is None:
            # rtlint: disable=RT003 — GIL-atomic publish (see above)
            self.memory_store[oid] = ("wire",) + s.to_wire()
        else:
            try:
                meta = s.store_meta()
                bufs = self._create_with_spill_retry(oid, s.data_size(),
                                                     len(meta))
                if bufs is not None:
                    try:
                        data, meta_view = bufs
                        s.write_to(data)
                        meta_view[:] = meta
                    except BaseException:
                        # never leave a CREATED-but-unsealed object behind
                        # for gc_unsealed to find minutes later
                        self.store.abort(oid)
                        raise
                    self.store.seal(oid)
                # rtlint: disable=RT003 — GIL-atomic publish (see above)
                self.memory_store[oid] = ("shm",)
                entry = self.owned.get(oid)
                if entry is not None:
                    entry["location"] = self.node_id
                if ledger.enabled() and bufs is not None:
                    # provenance for the object-lifetime ledger: one
                    # record covers create+seal (current_task_id is a
                    # loop-side field read advisorily from put threads).
                    # A failure here must never trip the wire fallback
                    # below — the shm put already succeeded.
                    try:
                        span = self.store.is_span(oid)
                    except OSError:
                        span = False
                    tid = self.current_task_id
                    ledger.record_put(
                        oid, size=s.data_size(), meta_size=len(meta),
                        owner=self.address, owner_worker=self.worker_id,
                        node_id=self.node_id,
                        task_id=tid.hex() if tid else None,
                        is_span=span)
            except Exception:
                logger.exception("shm put failed; falling back to memory store")
                # rtlint: disable=RT003 — GIL-atomic publish (see above)
                self.memory_store[oid] = ("wire",) + s.to_wire()
        ev = self.object_events.pop(oid, None)
        if ev is not None:
            # asyncio.Event is not thread-safe: waiters park on the loop
            if threading.get_ident() == self._loop_thread_ident:
                ev.set()
            else:
                try:
                    self.loop.call_soon_threadsafe(ev.set)
                except RuntimeError:
                    pass   # loop closing during shutdown

    # ----------------------------------------------------------------- get
    def get_local(self, refs, timeout: Optional[float] = None):
        return asyncio.run_coroutine_threadsafe(
            self.get_many_async(refs, timeout), self.loop).result()

    async def get_many_async(self, refs: List[ObjectRef],
                             timeout: Optional[float] = None):
        # OWNED refs COMPLETE passively (executors push results/locations
        # to the owner; waiting just parks on a completion event), so
        # completion is awaited sequentially instead of gather's
        # one-asyncio.Task-per-ref — measurable at bench throughput
        # (200-ref batches). Anything needing ACTIVE work — a borrowed
        # ref's remote fetch, or an owned result that completed onto
        # ANOTHER node's store — gets an eager task so transfers overlap
        # instead of serializing one pull at a time.
        async def _all():
            n = len(refs)
            out = [None] * n
            tasks: Dict[int, "asyncio.Future"] = {}
            try:
                for i, r in enumerate(refs):
                    if r.id not in self.owned:
                        tasks[i] = asyncio.ensure_future(self.get_async(r))
                for i, r in enumerate(refs):
                    if i in tasks:
                        continue
                    entry = self.owned.get(r.id)
                    while entry is not None and not entry.get("complete"):
                        ev = self.object_events.setdefault(
                            r.id, asyncio.Event())
                        await ev.wait()
                        entry = self.owned.get(r.id)
                    loc = self.memory_store.get(r.id)
                    if loc is not None and loc[0] == "loc" \
                            and loc[1] != self.node_id:
                        tasks[i] = asyncio.ensure_future(self.get_async(r))
                    else:
                        out[i] = await self.get_async(r)
                for i, t in list(tasks.items()):
                    out[i] = await t
                    del tasks[i]
            finally:
                # an early error/cancellation (incl. wait_for timeout)
                # must not orphan in-flight fetch tasks
                for t in tasks.values():
                    t.cancel()
            return out
        if timeout is None:
            return await _all()
        return await asyncio.wait_for(_all(), timeout)

    async def get_async(self, ref: ObjectRef):
        val, is_exc = await self._resolve(ref)
        if is_exc:
            raise val
        return val

    # ------------------------------------------------- lineage reconstruction
    async def _node_is_dead(self, node_id: str) -> bool:
        """GCS-verified liveness (authoritative node table)."""
        try:
            nodes = await self.gcs_call_async("get_all_nodes")
        except (rpc.RpcError, rpc.ConnectionLost, ConnectionError):
            return False   # can't verify -> don't destroy state
        for n in nodes:
            if n.get("node_id") == node_id:
                return not n.get("alive", False)
        return True        # unknown to the GCS: gone

    async def _recover_object(self, oid: bytes) -> bool:
        """Re-execute the creating task of a lost object (reference:
        ObjectRecoveryManager::RecoverObject, object_recovery_manager.h:41).
        Returns True if a reconstruction attempt was started (caller should
        re-wait on the object), False if the object is unrecoverable."""
        entry = self.owned.get(oid)
        if entry is None:
            return False
        lineage = entry.get("lineage")
        if not lineage:
            return False
        fut = entry.get("recovering")
        if fut is not None:
            # another getter already triggered reconstruction — piggyback
            await fut
            return True
        if lineage["attempts"] >= cfg.lineage_max_depth:
            logger.warning("object %s exceeded %d reconstruction attempts",
                           oid.hex()[:16], cfg.lineage_max_depth)
            return False
        lineage["attempts"] += 1
        spec = lineage["spec"]
        task_id = spec["task_id"]
        logger.info("reconstructing %s via task %s (attempt %d)",
                    oid.hex()[:16], spec["name"], lineage["attempts"])
        fut = self.loop.create_future()
        return_ids = spec["return_ids"]
        for rid in return_ids:
            e = self.owned.get(rid)
            if e is not None:
                e["complete"] = False
                e["location"] = None
                e["recovering"] = fut
            self.memory_store.pop(rid, None)
        self._record_task_event(task_id, "PENDING", name=spec["name"],
                                job_id=self.job_id, type="NORMAL_TASK",
                                reconstruction=True)
        pt = PendingTask(spec, return_ids, lineage["max_retries"],
                         list(lineage["arg_refs"]))
        for r in pt.arg_refs:
            e = self.owned.get(r.id)
            if e is not None:
                e["submitted"] = e.get("submitted", 0) + 1
        self.pending_tasks[task_id] = pt
        self._enqueue_task(pt, lineage["resources"], lineage["scheduling"])

        def _done(_fut=fut, _ids=return_ids):
            for rid in _ids:
                e = self.owned.get(rid)
                if e is not None and e.get("recovering") is _fut:
                    e.pop("recovering", None)
            if not _fut.done():
                _fut.set_result(None)

        # resolve the recovery future when the task completes (or fails):
        # _complete_task/_fail_task repopulate memory_store and set+pop the
        # object events, so poll presence with an event-assisted wait (a
        # bare event wait would race a completion that happened before we
        # registered)
        async def _watch():
            # no wall deadline: clearing the recovering marker while the
            # resubmitted task is still queued would allow a duplicate
            # concurrent reconstruction of the same task_id. The task is
            # finished once its result lands in memory_store or its
            # pending entry is gone (dispatchers always _complete_task or
            # _fail_task, and failed dispatchers respawn).
            rid0 = return_ids[0]
            while (rid0 not in self.memory_store
                   and task_id in self.pending_tasks):
                ev = self.object_events.setdefault(rid0, asyncio.Event())
                try:
                    await asyncio.wait_for(ev.wait(), timeout=0.5)
                except asyncio.TimeoutError:
                    pass
            _done()

        self._spawn(_watch())
        await fut
        return True

    async def _resolve(self, ref: ObjectRef) -> Tuple[Any, bool]:
        """Returns (value, is_exception)."""
        oid = ref.id
        tried_restore = False
        while True:
            entry = self.memory_store.get(oid)
            if entry is not None:
                kind = entry[0]
                if kind == "wire":
                    return self._deser_wire(entry[1], entry[2], entry[3])
                if kind == "shm":
                    val, is_exc = self._deser_shm(oid)
                    if (is_exc and isinstance(val, ObjectLostError)
                            and not tried_restore
                            and self.node_conn is not None):
                        # evicted locally — maybe spilled to disk by the
                        # node manager; restore once and retry
                        tried_restore = True
                        try:
                            ok = await self.node_conn.call(
                                "restore_object", oid=oid)
                        except Exception:
                            ok = False
                        if ok:
                            self.memory_store[oid] = ("shm",)
                            continue
                    if (is_exc and isinstance(val, ObjectLostError)
                            and await self._recover_object(oid)):
                        tried_restore = False
                        continue
                    return val, is_exc
                if kind == "loc":
                    node_id = entry[1]
                    if node_id == self.node_id:
                        self.memory_store[oid] = ("shm",)
                        continue
                    try:
                        await self._pull_to_local(oid, node_id)
                    except Exception as e:
                        # holding node gone. Owner: re-execute the
                        # creating task from lineage. Borrower: report
                        # the loss to the owner, who reconstructs and
                        # replies with a fresh status.
                        self.memory_store.pop(oid, None)
                        if oid in self.owned:
                            if await self._recover_object(oid):
                                continue
                            return ObjectLostError(
                                f"{oid.hex()[:16]} lost with node "
                                f"{node_id[:12]}: {e}"), True
                        owner = ref.owner_address
                        if owner and owner != self.address:
                            try:
                                resp = await self.pool.call(
                                    owner, "wait_object", oid=oid,
                                    lost_on=node_id)
                            except (rpc.RpcError, rpc.ConnectionLost,
                                    ConnectionError) as e2:
                                return ObjectLostError(
                                    f"owner unreachable during recovery: "
                                    f"{e2}"), True
                            err = self._apply_wait_object_resp(oid, resp)
                            if err is not None:
                                return err
                            continue
                        return ObjectLostError(
                            f"{oid.hex()[:16]} lost with node "
                            f"{node_id[:12]}: {e}"), True
                    self.memory_store[oid] = ("shm",)
                    continue
            if self.store is not None and self.store.contains(oid):
                self.memory_store[oid] = ("shm",)
                continue
            if oid in self.owned:
                # we own it but it's not complete yet: wait for task completion
                ev = self.object_events.setdefault(oid, asyncio.Event())
                await ev.wait()
                continue
            # borrowed: ask the owner
            owner = ref.owner_address
            if not owner or owner == self.address:
                ev = self.object_events.setdefault(oid, asyncio.Event())
                await ev.wait()
                continue
            try:
                resp = await self.pool.call(owner, "wait_object", oid=oid)
            except (rpc.RpcError, rpc.ConnectionLost, ConnectionError) as e:
                return ObjectLostError(
                    f"owner {owner} unreachable for {oid.hex()[:16]}: {e}"), True
            err = self._apply_wait_object_resp(oid, resp)
            if err is not None:
                return err

    def _apply_wait_object_resp(self, oid: bytes, resp: Dict):
        """Record a wait_object reply into the local memory store; returns
        an (error, True) tuple for a lost object, else None."""
        status = resp["status"]
        if status == "inline":
            self.memory_store[oid] = ("wire", resp["kind"], resp["pkl"],
                                      resp["bufs"])
            return None
        if status == "location":
            self.memory_store[oid] = ("loc", resp["node_id"])
            return None
        return ObjectLostError(resp.get("reason", "object lost")), True

    def _deser_wire(self, kind, pkl, bufs):
        try:
            return serialization.deserialize_wire(kind, pkl, bufs), False
        except TaskError as e:
            return e.cause if isinstance(e.cause, BaseException) else e, True
        except BaseException as e:
            return e, True

    def _deser_shm(self, oid):
        buf = self.store.get(oid)
        if buf is None:
            self.memory_store.pop(oid, None)
            return ObjectLostError(f"{oid.hex()[:16]} evicted"), True
        # Zero-copy views embedded in the value keep the store pin alive
        # through the buffer-protocol chain (see _PinnedRegion): the pin is
        # released when the last derived view is collected, so dropping the
        # value frees arena space even while the ObjectRef is still held —
        # a later re-get re-reads or restores from spill.
        try:
            val = serialization.deserialize_from_store(buf.data, buf.metadata)
            return val, False
        except TaskError as e:
            return e.cause if isinstance(e.cause, BaseException) else e, True
        except BaseException as e:
            return e, True
        finally:
            buf.close()

    async def _pull_to_local(self, oid: bytes, node_id: str):
        for attempt in range(5):
            try:
                await self.node_conn.call("pull_object", oid=oid,
                                          node_id=node_id)
                return
            except rpc.RpcError:
                await asyncio.sleep(0.05 * (attempt + 1))
        await self.node_conn.call("pull_object", oid=oid, node_id=node_id)

    async def h_wait_object(self, conn, oid: bytes, lost_on: str = None):
        """Owner-side: serve value or location to a borrower (reference:
        core_worker GetObjectStatus / future_resolver.h). ``lost_on`` is a
        borrower reporting that the named node no longer serves the
        object — if our view still points there, reconstruct from lineage
        before answering (reference: ObjectRecoveryManager pinning-or-
        reconstruct on owner, object_recovery_manager.h:41)."""
        if lost_on is not None:
            entry = self.memory_store.get(oid)
            owned = self.owned.get(oid)
            stale = ((entry is not None and entry[0] == "loc"
                      and entry[1] == lost_on)
                     or (owned is not None
                         and owned.get("location") == lost_on))
            if stale and await self._node_is_dead(lost_on):
                # verified against the GCS node table — a transient pull
                # failure from a healthy node must NOT destroy the only
                # location record (the borrower just retries)
                self.memory_store.pop(oid, None)
                if owned is not None:
                    owned["location"] = None
                if not await self._recover_object(oid):
                    return {"status": "lost",
                            "reason": f"copy on {lost_on[:12]} lost and "
                                      "not reconstructable"}
        while True:
            entry = self.memory_store.get(oid)
            if entry is not None:
                if entry[0] == "wire":
                    return {"status": "inline", "kind": entry[1],
                            "pkl": entry[2], "bufs": entry[3]}
                if entry[0] == "shm":
                    return {"status": "location", "node_id": self.node_id}
                if entry[0] == "loc":
                    return {"status": "location", "node_id": entry[1]}
            owned = self.owned.get(oid)
            if owned is not None and owned.get("location"):
                return {"status": "location", "node_id": owned["location"]}
            if owned is None:
                return {"status": "lost", "reason": "not owned / already freed"}
            ev = self.object_events.setdefault(oid, asyncio.Event())
            await ev.wait()

    def h_free_object(self, conn, oid: bytes):
        self.memory_store.pop(oid, None)
        return True

    # ---------------------------------------------------------------- wait
    async def wait_async(self, refs: List[ObjectRef], num_returns: int,
                         timeout: Optional[float]):
        # mirror the reference's contract: duplicates are rejected rather
        # than silently collapsed (ray.wait raises on duplicate refs)
        if len({r.id for r in refs}) != len(refs):
            raise ValueError("wait() expects a list of distinct ObjectRefs")
        # fast path: a LOCALLY-materialized entry ("wire" bytes in
        # memory / "shm" in the local store) means wait's fetch-local
        # contract is already satisfied — no resolve coroutine per ref,
        # which at wait([1000 ready refs]) is the whole cost (one task
        # spawn + value deserialization each). "loc" entries (value
        # lives on ANOTHER node) still go through resolve: declaring
        # them ready would skip the local fetch (and any lineage
        # reconstruction if that node died) that ray.wait's default
        # fetch_local=True promises.
        def _local(entry):
            return entry is not None and entry[0] in ("wire", "shm")

        ready_ids = {r.id for r in refs
                     if _local(self.memory_store.get(r.id))}
        if len(ready_ids) >= num_returns or len(ready_ids) == len(refs):
            ready_in_order = [r for r in refs
                              if r.id in ready_ids][:num_returns]
            taken = {r.id for r in ready_in_order}
            return (ready_in_order,
                    [r for r in refs if r.id not in taken])
        pending = {self._spawn(self._resolve(r)): r for r in refs
                   if r.id not in ready_ids}
        deadline = None if timeout is None else time.monotonic() + timeout
        while pending and len(ready_ids) < num_returns:
            tmo = None if deadline is None else max(0, deadline - time.monotonic())
            done, _ = await asyncio.wait(pending.keys(), timeout=tmo,
                                         return_when=asyncio.FIRST_COMPLETED)
            if not done:
                break
            for fut in done:
                ready_ids.add(pending.pop(fut).id)
        for fut in pending:
            fut.cancel()
        ready_in_order = [r for r in refs if r.id in ready_ids][:num_returns]
        taken = {r.id for r in ready_in_order}
        rest = [r for r in refs if r.id not in taken]
        return ready_in_order, rest

    # ---------------------------------------------------- function shipping
    def _function_key(self, pickled: bytes) -> bytes:
        return hashlib.sha1(pickled).digest()

    def _ship_function_nowait(self, func) -> bytes:
        """Register the function and start the GCS KV upload without
        awaiting it: keeping this non-blocking preserves submission order
        across tasks (an await here would let later same-function
        submissions overtake the first one in the dispatch queue).
        Executors that race the upload fetch the blob from us directly
        (h_fetch_function)."""
        pickled = getattr(func, "_rt_pickled", None)
        if pickled is None:
            pickled = cloudpickle.dumps(func)
            try:
                func._rt_pickled = pickled
            except (AttributeError, TypeError):
                pass
        fid = self._function_key(pickled)
        if fid not in self._func_blobs:
            # blob retained so executors can re-fetch from us if the GCS
            # KV copy is lost (GCS restart from a pre-ship snapshot);
            # presence doubles as the shipped-marker
            self._func_blobs[fid] = pickled
            self._func_blob_bytes += len(pickled)
            while (self._func_blob_bytes > self._func_blob_cap
                   and len(self._func_blobs) > 1):
                _, old_blob = self._func_blobs.popitem(last=False)
                self._func_blob_bytes -= len(old_blob)
            self._spawn(self.gcs_call_async(
                "kv_put", ns="funcs", key=fid, value=pickled,
                overwrite=False))
        else:
            self._func_blobs.move_to_end(fid)
        self._cache_function(fid, func)
        return fid

    def _cache_function(self, fid: bytes, func) -> None:
        cache = self._func_cache
        cache[fid] = func
        cache.move_to_end(fid)
        while len(cache) > self._func_cache_cap:
            cache.popitem(last=False)

    async def _ship_function(self, func) -> bytes:
        return self._ship_function_nowait(func)

    def h_fetch_function(self, conn, fid: bytes):
        return self._func_blobs.get(fid)

    async def _load_function_any(self, spec: Dict):
        """func_id -> cloudpickled function from GCS KV; func_ref ->
        "module:attr" import (cross-language callers name functions
        instead of shipping pickles, reference: cross_language function
        descriptors)."""
        ref = spec.get("func_ref")
        if ref:
            return _import_ref(ref)
        return await self._load_function(spec["func_id"],
                                         spec.get("owner_address"))

    async def _load_function(self, fid: bytes, owner_address: str = None):
        fn = self._func_cache.get(fid)
        if fn is not None:
            return fn
        pickled = await self.gcs_call_async("kv_get", ns="funcs", key=fid)
        if pickled is None and owner_address:
            # GCS KV lost the blob (restart from a pre-ship snapshot):
            # the owner retains every function it shipped — fetch from it
            # and repair the table for other executors
            try:
                pickled = await self.pool.call(owner_address,
                                               "fetch_function", fid=fid)
            except (rpc.RpcError, rpc.ConnectionLost, ConnectionError):
                pickled = None
            if pickled is not None:
                try:
                    await self.gcs_call_async("kv_put", ns="funcs", key=fid,
                                              value=pickled, overwrite=False)
                except Exception:
                    pass
        if pickled is None:
            raise RuntimeError(f"function {fid.hex()[:12]} not in GCS KV")
        fn = cloudpickle.loads(pickled)
        self._cache_function(fid, fn)
        return fn

    # ------------------------------------------------------ task submission
    def submit_task(self, func, args, kwargs, num_returns=1, resources=None,
                    max_retries=None, scheduling=None,
                    name=None, runtime_env=None) -> List[ObjectRef]:
        return asyncio.run_coroutine_threadsafe(
            self.submit_task_async(func, args, kwargs, num_returns, resources,
                                   max_retries, scheduling, name, runtime_env),
            self.loop).result()

    def _trace_fields(self) -> Dict[str, Optional[str]]:
        """New span chained under the caller's context: a task submitted
        from inside another task inherits its trace id and points its
        parent at the enclosing task's span. The enclosing context comes
        from the executing thread (sync methods) or the coroutine's
        contextvar (async methods) — never shared instance state."""
        ctx = getattr(_exec_tls, "trace", None) or _trace_ctx.get()
        trace_id, parent = ctx if ctx else (None, None)
        return {"trace_id": trace_id or self._root_trace_id,
                "span_id": ids.span_id(),
                "parent_span_id": parent}

    def _build_task_spec(self, func, args, kwargs, num_returns, name):
        """Caller-thread-safe part of task submission: ids + arg encoding
        (ids are urandom-based; serialization touches no loop state)."""
        task_id = ids.new_task_id(ids.job_id_from_int(self.job_id))
        return_ids = [ids.object_id_for_return(task_id, i)
                      for i in range(1, num_returns + 1)]
        arg_refs: List[ObjectRef] = []
        spec = {
            "task_id": task_id, "job_id": self.job_id,
            "name": name or getattr(func, "__name__", "task"),
            "args": [_encode_arg(a, arg_refs.append, self) for a in args],
            "kwargs": {k: _encode_arg(v, arg_refs.append, self)
                       for k, v in (kwargs or {}).items()},
            "return_ids": return_ids, "owner_address": self.address,
            "owner_node": self.node_id,
            **self._trace_fields(),
        }
        refs = [ObjectRef(rid, self.address) for rid in return_ids]
        return spec, return_ids, arg_refs, refs

    def submit_task_threadsafe(self, func, args, kwargs, num_returns=1,
                               resources=None, max_retries=None,
                               scheduling=None, name=None,
                               runtime_env=None) -> List[ObjectRef]:
        """Fire-and-forget submission from a user thread: the refs come
        back without a loop round trip (submission is local-fast like the
        reference's SubmitTask; errors surface through the refs)."""
        spec, return_ids, arg_refs, refs = self._build_task_spec(
            func, args, kwargs, num_returns, name)

        self._enqueue_submit(
            self._kickoff_task_submit, func, spec, return_ids, arg_refs,
            resources, max_retries, scheduling, runtime_env)
        return refs

    def _kickoff_task_submit(self, func, spec, return_ids, arg_refs,
                             resources, max_retries, scheduling, runtime_env):
        self._spawn(self._finish_task_submit(
            func, spec, return_ids, arg_refs, resources, max_retries,
            scheduling, runtime_env))

    async def submit_task_async(self, func, args, kwargs, num_returns=1,
                                resources=None, max_retries=None,
                                scheduling=None, name=None,
                                runtime_env=None) -> List[ObjectRef]:
        spec, return_ids, arg_refs, refs = self._build_task_spec(
            func, args, kwargs, num_returns, name)
        await self._finish_task_submit(func, spec, return_ids, arg_refs,
                                       resources, max_retries, scheduling,
                                       runtime_env)
        return refs

    async def _finish_task_submit(self, func, spec, return_ids, arg_refs,
                                  resources, max_retries, scheduling,
                                  runtime_env):
        """Loop-side completion of a task submission. Failures surface on
        the return refs (the submitting thread has already moved on)."""
        resources = dict(resources or {})
        if not resources:
            resources = {"CPU": 1.0}
        if max_retries is None:
            max_retries = cfg.task_max_retries
        # Lineage: retain the creating task so a lost shm copy can be
        # re-executed (reference: ObjectRecoveryManager
        # object_recovery_manager.h:41; spec retained by TaskManager,
        # task_manager.h:208). Holding arg_refs in the lineage keeps the
        # argument objects' owned entries alive for as long as any return
        # ref might need reconstruction (lineage pinning,
        # reference_count.h:64).
        lineage = {"spec": spec, "resources": dict(resources),
                   "scheduling": dict(scheduling or {}),
                   "max_retries": max_retries, "arg_refs": list(arg_refs),
                   "attempts": 0}
        for rid in return_ids:
            self._register_owned(rid, lineage=lineage, complete=False)
        pt = PendingTask(spec, return_ids, max_retries, arg_refs)
        # pin args for the task's duration
        for r in arg_refs:
            e = self.owned.get(r.id)
            if e is not None:
                e["submitted"] = e.get("submitted", 0) + 1
        self.pending_tasks[spec["task_id"]] = pt
        self._record_task_event(spec["task_id"], "PENDING",
                                name=spec["name"], job_id=self.job_id,
                                type="NORMAL_TASK")
        try:
            spec["func_id"] = self._ship_function_nowait(func)
            if runtime_env:
                spec["runtime_env"] = await self._package_runtime_env(
                    runtime_env)
            await self._resolve_dependencies(arg_refs)
        except Exception as e:
            self._fail_task(pt, RuntimeError(f"task submission failed: {e}"))
            self.pending_tasks.pop(spec["task_id"], None)
            return
        self._enqueue_task(pt, resources, scheduling or {})

    # Per-signature dispatch: tasks queue by (resources, scheduling)
    # signature and a bounded set of dispatchers each hold ONE lease and
    # run queued tasks on it serially (reference: NormalTaskSubmitter —
    # bounded in-flight lease requests + task pipelining onto granted
    # workers, normal_task_submitter.cc). Without this, N concurrent
    # submissions issue N simultaneous lease requests and the node
    # manager's waiter queue becomes the bottleneck.

    async def _resolve_dependencies(self, arg_refs: List[ObjectRef]):
        """Wait until every argument object is complete BEFORE the task
        occupies a lease (reference: DependencyResolver in
        NormalTaskSubmitter, transport/dependency_resolver.h — args
        resolve owner-side so leased workers never block on upstream
        tasks; without this, dependent tasks can exhaust the lease pool
        and deadlock behind their own dependencies)."""
        for r in arg_refs:
            entry = self.owned.get(r.id)
            if entry is not None:
                while not entry.get("complete"):
                    ev = self.object_events.setdefault(r.id, asyncio.Event())
                    await ev.wait()
                    entry = self.owned.get(r.id)
                    if entry is None:
                        break
            elif r.owner_address and r.owner_address != self.address:
                try:
                    await self.pool.call(r.owner_address, "wait_object",
                                         oid=r.id)
                except (rpc.RpcError, rpc.ConnectionLost, ConnectionError):
                    pass   # the executor surfaces the fetch error

    def _enqueue_task(self, pt: PendingTask, resources, scheduling):
        from ray_tpu._private.runtime_env_plugins import proc_env_of
        renv = pt.spec.get("runtime_env")
        env_hash = self._runtime_env_hash(renv)
        sig = self._lease_sig(resources, scheduling, env_hash)
        st = self._sig_queues.get(sig)
        if st is None:
            st = {"queue": __import__("collections").deque(),
                  "dispatchers": 0, "busy": 0, "grants": 0,
                  "resources": resources,
                  "scheduling": scheduling, "env_hash": env_hash,
                  "proc_env": proc_env_of(renv)}
            self._sig_queues[sig] = st
        st["queue"].append(pt)
        self._maybe_spawn_dispatcher(sig, st)

    def _maybe_spawn_dispatcher(self, sig, st):
        # Spawn when queued tasks outnumber FREE dispatchers (dispatchers
        # whose current task is in flight count as busy — a running task
        # may block on a queued task's result, so leaving work behind a
        # busy dispatcher can deadlock a dependency chain), and always
        # when an idle lease can serve the task immediately — otherwise a
        # dispatcher blocked in a server-side lease wait would serialize
        # fresh submissions behind grant latency.
        free = st["dispatchers"] - st["busy"]
        if (st["dispatchers"] < cfg.max_dispatchers_per_sig
                and (len(st["queue"]) > free
                     or self._idle_leases.get(sig))):
            st["dispatchers"] += 1
            self._spawn(self._dispatch_loop(sig, st))

    async def _dispatch_loop(self, sig, st):
        my_grants = -1
        cur_batch = 1
        try:
            while st["queue"]:
                try:
                    lease = await self._acquire_lease(
                        st["resources"], st["scheduling"],
                        st.get("env_hash"), st.get("proc_env"))
                    st["grants"] += 1
                except Exception as e:
                    if st["queue"]:
                        pt = st["queue"].popleft()
                        self._fail_task(pt, RuntimeError(
                            f"lease failed: {e}"))
                        self.pending_tasks.pop(pt.spec["task_id"], None)
                    continue
                lease_ok = True
                while st["queue"] and lease_ok:
                    # adaptive frame batching: serialize queued tasks
                    # behind THIS lease only when there is evidence no
                    # other lease is coming — i.e. no grant has landed
                    # for this signature since our last round (the
                    # 1-worker case: parked dispatchers stay parked, so
                    # the batch doubles toward task_push_batch). Any
                    # fresh grant or an idle lease resets to single-task
                    # frames so work spreads across workers/nodes
                    # (spillback, spread). Acks stream back per-task
                    if (st["grants"] != my_grants
                            or self._idle_leases.get(sig)):
                        cur_batch = 1
                    else:
                        cur_batch = min(cur_batch * 2,
                                        cfg.task_push_batch)
                    my_grants = st["grants"]
                    batch = [st["queue"].popleft()]
                    # streaming tasks own their frame: the PARTIAL slots
                    # of push_task_streaming carry items, not batch acks
                    if not batch[0].spec.get("streaming"):
                        while (st["queue"] and len(batch) < cur_batch
                               and not st["queue"][0].spec.get("streaming")):
                            batch.append(st["queue"].popleft())
                    st["busy"] += 1
                    # work remains behind us: make sure it isn't stuck
                    # waiting for this (possibly dependent) task
                    if st["queue"]:
                        self._maybe_spawn_dispatcher(sig, st)
                    try:
                        lease_ok = await self._run_on_lease(batch, lease,
                                                            st)
                    except Exception as e:
                        # unexpected failure must not strand the queue:
                        # fail these tasks, drop the (suspect) lease, keep
                        # draining with a fresh one
                        logger.exception("dispatcher error running %s",
                                         batch[0].spec.get("name"))
                        for pt in batch:
                            self._fail_task(pt, RuntimeError(
                                f"dispatch failed: {e}"))
                            self.pending_tasks.pop(pt.spec["task_id"],
                                                   None)
                        await self._drop_lease(lease, dead=True)
                        lease_ok = False
                    finally:
                        st["busy"] -= 1
                if lease_ok:
                    try:
                        await self._return_lease(lease)
                    except Exception:
                        logger.exception("lease return failed")
        finally:
            st["dispatchers"] -= 1
            if st["queue"] and st["dispatchers"] == 0 and not self._shutdown:
                # we were the last dispatcher and tasks remain (e.g. an
                # exception escaped above): respawn so callers never hang
                # (never during shutdown: a task spawned while stop_async
                # is cancelling would escape its victim snapshot)
                st["dispatchers"] += 1
                self._spawn(self._dispatch_loop(sig, st))
            elif not st["queue"] and st["dispatchers"] == 0:
                self._sig_queues.pop(sig, None)

    async def _run_on_lease(self, pts: List[PendingTask], lease, st) -> bool:
        """Run a batch of tasks on a held lease (one frame, serial
        execution on the worker). Returns False if the lease died (caller
        must stop using it). Each pending_tasks entry stays alive only
        while its task can still run (requeued for retry)."""
        run = []
        for pt in pts:
            if pt.cancelled:
                self._fail_task(pt, TaskCancelledError(pt.spec["name"]))
                self.pending_tasks.pop(pt.spec["task_id"], None)
            else:
                run.append(pt)
        if not run:
            return True

        def on_part(idx, ok, payload):
            pt = run[idx]
            if pt.done:
                return
            if ok:
                self._complete_task(pt, payload)
            else:
                self._fail_task(pt, RuntimeError(
                    f"{payload[0]}: {payload[1]}"
                    if isinstance(payload, list) else str(payload)))
            self.pending_tasks.pop(pt.spec["task_id"], None)

        try:
            for pt in run:
                if lease.resource_ids:
                    pt.spec["accelerator_ids"] = lease.resource_ids
                pt.current_worker = lease.worker_address
            conn = await self.pool.get(lease.worker_address)
            if len(run) == 1 and run[0].spec.get("streaming"):
                # streaming generator: PARTIALs are items; the lease is
                # held (task running) until the final response
                pt = run[0]
                gen = self._generators.get(pt.spec["task_id"])
                if gen is None:
                    # closed before dispatch: don't run it at all
                    self._fail_task(pt, TaskCancelledError(
                        pt.spec.get("name", "stream")))
                    self.pending_tasks.pop(pt.spec["task_id"], None)
                    return True
                gen._worker_address = lease.worker_address
                resp = await conn.call_start_parts(
                    "push_task_streaming", {"spec": pt.spec},
                    functools.partial(self._on_gen_part, pt))
                self._complete_task(pt, resp)
                if gen is not None:
                    gen._finish()
                self._generators.pop(pt.spec["task_id"], None)
                self.pending_tasks.pop(pt.spec["task_id"], None)
            elif len(run) == 1:
                resp = await conn.call("push_task", spec=run[0].spec)
                self._complete_task(run[0], resp)
                self.pending_tasks.pop(run[0].spec["task_id"], None)
            else:
                # one frame out; per-task acks stream back as PARTIALs
                # (a fast task completes the moment IT finishes, and a
                # worker death only retries unacked tasks)
                await conn.call_start_parts(
                    "push_tasks", {"specs": [p.spec for p in run]},
                    on_part)
        except (rpc.ConnectionLost, ConnectionError, rpc.RpcError) as e:
            await self._drop_lease(lease, dead=True)
            stragglers = [pt for pt in run if not pt.done]
            if isinstance(e, rpc.RpcError):
                for pt in stragglers:
                    self._fail_task(pt, RuntimeError(f"push failed: {e}"))
                    self.pending_tasks.pop(pt.spec["task_id"], None)
                return False
            retried = 0
            for pt in reversed(stragglers):   # keep submission order
                if pt.retries_left > 0:
                    pt.retries_left -= 1
                    st["queue"].appendleft(pt)   # keep pending for retry
                    retried += 1
                else:
                    self._fail_task(pt, WorkerCrashedError(
                        f"worker died running {pt.spec['name']}"))
                    self.pending_tasks.pop(pt.spec["task_id"], None)
            if retried:
                logger.warning("worker died; retrying %d task(s)", retried)
            return False
        return True

    def _complete_task(self, pt: PendingTask, resp: Dict):
        self._record_task_event(pt.spec["task_id"], "FINISHED")
        for rid, ret in zip(pt.return_ids, resp["returns"]):
            entry = self.owned.get(rid)
            if ret[0] == "wire":
                self.memory_store[rid] = ("wire", ret[1], ret[2], ret[3])
            else:  # ["shm", node_id]
                self.memory_store[rid] = ("loc", ret[1])
                if entry is not None:
                    entry["location"] = ret[1]
            if entry is not None:
                entry["complete"] = True
            ev = self.object_events.pop(rid, None)
            if ev is not None:
                ev.set()
        self._unpin_args(pt)

    # ------------------------------------------------ streaming generators
    # (owner side: each PARTIAL from push_task_streaming materializes one
    # brand-new owned object; consumption acks open the executor's window)

    def _on_gen_part(self, pt: PendingTask, idx: int, ok: bool, payload):
        gen = self._generators.get(pt.spec["task_id"])
        if not ok:
            if gen is not None:
                gen._fail(RuntimeError(
                    f"{payload[0]}: {payload[1]}"
                    if isinstance(payload, list) else str(payload)))
            return
        if gen is None:
            # stream closed while this item was in flight: registering
            # it would leak an owned entry no ref can ever free
            return
        rid = ids.object_id_for_return(pt.spec["task_id"], 2 + idx)
        self._register_owned(rid, complete=True)
        entry = self.owned.get(rid)
        if payload[0] == "wire":
            self.memory_store[rid] = ("wire", payload[1], payload[2],
                                      payload[3])
        else:   # ["shm", node_id]
            self.memory_store[rid] = ("loc", payload[1])
            if entry is not None:
                entry["location"] = payload[1]
        if gen is not None:
            gen._push(ObjectRef(rid, self.address))

    def _gen_send_ack(self, gen) -> None:
        """Consumption ack (loop side): opens the executor's in-flight
        window. Fire-and-forget — a lost ack only delays the window until
        the next one."""
        if gen._worker_address is None or gen._done:
            return
        self._spawn(self._gen_ack_async(gen._worker_address,
                                        gen._task_id, gen._consumed))

    async def _gen_ack_async(self, address: str, task_id: bytes,
                             consumed: int):
        try:
            conn = await self.pool.get(address)
            conn.call_start_nowait("generator_ack",
                                   {"task_id": task_id,
                                    "consumed": consumed})
        except Exception:
            pass

    async def _gen_close_async(self, gen):
        """Consumer walked away: stop the producer, drop unconsumed
        items (their owned entries free via normal refcounting once the
        local refs die with the deque)."""
        gen._finish()
        gen._items.clear()
        self._generators.pop(gen._task_id, None)
        if gen._worker_address:
            try:
                conn = await self.pool.get(gen._worker_address)
                conn.call_start_nowait("generator_close",
                                       {"task_id": gen._task_id})
            except Exception:
                pass
        else:
            # not dispatched yet: cancel it in the queue (the dispatch
            # paths also skip tasks whose generator is gone)
            try:
                await self.cancel_task_async(gen._completed_ref)
            except Exception:
                pass

    def submit_streaming_task_threadsafe(
            self, func, args, kwargs, resources=None, scheduling=None,
            name=None, runtime_env=None, backpressure=None):
        """num_returns='streaming' submission: returns an
        ObjectRefGenerator instead of refs. Streaming tasks never retry
        (stated divergence — see generator.py docstring)."""
        from ray_tpu._private.generator import ObjectRefGenerator
        spec, return_ids, arg_refs, refs = self._build_task_spec(
            func, args, kwargs, 1, name)
        spec["streaming"] = True
        if backpressure:
            spec["backpressure"] = int(backpressure)
        gen = ObjectRefGenerator(self, spec["task_id"], refs[0])
        self._generators[spec["task_id"]] = gen
        self._enqueue_submit(
            self._kickoff_task_submit, func, spec, return_ids, arg_refs,
            resources, 0, scheduling, runtime_env)
        return gen

    def submit_streaming_actor_task_threadsafe(
            self, actor_id: str, method: str, args, kwargs,
            concurrency_group=None, backpressure=None):
        from ray_tpu._private.generator import ObjectRefGenerator
        spec, return_ids, arg_refs, refs = self._build_actor_task_spec(
            actor_id, method, args, kwargs, 1, concurrency_group)
        spec["streaming"] = True
        if backpressure:
            spec["backpressure"] = int(backpressure)
        gen = ObjectRefGenerator(self, spec["task_id"], refs[0])
        self._generators[spec["task_id"]] = gen
        self._enqueue_submit(self._finish_actor_submit, spec, return_ids,
                             arg_refs, 0)
        return gen

    def _fail_task(self, pt: PendingTask, exc: BaseException):
        self._record_task_event(pt.spec["task_id"], "FAILED",
                                error=f"{type(exc).__name__}: {exc}")
        gen = self._generators.pop(pt.spec["task_id"], None)
        if gen is not None:
            gen._fail(exc)
        s = serialization.serialize_error(exc)
        kind, pkl, bufs = s.to_wire()
        for rid in pt.return_ids:
            self.memory_store[rid] = ("wire", kind, pkl, bufs)
            entry = self.owned.get(rid)
            if entry is not None:
                entry["complete"] = True
            ev = self.object_events.pop(rid, None)
            if ev is not None:
                ev.set()
        self._unpin_args(pt)

    def _unpin_args(self, pt: PendingTask):
        if pt.done:
            return
        pt.done = True
        for r in pt.arg_refs:
            e = self.owned.get(r.id)
            if e is not None:
                e["submitted"] = max(0, e.get("submitted", 0) - 1)
                self._maybe_free(r.id)

    async def broadcast_async(self, ref: ObjectRef, node_ids: List[str]):
        """Owner-directed broadcast: fan a shm-resident object out to
        `node_ids` through the node managers' binomial push tree (gang arg
        feeding / weight distribution; reference has point-to-point
        Push/Pull only, object_manager.h:117)."""
        entry = self.owned.get(ref.id)
        loc = entry.get("location") if entry is not None else None
        if loc is None and self.store is not None \
                and self.store.contains(ref.id):
            loc = self.node_id
        if loc is None:
            raise ValueError(
                "broadcast requires a sealed shm object (inline objects "
                "travel with their task specs)")
        targets = [n for n in node_ids if n != loc]
        if not targets:
            return
        if loc == self.node_id:
            await self.node_conn.call("broadcast_object", oid=ref.id,
                                      targets=targets)
        else:
            view = await self.gcs_call_async("get_cluster_view")
            holder = view.get(loc)
            if holder is None:
                raise RuntimeError(f"holder node {loc[:12]} unknown")
            await self.pool.call(holder["address"], "broadcast_object",
                                 oid=ref.id, targets=targets)

    def _broadcast_holder_node(self, ref: ObjectRef) -> Optional[str]:
        entry = self.owned.get(ref.id)
        loc = entry.get("location") if entry is not None else None
        if loc is None and self.store is not None \
                and self.store.contains(ref.id):
            loc = self.node_id
        return loc

    async def broadcast_weights_async(self, ref: ObjectRef,
                                      node_ids: Optional[List[str]] = None,
                                      max_retries: int = 2) -> Dict:
        """Weight-distribution plane: fan `ref`'s sealed (possibly
        multi-GB spanning) object out to the target nodes through the
        node managers' binomial relay tree over the striped data plane —
        one source put, log-depth fan-out, receivers recv_into their own
        (spanning) arena allocations, zero staging copies end to end.

        A relay node dying mid-subtree surfaces at the root's await
        (the completing chunk's ack defers past the subtree); the retry
        then takes a census of who actually holds the object and
        re-broadcasts the missing shard from EVERY surviving holder in
        parallel — the tree heals around the dead relay instead of
        restarting from the single source. Nodes that left the cluster
        are dropped (membership is the GCS's problem, not the
        broadcast's). Returns {"delivered", "skipped", "retries"}.
        """
        from ray_tpu._private import events
        from ray_tpu._private.data_plane import plan_rebroadcast
        loc = self._broadcast_holder_node(ref)
        if loc is None:
            raise ValueError(
                "broadcast_weights requires a sealed shm object (inline "
                "objects travel with their task specs)")
        view = await self.gcs_call_async("get_cluster_view")
        if node_ids is None:
            node_ids = list(view)
        targets = [n for n in node_ids if n != loc and n in view]
        skipped = [n for n in node_ids if n != loc and n not in view]
        nbytes = None
        if self.store is not None and self.store.contains(ref.id):
            buf = self.store.get(ref.id)
            if buf is not None:
                nbytes = len(buf.data)
                buf.close()

        async def _census(nodes):
            """(have, missing, gone) among `nodes` right now."""
            have, missing, gone = [], [], []
            async def probe(n):
                try:
                    r = await self.pool.call(view[n]["address"],
                                             "has_object", oid=ref.id)
                    (have if (r or {}).get("in_store") or
                     (r or {}).get("spilled") else missing).append(n)
                except Exception:
                    gone.append(n)
            await asyncio.gather(*[probe(n) for n in nodes])
            return have, missing, gone

        async def _bcast_from(holder_node, tgts):
            if holder_node == self.node_id:
                await self.node_conn.call("broadcast_object", oid=ref.id,
                                          targets=tgts)
            else:
                await self.pool.call(view[holder_node]["address"],
                                     "broadcast_object", oid=ref.id,
                                     targets=tgts)

        with events.record_span(
                "store.broadcast", category="store",
                object_id=ref.id.hex()[:16], bytes=nbytes,
                peers=len(targets)) as span:
            retries = 0
            last_err: Optional[BaseException] = None
            remaining = list(targets)
            for attempt in range(max_retries + 1):
                if not remaining:
                    break
                try:
                    if attempt == 0:
                        await self._bcast_via_holder(ref, loc, remaining,
                                                     view)
                        remaining = []
                        break
                    retries += 1
                    have, missing, gone = await _census(remaining)
                    skipped.extend(gone)
                    remaining = missing
                    if not remaining:
                        break
                    plan = plan_rebroadcast(remaining, [loc] + have)
                    await asyncio.gather(*[
                        _bcast_from(h, tgts) for h, tgts in plan])
                    remaining = []
                except Exception as e:      # noqa: BLE001 — retried below
                    last_err = e
                    logger.warning(
                        "broadcast of %s attempt %d failed (%s); "
                        "retrying via surviving holders",
                        ref.id.hex()[:16], attempt, e)
            if remaining:
                raise RuntimeError(
                    f"broadcast_weights of {ref.id.hex()[:16]} could not "
                    f"reach {len(remaining)} node(s) after {retries} "
                    f"retries") from last_err
            delivered = [n for n in targets if n not in skipped]
            span.set(delivered=len(delivered), skipped=len(skipped),
                     retries=retries)
        return {"delivered": delivered, "skipped": skipped,
                "retries": retries}

    async def _bcast_via_holder(self, ref: ObjectRef, loc: str,
                                targets: List[str], view: Dict):
        if loc == self.node_id:
            await self.node_conn.call("broadcast_object", oid=ref.id,
                                      targets=targets)
        else:
            holder = view.get(loc)
            if holder is None:
                raise RuntimeError(f"holder node {loc[:12]} unknown")
            await self.pool.call(holder["address"], "broadcast_object",
                                 oid=ref.id, targets=targets)

    async def cancel_task_async(self, ref: ObjectRef, force: bool = False):
        task_id = ids.task_id_of_object(ref.id)
        pt = self.pending_tasks.get(task_id)
        if pt is None:
            return False       # already finished (or not ours)
        pt.cancelled = True
        if pt.current_worker:
            try:
                await self.pool.call(pt.current_worker, "cancel_task",
                                     task_id=task_id, force=force)
            except Exception:
                pass
        return True

    # ---------------------------------------------------------------- leases
    def _lease_sig(self, resources: Dict, scheduling: Dict,
                   env_hash: Optional[str] = None) -> tuple:
        return (tuple(sorted(resources.items())),
                tuple(sorted((k, str(v)) for k, v in scheduling.items())),
                env_hash)

    @staticmethod
    def _runtime_env_hash(renv) -> Optional[str]:
        """Worker-pool key (shared scheme with the actor path — see
        runtime_env_plugins.runtime_env_hash)."""
        from ray_tpu._private.runtime_env_plugins import runtime_env_hash
        return runtime_env_hash(renv)

    async def _acquire_lease(self, resources: Dict, scheduling: Dict,
                             env_hash: Optional[str] = None,
                             proc_env: Optional[Dict] = None) -> Lease:
        sig = self._lease_sig(resources, scheduling, env_hash)
        pool = self._idle_leases.get(sig)
        while pool:
            lease = pool.pop()
            return lease
        target_conn = self.node_conn
        addr_chain = 0
        attempts = 0
        while True:
            try:
                resp = await target_conn.call(
                    "request_lease", resources=resources,
                    scheduling=scheduling, worker_id=self.worker_id,
                    env_hash=env_hash, proc_env=proc_env,
                    spilled=addr_chain > 0)
            except (rpc.RpcError, rpc.ConnectionLost) as e:
                # transient control-plane failure (or injected chaos):
                # back off and retry (reference: retryable lease clients,
                # normal_task_submitter.cc retry-on-raylet-unavailable)
                attempts += 1
                if attempts > 5:
                    raise
                await asyncio.sleep(0.05 * attempts)
                if target_conn is not self.node_conn and target_conn.closed:
                    target_conn = self.node_conn
                    addr_chain = 0
                continue
            if resp["status"] == "ok":
                return Lease(resp["lease_id"], resp["worker_address"],
                             resp["node_address"], sig,
                             resp.get("resource_ids"))
            if resp["status"] == "spill":
                addr_chain += 1
                if addr_chain > 8:
                    raise RuntimeError("lease spillback loop")
                target_conn = await self.pool.get(resp["spill_to"])
                continue
            raise RuntimeError(resp.get("reason", "lease denied"))

    async def _return_lease(self, lease: Lease):
        lease.last_used = time.monotonic()
        self._idle_leases.setdefault(lease.signature, []).append(lease)

    async def _drop_lease(self, lease: Lease, dead: bool = False):
        if dead:
            self.pool.invalidate(lease.worker_address)
        try:
            conn = (self.node_conn if lease.node_address == self.node_address
                    else await self.pool.get(lease.node_address))
            await conn.call("return_lease", lease_id=lease.lease_id,
                            worker_dead=dead)
        except Exception:
            pass

    async def _reap_leases(self):
        while not self._shutdown:
            await asyncio.sleep(cfg.lease_idle_timeout_s / 2)
            now = time.monotonic()
            for sig, pool in list(self._idle_leases.items()):
                keep = []
                for lease in pool:
                    if now - lease.last_used > cfg.lease_idle_timeout_s:
                        self._spawn(self._drop_lease(lease))
                    else:
                        keep.append(lease)
                self._idle_leases[sig] = keep

    # ------------------------------------------------------------ actor API
    async def create_actor_async(self, cls, init_args, init_kwargs, *,
                                 num_returns=1, resources=None, name=None,
                                 namespace=None, max_restarts=0,
                                 max_concurrency=1, scheduling=None,
                                 lifetime=None, method_names=None,
                                 runtime_env=None, concurrency_groups=None,
                                 method_groups=None) -> str:
        actor_id = ids.new_actor_id(ids.job_id_from_int(self.job_id)).hex()
        cid = await self._ship_function(cls)
        arg_refs: List[ObjectRef] = []
        spec = {
            "actor_id": actor_id, "job_id": self.job_id,
            "class_id": cid, "name": name,
            "namespace": namespace or self.namespace,
            "init_args": [_encode_arg(a, arg_refs.append, self)
                          for a in init_args],
            "init_kwargs": {k: _encode_arg(v, arg_refs.append, self)
                            for k, v in (init_kwargs or {}).items()},
            "resources": dict(resources or {"CPU": 1.0}),
            "max_restarts": max_restarts,
            "max_concurrency": max_concurrency,
            "scheduling": scheduling or {},
            "owner_address": self.address,
            "lifetime": lifetime,
            "method_names": list(method_names or []),
            "concurrency_groups": dict(concurrency_groups or {}),
            "method_groups": dict(method_groups or {}),
        }
        if runtime_env:
            spec["runtime_env"] = await self._package_runtime_env(
                runtime_env)
        st = ActorHandleState(actor_id)
        self.actor_handles[actor_id] = st
        await self._ensure_actor_subscription()
        await self.gcs_call_async("create_actor", spec=spec)
        return actor_id

    async def _ensure_actor_subscription(self):
        if getattr(self, "_subscribed_actor_channel", False):
            return
        self._subscribed_actor_channel = True
        self._subscribed_channels.add("ACTOR")
        await self.gcs_call_async("subscribe", channel="ACTOR")

    def h_pubsub(self, conn, channel: str, key: str, payload: Any):
        if channel == "LOGS":
            # worker log lines -> driver stdout with a routing prefix
            # (reference: log_monitor pubsub -> driver magic-prefix print)
            import sys
            prefix = f"({payload.get('pid')}, ip={payload.get('ip')})"
            out = sys.stderr if payload.get("stream") == "stderr" \
                else sys.stdout
            for line in payload.get("lines", []):
                print(f"{prefix} {line}", file=out)
            return None
        if channel == "ACTOR":
            st = self.actor_handles.get(key)
            if st is None:
                return
            st.state = payload["state"]
            st.death_cause = payload.get("death_cause")
            if payload["state"] == "ALIVE":
                st.address = payload["address"]
                st.ready.set()
            elif payload["state"] in ("RESTARTING", "PENDING_CREATION"):
                st.address = None
                st.ready.clear()
            elif payload["state"] == "DEAD":
                st.address = None
                st.ready.set()
        return None

    async def _actor_state(self, actor_id: str) -> ActorHandleState:
        st = self.actor_handles.get(actor_id)
        probe = st is None or not st.ready.is_set()
        if st is None:
            st = ActorHandleState(actor_id)
            self.actor_handles[actor_id] = st
        if probe:
            await self._ensure_actor_subscription()
            info = await self.gcs_call_async("get_actor_info", actor_id=actor_id)
            if info is not None:
                # don't regress a fresher pubsub update that raced us
                if not st.ready.is_set():
                    st.state = info["state"]
                    st.death_cause = info.get("death_cause")
                    if info["state"] == "ALIVE":
                        st.address = info["address"]
                        st.ready.set()
                    elif info["state"] == "DEAD":
                        st.ready.set()
        return st

    def _build_actor_task_spec(self, actor_id, method, args, kwargs,
                               num_returns, concurrency_group=None):
        task_id = ids.new_task_id(ids.job_id_from_int(self.job_id))
        return_ids = [ids.object_id_for_return(task_id, i)
                      for i in range(1, num_returns + 1)]
        arg_refs: List[ObjectRef] = []
        spec = {
            "task_id": task_id, "job_id": self.job_id, "name": method,
            "actor_id": actor_id, "method": method,
            "args": [_encode_arg(a, arg_refs.append, self) for a in args],
            "kwargs": {k: _encode_arg(v, arg_refs.append, self)
                       for k, v in (kwargs or {}).items()},
            "return_ids": return_ids, "owner_address": self.address,
            "owner_node": self.node_id,
            **self._trace_fields(),
        }
        if concurrency_group:
            spec["concurrency_group"] = concurrency_group
        refs = [ObjectRef(rid, self.address) for rid in return_ids]
        return spec, return_ids, arg_refs, refs

    def submit_actor_task_threadsafe(self, actor_id: str, method: str,
                                     args, kwargs, num_returns=1,
                                     max_task_retries=0,
                                     concurrency_group=None
                                     ) -> List[ObjectRef]:
        """Fire-and-forget actor submission from a user thread — no loop
        round trip per call. Ordering: the submit buffer is FIFO and
        _finish_actor_submit enqueues synchronously, so calls from one
        thread start in submission order (the reference's
        SequentialActorSubmitQueue guarantee)."""
        spec, return_ids, arg_refs, refs = self._build_actor_task_spec(
            actor_id, method, args, kwargs, num_returns, concurrency_group)
        self._enqueue_submit(self._finish_actor_submit, spec, return_ids,
                             arg_refs, max_task_retries)
        return refs

    async def submit_actor_task_async(self, actor_id: str, method: str,
                                      args, kwargs, num_returns=1,
                                      max_task_retries=0,
                                      concurrency_group=None
                                      ) -> List[ObjectRef]:
        spec, return_ids, arg_refs, refs = self._build_actor_task_spec(
            actor_id, method, args, kwargs, num_returns, concurrency_group)
        self._finish_actor_submit(spec, return_ids, arg_refs,
                                  max_task_retries)
        return refs

    def _finish_actor_submit(self, spec, return_ids, arg_refs,
                             max_task_retries):
        actor_id = spec["actor_id"]
        for rid in return_ids:
            self._register_owned(rid, complete=False)
        pt = PendingTask(spec, return_ids, max_task_retries, arg_refs)
        for r in arg_refs:
            e = self.owned.get(r.id)
            if e is not None:
                e["submitted"] = e.get("submitted", 0) + 1
        self._record_task_event(spec["task_id"], "PENDING",
                                name=spec["method"], job_id=self.job_id,
                                type="ACTOR_TASK", actor_id=actor_id)
        st = self.actor_handles.get(actor_id)
        if st is None:
            # borrowed handle's first use: create the state synchronously
            # so later calls enqueue behind this one in order, and kick an
            # async GCS probe to resolve the address (the sender loop
            # blocks on st.ready until it lands)
            st = ActorHandleState(actor_id)
            self.actor_handles[actor_id] = st
            self._spawn(self._actor_state(actor_id))
        if st.sender is None:
            st.sender = self._spawn(
                self._actor_sender(actor_id, st))
        pt.seq = st.seq_counter
        st.seq_counter += 1
        st.pending.append(pt)
        st.work.set()

    async def _actor_sender(self, actor_id: str, st: ActorHandleState):
        """Per-actor ordered submission pipeline: sends are serialized (so
        method calls start in submission order, the reference's
        SequentialActorSubmitQueue guarantee); responses are awaited
        concurrently so calls pipeline. Retries of calls that died with a
        connection re-enter by sequence number ahead of later fresh
        submissions."""
        while True:
            while not st.retry and not st.pending:
                st.work.clear()
                await st.work.wait()
            if st.retry:
                _, pt = heapq.heappop(st.retry)
            else:
                pt = st.pending.popleft()
            await self._resolve_dependencies(pt.arg_refs)
            while True:
                await st.ready.wait()
                if st.retry and st.retry[0][0] < pt.seq:
                    # while we were blocked, earlier in-flight calls
                    # failed into the retry heap: they must go first
                    heapq.heappush(st.retry, (pt.seq, pt))
                    _, pt = heapq.heappop(st.retry)
                if st.state == "DEAD":
                    self._fail_task(pt, ActorDiedError(
                        f"actor {actor_id[:12]} is dead: {st.death_cause}"))
                    break
                address = st.address
                try:
                    conn = await self.pool.get(address)
                except (rpc.ConnectionLost, ConnectionError) as e:
                    if not self._note_actor_conn_loss(st, address):
                        continue
                    if pt.retries_left != 0:
                        if pt.retries_left > 0:
                            pt.retries_left -= 1
                        continue
                    self._fail_task(pt, ActorDiedError(
                        f"actor {actor_id[:12]} connection lost: {e}"))
                    break
                if st.retry and st.retry[0][0] < pt.seq:
                    # pool.get suspended (fresh connection): earlier
                    # in-flight calls may have failed into the retry heap
                    # meanwhile — they must go first
                    heapq.heappush(st.retry, (pt.seq, pt))
                    _, pt = heapq.heappop(st.retry)
                    continue
                # coalesce immediately-sendable successors into one frame
                # (order preserved; only when no retry is waiting and the
                # next calls' deps are already satisfied). Per-call acks
                # stream back as PARTIALs, so batching never delays or
                # coarsens completion
                batch = [pt]
                while (not st.retry and st.pending
                       and not pt.spec.get("streaming")
                       and not st.pending[0].spec.get("streaming")
                       and len(batch) < cfg.actor_push_batch
                       and self._deps_ready(st.pending[0])):
                    batch.append(st.pending.popleft())
                if pt.spec.get("streaming") \
                        and pt.spec["task_id"] not in self._generators:
                    # closed before dispatch: skip execution entirely
                    self._fail_task(pt, TaskCancelledError(
                        pt.spec.get("name", "stream")))
                    break
                try:
                    if pt.spec.get("streaming"):
                        gen = self._generators.get(pt.spec["task_id"])
                        gen._worker_address = address
                        fut = conn.call_start_parts(
                            "push_task_streaming", {"spec": pt.spec},
                            functools.partial(self._on_gen_part, pt))
                    elif len(batch) == 1:
                        fut = conn.call_start_nowait("push_task",
                                                     {"spec": pt.spec})
                    else:
                        fut = conn.call_start_parts(
                            "push_tasks",
                            {"specs": [p.spec for p in batch]},
                            functools.partial(self._on_actor_part, batch))
                except (rpc.ConnectionLost, ConnectionError) as e:
                    if not self._note_actor_conn_loss(st, address):
                        continue
                    requeued = False
                    for p in batch:
                        if p.retries_left != 0:
                            if p.retries_left > 0:
                                p.retries_left -= 1
                            heapq.heappush(st.retry, (p.seq, p))
                            requeued = True
                        else:
                            self._fail_task(p, ActorDiedError(
                                f"actor {actor_id[:12]} connection lost:"
                                f" {e}"))
                    if requeued:
                        st.work.set()
                        continue
                    break
                # completion rides the response future's callback — no
                # task per in-flight call (reference pipelines the same
                # way, actor_task_submitter.h:75)
                fut.add_done_callback(
                    functools.partial(self._on_actor_reply, batch,
                                      actor_id, st, address))
                try:
                    await conn.maybe_drain()   # backpressure: slow peer
                except (rpc.ConnectionLost, ConnectionError):
                    pass   # the reply callback handles the failure
                break

    def _deps_ready(self, pt: "PendingTask") -> bool:
        """True when every arg ref is locally known-complete (the batch
        fast path; anything else goes through _resolve_dependencies)."""
        for r in pt.arg_refs:
            e = self.owned.get(r.id)
            if e is not None:
                if not e.get("complete"):
                    return False
            elif r.owner_address and r.owner_address != self.address:
                return False
        return True

    def _note_actor_conn_loss(self, st: ActorHandleState, address) -> bool:
        """Mark the actor's address suspect after a connection failure.
        Returns True if the caller should count this against retries."""
        self.pool.invalidate(address)
        if st.address == address and st.ready.is_set():
            st.ready.clear()
            st.state = "RESTARTING?"
        self._spawn(self._probe_actor(st.actor_id))
        return True

    def _on_actor_part(self, batch: List[PendingTask], idx: int, ok: bool,
                       payload):
        """Streamed per-call ack from a batched frame."""
        pt = batch[idx]
        if pt.done:
            return
        if ok:
            self._complete_task(pt, payload)
        else:
            self._fail_task(pt, RuntimeError(
                f"{payload[0]}: {payload[1]}" if isinstance(payload, list)
                else str(payload)))

    def _on_actor_reply(self, batch: List[PendingTask], actor_id: str,
                        st: ActorHandleState, address: str, fut):
        """Final-response callback for one frame (1..N coalesced calls):
        completes (single-call frames) or fails/requeues stragglers whose
        per-call ack never arrived."""
        exc = (asyncio.CancelledError("connection closed")
               if fut.cancelled() else fut.exception())
        if exc is None:
            if len(batch) == 1 and not batch[0].done:
                self._complete_task(batch[0], fut.result())
                gen = self._generators.pop(batch[0].spec["task_id"], None)
                if gen is not None:
                    gen._finish()
            return   # batched calls completed via their PARTIALs
        pending = [pt for pt in batch if not pt.done]
        if not pending:
            return
        if isinstance(exc, (rpc.ConnectionLost, ConnectionError,
                            asyncio.CancelledError)):
            self._note_actor_conn_loss(st, address)
            requeued = False
            for pt in pending:
                if pt.retries_left != 0:
                    if pt.retries_left > 0:
                        pt.retries_left -= 1
                    # re-run after restart IN SUBMISSION ORDER: a dying
                    # connection fails a pipeline of in-flight calls in
                    # arbitrary completion order; the seq heap restores
                    # it and jumps ahead of later fresh submissions.
                    # Calls acked by a PARTIAL never re-run.
                    heapq.heappush(st.retry, (pt.seq, pt))
                    requeued = True
                else:
                    self._fail_task(pt, ActorDiedError(
                        f"actor {actor_id[:12]} died mid-call: {exc}"))
            if requeued:
                st.work.set()
            return
        for pt in pending:
            if isinstance(exc, rpc.RpcError):
                self._fail_task(pt, RuntimeError(str(exc)))
            else:
                self._fail_task(pt, exc if isinstance(exc, Exception)
                                else RuntimeError(repr(exc)))

    async def _probe_actor(self, actor_id: str):
        """Refresh actor state from GCS after a connection loss."""
        await asyncio.sleep(cfg.actor_restart_probe_s)
        st = self.actor_handles.get(actor_id)
        if st is None or st.ready.is_set():
            return
        info = await self.gcs_call_async("get_actor_info", actor_id=actor_id)
        if info and info["state"] == "ALIVE" and info["address"]:
            st.state = "ALIVE"
            st.address = info["address"]
            st.ready.set()
        elif info and info["state"] == "DEAD":
            st.state = "DEAD"
            st.death_cause = info.get("death_cause")
            st.ready.set()

    async def kill_actor_async(self, actor_id: str, no_restart=True):
        await self.gcs_call_async("kill_actor", actor_id=actor_id,
                            no_restart=no_restart)

    # --------------------------------------------------------- execution side
    def h_push_task(self, conn, spec: Dict):
        # sync handler returning a Future: the rpc layer responds from the
        # future's done-callback, so the hot execution path spawns no
        # per-call dispatch task
        fut = self.loop.create_future()
        self._queue_for(spec).put_nowait((spec, fut))
        return fut

    def h_push_tasks(self, conn, seq, specs: List[Dict]):
        """Batched push with STREAMED acks: one frame in, a PARTIAL out
        per task as it completes (so a fast task's ack never waits for a
        slow one sharing its frame, and a worker death mid-batch only
        loses unacked tasks), then a final response."""
        state = {"remaining": len(specs)}

        def make_cb(idx):
            def cb(fut):
                if fut.cancelled():
                    conn.send_partial(seq, idx, False,
                                      ("CancelledError", "cancelled", ""))
                else:
                    exc = fut.exception()
                    if exc is not None:
                        conn.send_partial(
                            seq, idx, False,
                            (type(exc).__name__, str(exc), ""))
                    else:
                        conn.send_partial(seq, idx, True, fut.result())
                state["remaining"] -= 1
                if state["remaining"] == 0:
                    conn.send_final(seq, len(specs))
            return cb

        for idx, spec in enumerate(specs):
            fut = self.loop.create_future()
            fut.add_done_callback(make_cb(idx))
            self._queue_for(spec).put_nowait((spec, fut))

    h_push_tasks.streaming = True

    # ------------------------------------------------ streaming generators
    # (executor side; reference: ReportGeneratorItemReturns,
    # core_worker.proto:400 — here each yielded item is one PARTIAL frame
    # on the push_task_streaming RPC itself)

    def h_push_task_streaming(self, conn, seq, spec: Dict):
        """Streaming task push: items flow back as PARTIALs as the
        generator yields; the final RESPONSE carries the completion
        sentinel for return_ids[0]."""
        spec["_stream_out"] = (conn, seq)
        fut = self.loop.create_future()

        def done(f):
            if f.cancelled():
                conn._respond(seq, False, ("CancelledError", "cancelled", ""))
            elif f.exception() is not None:
                e = f.exception()
                conn._respond(seq, False, (type(e).__name__, str(e), ""))
            else:
                conn.send_final(seq, f.result())
        fut.add_done_callback(done)
        self._queue_for(spec).put_nowait((spec, fut))

    h_push_task_streaming.streaming = True

    def h_generator_ack(self, conn, task_id: bytes, consumed: int):
        st = self._gen_flow.get(task_id)
        if st is not None:
            st["acked"] = max(st["acked"], consumed)
            st["event"].set()

    def h_generator_close(self, conn, task_id: bytes):
        st = self._gen_flow.get(task_id)
        if st is not None:
            st["closed"] = True
            st["event"].set()
        else:
            # close raced ahead of execution (task still queued here):
            # leave a tombstone so _execute_streaming exits immediately
            # instead of producing into a window nobody will ever open
            self._gen_tombstones.add(task_id)
            while len(self._gen_tombstones) > 4096:
                self._gen_tombstones.pop()
        return True

    async def _execute_streaming(self, spec: Dict, fn, args, kwargs) -> Dict:
        """Drive a (sync or async) generator function, shipping each item
        as its own owner-visible return object with bounded in-flight
        items. Returns the final-response payload (the completion
        sentinel: the item count)."""
        conn, seq = spec.pop("_stream_out")
        task_id = spec["task_id"]
        limit = int(spec.get("backpressure")
                    or cfg.streaming_backpressure)
        closed_early = task_id in self._gen_tombstones
        self._gen_tombstones.discard(task_id)
        flow = {"acked": 0, "closed": closed_early,
                "event": asyncio.Event()}
        self._gen_flow[task_id] = flow
        sent = 0
        agen = sgen = None
        # streaming bodies run outside _execute's sync/async trace-setting
        # paths (each resumption lands on whatever executor thread is
        # free), so the task's propagated trace context is re-established
        # around every resumption — runtime spans recorded inside a
        # streaming generator (engine phases) parent under this task
        trace_pair = (spec.get("trace_id"), spec.get("span_id"))
        trace_tok = _trace_ctx.set(trace_pair)
        try:
            out = fn(*args, **kwargs)
            if hasattr(out, "__anext__"):
                agen = out
            elif hasattr(out, "__next__"):
                sgen = out
            else:
                raise TypeError(
                    f"num_returns='streaming' task {spec.get('name')} "
                    f"returned {type(out).__name__}, not a generator")
            _SENTINEL = object()

            def _next_sync():
                prev_trace = getattr(_exec_tls, "trace", None)
                _exec_tls.trace = trace_pair
                try:
                    return next(sgen)
                except StopIteration:
                    return _SENTINEL
                finally:
                    _exec_tls.trace = prev_trace

            while True:
                # bounded in-flight window: wait for consumption acks
                # (poll the connection so a dead consumer can't wedge
                # this executor forever)
                while (sent - flow["acked"] >= limit
                       and not flow["closed"] and not conn.closed):
                    flow["event"].clear()
                    try:
                        await asyncio.wait_for(flow["event"].wait(), 1.0)
                    except asyncio.TimeoutError:
                        pass
                if flow["closed"] or conn.closed:
                    break
                if agen is not None:
                    try:
                        value = await agen.__anext__()
                    except StopAsyncIteration:
                        break
                else:
                    value = await self.loop.run_in_executor(
                        self.executor, _next_sync)
                    if value is _SENTINEL:
                        break
                rid = ids.object_id_for_return(task_id, 2 + sent)
                conn.send_partial(seq, sent, True,
                                  self._encode_return(rid, value))
                sent += 1
        except Exception as e:
            # the error IS the next item: consumers hit it in stream
            # order via get(ref) (reference: generator errors surface on
            # the failing index's ref)
            if not conn.closed:
                s = serialization.serialize_error(e)
                conn.send_partial(seq, sent, True,
                                  ["wire"] + list(s.to_wire()))
                sent += 1
        finally:
            _trace_ctx.reset(trace_tok)
            self._gen_flow.pop(task_id, None)
            for g in (agen, sgen):
                if g is not None:
                    try:
                        closer = getattr(g, "aclose", None) \
                            or getattr(g, "close", None)
                        res = closer() if closer else None
                        if asyncio.iscoroutine(res):
                            await res
                    # rtlint: disable=RT004 — best-effort close of a user
                    # generator whose task already finished/errored; its
                    # close-time exception has nowhere useful to go
                    except Exception:
                        pass
            self.current_task_name = None
            self.current_task_id = None
        return {"returns": [self._encode_return(spec["return_ids"][0],
                                                sent)],
                "n_items": sent}

    def h_cancel_task(self, conn, task_id: bytes, force: bool = False):
        """Cancel a queued (not yet started) task on this worker
        (reference: CoreWorker::CancelTask — queued tasks are dropped;
        force-cancel of running tasks kills the worker)."""
        self._cancelled_tasks.add(task_id)
        # force-kill only if the task being cancelled is the one running —
        # never take down an unrelated task sharing this worker
        if force and self.current_task_id == task_id:
            asyncio.get_event_loop().call_later(0.05, os._exit, 1)
        return True

    def _queue_for(self, spec: Dict) -> "asyncio.Queue":
        """Route a task to its concurrency group's queue (per-call option
        wins over the method's declared group; default queue otherwise)."""
        gq = getattr(self, "_group_queues", None)
        if not gq:
            return self._exec_queue
        group = spec.get("concurrency_group") \
            or self._method_groups.get(spec.get("method"))
        return gq.get(group, self._exec_queue)

    async def _exec_consumer(self, queue: Optional["asyncio.Queue"] = None):
        queue = queue if queue is not None else self._exec_queue
        while not self._shutdown:
            spec, fut = await queue.get()
            if spec["task_id"] in self._cancelled_tasks:
                self._cancelled_tasks.discard(spec["task_id"])
                result = self._encode_error(
                    spec, TaskCancelledError(spec.get("name", "task")))
                if not fut.done():
                    fut.set_result(result)
                continue
            try:
                result = await self._execute(spec)
            except asyncio.CancelledError:
                raise
            except BaseException as e:
                result = self._encode_error(spec, e)
            if not fut.done():
                fut.set_result(result)

    def _apply_accelerator_ids(self, spec: Dict):
        ids = spec.get("accelerator_ids")
        try:
            from ray_tpu._private.accelerators import (all_accelerator_managers,
                                                       get_accelerator_manager)
            if not ids:
                # restore the process's original visibility so a reused
                # worker doesn't leak a previous task's chip mask
                for res, mgr in all_accelerator_managers().items():
                    orig = self._orig_visible.get(res)
                    var = mgr.get_visible_accelerator_ids_env_var()
                    if res in self._visible_dirty:
                        if orig is None:
                            os.environ.pop(var, None)
                        else:
                            os.environ[var] = orig
                        self._visible_dirty.discard(res)
                return
            for res, chip_ids in ids.items():
                mgr = get_accelerator_manager(res)
                if mgr is not None:
                    var = mgr.get_visible_accelerator_ids_env_var()
                    self._orig_visible.setdefault(res, os.environ.get(var))
                    self._visible_dirty.add(res)
                    mgr.set_current_process_visible_accelerator_ids(
                        [str(c) for c in chip_ids])
        except Exception:
            logger.exception("failed to set accelerator visibility")

    async def _package_runtime_env(self, renv: Dict) -> Dict:
        """Submission side: zip local working_dir / py_modules dirs into
        content-addressed GCS KV packages (reference: runtime-env
        packaging python/ray/_private/runtime_env/packaging.py — GCS URI
        zips; URI-cached so identical dirs upload once)."""
        import hashlib
        import io
        import zipfile
        out = dict(renv)

        async def pack_dir(path: str) -> str:
            buf = io.BytesIO()
            with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
                for root, dirs, files in os.walk(path):
                    dirs[:] = [d for d in dirs if d != "__pycache__"]
                    for fname in sorted(files):
                        full = os.path.join(root, fname)
                        z.write(full, os.path.relpath(full, path))
            data = buf.getvalue()
            uri = hashlib.sha1(data).hexdigest()
            existing = await self.gcs_call_async("kv_get", ns="runtime_env",
                                           key=uri.encode())
            if existing is None:
                await self.gcs_call_async("kv_put", ns="runtime_env",
                                    key=uri.encode(), value=data)
            return uri

        wd = out.get("working_dir")
        if wd and os.path.isdir(wd):
            out["working_dir_uri"] = await pack_dir(wd)
            out["working_dir_base"] = os.path.basename(
                os.path.abspath(wd))
            del out["working_dir"]
        uris = []
        for m in out.get("py_modules") or []:
            if os.path.isdir(m):
                uris.append([await pack_dir(m),
                             os.path.basename(os.path.abspath(m))])
        if uris:
            out["py_modules_uris"] = uris
            out.pop("py_modules", None)
        return out

    def _materialize_uri(self, uri: str, base: str = "") -> str:
        """Worker side: fetch + extract a packaged dir (content-addressed
        cache shared by all workers on the node; reference: uri_cache.py)."""
        import zipfile
        dest = f"/tmp/raytpu/runtime_envs/{uri}"
        mod_root = os.path.join(dest, base) if base else dest
        if os.path.isdir(dest):
            return mod_root
        data = asyncio.run_coroutine_threadsafe(
            self.gcs_call_async("kv_get", ns="runtime_env", key=uri.encode()),
            self.loop).result(120)
        if data is None:
            raise RuntimeError(f"runtime_env package {uri} missing")
        tmp = dest + ".tmp" + os.urandom(4).hex()
        extract_to = os.path.join(tmp, base) if base else tmp
        os.makedirs(extract_to, exist_ok=True)
        import io
        with zipfile.ZipFile(io.BytesIO(data)) as z:
            z.extractall(extract_to)
        try:
            os.rename(tmp, dest)
        except OSError:
            import shutil
            shutil.rmtree(tmp, ignore_errors=True)   # raced another worker
        return mod_root

    _PIP_ENV_ROOT = "/tmp/raytpu/runtime_envs"

    def _ensure_pip_env(self, packages: List[str]) -> str:
        """Materialize a cached package dir for a pip runtime env and
        return it (reference: _private/runtime_env/pip.py — hashed-spec
        isolated installs; here `pip install --target` into a per-spec
        dir layered onto sys.path, which composes with the base install
        the way the reference's --system-site-packages venv does and
        works when the interpreter itself lives in a venv). A file lock
        serializes concurrent workers; the dir is only marked ready once
        the install succeeded."""
        import hashlib
        import subprocess
        import sys

        key = hashlib.sha1("\n".join(sorted(packages)).encode()).hexdigest()
        env_dir = os.path.join(self._PIP_ENV_ROOT, f"pip_{key[:16]}")
        ready = os.path.join(env_dir, ".ready")
        site = os.path.join(env_dir, "pkgs")
        if os.path.exists(ready):
            return site
        os.makedirs(self._PIP_ENV_ROOT, exist_ok=True)
        import fcntl
        with open(os.path.join(self._PIP_ENV_ROOT,
                               f".lock_{key[:16]}"), "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            if os.path.exists(ready):
                return site
            proc = subprocess.run(
                [sys.executable, "-m", "pip", "install",
                 "--no-build-isolation", "--target", site, *packages],
                capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"pip runtime env install failed: {proc.stderr[-2000:]}")
            with open(ready, "w") as f:
                f.write("ok")
        return site

    def _apply_runtime_env(self, spec: Dict):
        """Worker-scope runtime env for this execution, dispatched
        through the plugin protocol (reference:
        python/ray/_private/runtime_env/plugin.py — env_vars /
        working_dir / py_modules / pip are built-in plugins; user
        plugins register via RAY_TPU_RUNTIME_ENV_PLUGINS; container is
        process-scope and was applied by the node manager at spawn).
        Runs on the executor thread, so blocking KV fetches and pip
        installs are safe."""
        import sys

        from ray_tpu._private.runtime_env_plugins import \
            apply_worker_plugins
        renv = spec.get("runtime_env")
        if not renv:
            return None
        ctx = apply_worker_plugins(renv, self)
        saved: Dict[str, Optional[str]] = {}
        for k, v in ctx.env_vars.items():
            saved[k] = os.environ.get(k)
            os.environ[k] = v
        saved_cwd = None
        if ctx.cwd:
            saved_cwd = os.getcwd()
            os.chdir(ctx.cwd)
        added_paths: List[str] = []
        for p in ctx.py_paths:
            sys.path.insert(0, p)
            added_paths.append(p)
        for p in ctx.permanent_py_paths:
            # pip site: permanent for this worker's life — the node
            # manager only ever reuses it for the same env hash
            # (reference: per-env worker pools)
            if p not in sys.path:
                sys.path.insert(0, p)
        return (saved, saved_cwd, added_paths)

    def _restore_runtime_env(self, token):
        import sys
        if token is None:
            return
        saved, saved_cwd, added_paths = token
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        if saved_cwd is not None:
            os.chdir(saved_cwd)
        for p in added_paths:
            try:
                sys.path.remove(p)
            except ValueError:
                pass

    async def _execute(self, spec: Dict) -> Dict:
        self._record_task_event(
            spec["task_id"], "RUNNING", name=spec.get("name"),
            job_id=spec.get("job_id"), node_id=self.node_id,
            worker_id=self.worker_id,
            trace_id=spec.get("trace_id"), span_id=spec.get("span_id"),
            parent_span_id=spec.get("parent_span_id"),
            type="ACTOR_TASK" if spec.get("actor_id") else "NORMAL_TASK")
        trace_pair = (spec.get("trace_id"), spec.get("span_id"))
        if not spec.get("actor_id"):
            # actor workers keep the mask set at become_actor for life
            self._apply_accelerator_ids(spec)
        args, kwargs = await self._resolve_args(spec)
        if spec.get("actor_id"):
            if self.actor_instance is None:
                raise RuntimeError("actor task on non-actor worker")
            if spec["method"] == "__rt_dag_loop__":
                # compiled-DAG execution loop (ray_tpu.dag.compiled)
                from ray_tpu.dag.compiled import _dag_actor_loop
                import functools
                fn = functools.partial(_dag_actor_loop, self.actor_instance)
            else:
                fn = getattr(self.actor_instance, spec["method"])
        else:
            fn = await self._load_function_any(spec)
        self.current_task_name = spec["name"]
        self.current_task_id = spec["task_id"]
        if spec.get("streaming"):
            return await self._execute_streaming(spec, fn, args, kwargs)
        if asyncio.iscoroutinefunction(getattr(fn, "__call__", fn)) or \
                asyncio.iscoroutinefunction(fn):
            tok = _trace_ctx.set(trace_pair)
            try:
                value = await fn(*args, **kwargs)
            finally:
                _trace_ctx.reset(tok)
        else:
            key = spec.get("method") or spec.get("func_id")

            def _call():
                token = self._apply_runtime_env(spec)
                prev = getattr(_exec_tls, "method_key", None)
                prev_trace = getattr(_exec_tls, "trace", None)
                _exec_tls.method_key = key
                _exec_tls.trace = trace_pair
                try:
                    return fn(*args, **kwargs)
                finally:
                    _exec_tls.method_key = prev
                    _exec_tls.trace = prev_trace
                    self._restore_runtime_env(token)
            # adaptive inline execution: methods with a sub-threshold
            # running-average duration skip the thread-pool round trip
            # (two loop wakeups + condvar, ~100us on a busy box). A method
            # that turns slow migrates back to the pool on the next call.
            # Inline code CANNOT use blocking sync APIs (they bridge onto
            # this very loop), so a method OBSERVED using the bridge
            # during its pool runs is marked inline-unsafe for good; the
            # rare first-ever bridge call while inline fail-fasts into a
            # clean task error (never a silent re-run — side effects must
            # not double, reference retry semantics are opt-in)
            # Inlining requires EVIDENCE, not one lucky sample: the EMA
            # is an average (a data-dependent slow call would block the
            # whole loop), so demand >=3 consecutive sub-threshold runs
            # before inlining, and a single run over threshold demotes
            # the method back to the pool until it re-earns the streak.
            ema = self._exec_ema.get(key)
            streak = self._exec_streak.get(key, 0)
            t0 = time.perf_counter()
            if (ema is not None and streak >= 3 and self._inline_ok
                    and key not in self._inline_unsafe
                    and ema < cfg.inline_exec_threshold_s):
                try:
                    value = _call()
                except _InlineBridgeError:
                    self._inline_unsafe.add(key)
                    raise RuntimeError(
                        f"{spec.get('name')}: blocking ray_tpu API call "
                        "from inline execution; the method is now marked "
                        "for thread-pool execution — retry the call")
            else:
                value = await self.loop.run_in_executor(self.executor,
                                                        _call)
            dt = time.perf_counter() - t0
            if key is not None:
                self._exec_ema[key] = dt if ema is None \
                    else 0.8 * ema + 0.2 * dt
                self._exec_streak[key] = streak + 1 \
                    if dt < cfg.inline_exec_threshold_s else 0
        self.current_task_name = None
        self.current_task_id = None
        nret = len(spec["return_ids"])
        if nret == 1:
            values = [value]
        else:
            values = list(value)
            if len(values) != nret:
                raise ValueError(
                    f"task returned {len(values)} values, expected {nret}")
        xlang = bool(spec.get("xlang"))
        return {"returns": [self._encode_return(rid, v, xlang=xlang)
                            for rid, v in zip(spec["return_ids"], values)]}

    def _encode_return(self, rid: bytes, value, xlang: bool = False) -> list:
        if xlang:
            # cross-language caller: msgpack result inline on the wire
            import msgpack as _mp
            payload = _mp.packb(value, use_bin_type=True, default=str)
            return ["wire", serialization.KIND_MSGPACK, b"", [payload]]
        s = serialization.serialize(value)
        if s.is_inline() or self.store is None:
            return ["wire"] + list(s.to_wire())
        try:
            meta = s.store_meta()
            bufs = self.store.create(rid, s.data_size(), len(meta))
            if bufs is not None:
                data, meta_view = bufs
                s.write_to(data)
                meta_view[:] = meta
                self.store.seal(rid)
            return ["shm", self.node_id]
        except Exception:
            logger.exception("shm return failed; inlining")
            return ["wire"] + list(s.to_wire())

    def _encode_error(self, spec, exc: BaseException) -> Dict:
        if not isinstance(exc, TaskError):
            logger.debug("task %s raised", spec.get("name"),
                         exc_info=exc)
        if spec.get("xlang"):
            # cross-language callers can't unpickle Python exceptions:
            # ship the message as msgpack text (kind 1 marks an error)
            import msgpack
            cause = exc.cause if isinstance(exc, TaskError) and \
                getattr(exc, "cause", None) else exc
            payload = msgpack.packb(
                f"{type(cause).__name__}: {cause}", use_bin_type=True)
            ret = ["wire", 1, b"", [payload]]
            return {"returns": [ret for _ in spec["return_ids"]]}
        s = serialization.serialize_error(exc)
        ret = ["wire"] + list(s.to_wire())
        return {"returns": [ret for _ in spec["return_ids"]]}

    async def _resolve_args(self, spec):
        async def dec(enc):
            if enc[0] == "v":
                return serialization.deserialize_wire(enc[1], enc[2], enc[3])
            ref = ObjectRef(enc[1], enc[2], _register=False)
            val, is_exc = await self._resolve(ref)
            if is_exc:
                raise TaskError(val) if not isinstance(val, TaskError) else val
            return val
        args = [await dec(a) for a in spec["args"]]
        kwargs = {k: await dec(v) for k, v in spec["kwargs"].items()}
        return args, kwargs

    async def h_become_actor(self, conn, spec: Dict):
        self._apply_accelerator_ids(spec)
        self._apply_runtime_env(spec)   # permanent for the actor's life
        if spec.get("class_ref"):
            # cross-language actor: importable "module:Class" instead of
            # a shipped pickle (reference: cross-language actor class
            # descriptors, java/cpp frontends)
            cls = _import_ref(spec["class_ref"])
        else:
            cls = await self._load_function(spec["class_id"],
                                            spec.get("owner_address"))
        args, kwargs = await self._resolve_args(
            {"args": spec["init_args"], "kwargs": spec["init_kwargs"]})
        self.actor_id = spec["actor_id"]
        self.actor_spec = spec
        maxc = spec.get("max_concurrency", 1)
        groups = spec.get("concurrency_groups") or {}
        self._method_groups = spec.get("method_groups") or {}
        extra = sum(groups.values())
        if maxc > 1 or groups:
            self._inline_ok = False    # parallel methods need real threads
            self.executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=maxc + extra, thread_name_prefix="actor-exec")
            for _ in range(maxc - 1):
                self._consumers.append(
                    self._spawn(self._exec_consumer()))
        # concurrency groups: per-group FIFO queue with its own consumer
        # pool, so e.g. an "io" group keeps serving while the default
        # group is busy (reference: ConcurrencyGroupManager + fibers,
        # core_worker/transport/concurrency_group_manager.h — threads
        # here, the asyncio loop plays the fiber scheduler)
        self._group_queues: Dict[str, asyncio.Queue] = {}
        for gname, limit in groups.items():
            q: asyncio.Queue = asyncio.Queue()
            self._group_queues[gname] = q
            for _ in range(max(1, int(limit))):
                self._consumers.append(
                    self._spawn(self._exec_consumer(q)))
        inner = cls.__ray_tpu_actual_class__ if hasattr(
            cls, "__ray_tpu_actual_class__") else cls
        # launch attribution: the callable-init phase (user __init__ —
        # model build, checkpoint load) records as a child of the
        # actor.launch trace the node manager forwarded in the spec
        lt = spec.get("_launch_trace") or {}
        t_init = time.time()
        instance = await self.loop.run_in_executor(
            self.executor, lambda: inner(*args, **kwargs))
        init_ms = (time.time() - t_init) * 1e3
        try:
            from ray_tpu._private import events as _events
            _events.record_complete(
                "launch.callable_init", t_init, time.time(),
                category="launch", trace_id=lt.get("trace_id"),
                parent_span_id=lt.get("parent_span_id"),
                actor_id=spec["actor_id"])
            from ray_tpu.util.metrics import Gauge
            if not hasattr(self, "_launch_phase_gauge"):
                self._launch_phase_gauge = Gauge(
                    "runtime_launch_phase_ms",
                    "most recent actor-launch phase duration (ms)")
            self._launch_phase_gauge.set(round(init_ms, 3),
                                         tags={"phase": "callable_init"})
        except Exception:
            pass
        self.actor_instance = instance
        return {"ok": True}

    async def h_exit(self, conn, reason: str = ""):
        asyncio.get_event_loop().call_later(0.05, os._exit, 0)
        return True

    def object_locations(self, refs) -> List[Optional[str]]:
        """Best-effort node ids for locally-known objects: owned refs
        carry the executor-reported primary location; store-resident
        objects are here. None = unknown (no cluster query — this is the
        cheap path locality-aware dealing needs, reference:
        RefBundle.get_cached_location)."""
        out: List[Optional[str]] = []
        for r in refs:
            entry = self.owned.get(r.id)
            if entry is not None and entry.get("location"):
                out.append(entry["location"])
            elif self.store is not None and self.store.contains(r.id):
                out.append(self.node_id)
            else:
                out.append(None)
        return out

    def h_dump_stacks(self, conn):
        """Live Python stacks of every thread in this worker (the
        `ray_tpu stack` data plane; reference: `ray stack` via py-spy —
        here each process serves its own frames, no ptrace)."""
        from ray_tpu._private.proc_util import format_thread_stacks
        from ray_tpu.util import sanitizers
        return {"pid": os.getpid(), "mode": self.mode,
                "stacks": format_thread_stacks(),
                "loop_stats": sanitizers.stats_snapshot()}

    async def dump_cluster_stacks_async(self) -> Dict[str, Any]:
        """node_id -> {node_manager: ..., workers: {worker_id: ...}} for
        every alive node (fans out through each node manager)."""
        out: Dict[str, Any] = {}
        nodes = await self.gcs_call_async("get_all_nodes")
        for n in nodes:
            if not n.get("alive"):
                continue
            try:
                out[n["node_id"]] = await asyncio.wait_for(
                    self.pool.call(n["address"], "dump_stacks"), 15.0)
            except Exception as e:
                out[n["node_id"]] = {"error": f"{type(e).__name__}: {e}"}
        return out

    # ------------------------------------------------------------- utilities
    def as_future(self, ref: ObjectRef) -> concurrent.futures.Future:
        return asyncio.run_coroutine_threadsafe(self.get_async(ref), self.loop)

    async def stop_async(self, private_loop: bool = True):
        self._shutdown = True
        # return held idle leases so the node manager can re-grant the
        # workers NOW — other drivers may be queued on them (the server
        # also reclaims by owner on disconnect, but an explicit return
        # frees the resources before the TCP teardown races the next
        # lease wait poll)
        leases = [l for pool in self._idle_leases.values() for l in pool]
        self._idle_leases.clear()
        if leases:
            try:
                await asyncio.wait_for(
                    asyncio.gather(*(self._drop_lease(l) for l in leases),
                                   return_exceptions=True), 2.0)
            except Exception:
                pass
        # flush buffered task events so the GCS timeline isn't truncated
        if self._task_events and self.gcs is not None and not self.gcs.closed:
            batch, self._task_events = self._task_events, []
            try:
                await asyncio.wait_for(
                    self.gcs.notify("add_task_events", events=batch), 1.0)
            except Exception:
                pass
        if self.gcs is not None and not self.gcs.closed:
            # flight-recorder spans buffered in this process ride the same
            # sink — a short-lived worker's runtime events must not die
            # with its 1s flusher cadence
            try:
                from ray_tpu._private import events as _events
                ev_rows = _events.drain()
                if ev_rows:
                    await asyncio.wait_for(
                        self.gcs.notify("add_task_events", events=ev_rows),
                        1.0)
            except Exception:
                pass
            # ledger: announce this worker's exit (its owned-table dies
            # with it — sealed objects it leaves behind become leak
            # candidates) and ship any buffered provenance records
            try:
                if ledger.enabled():
                    ledger.record(b"", "worker_exit",
                                  worker_id=self.worker_id)
                batch = ledger.drain()
                if batch:
                    await asyncio.wait_for(
                        self.gcs.notify("update_object_ledger",
                                        records=batch,
                                        node_id=self.node_id,
                                        worker_id=self.worker_id), 1.0)
            except Exception:
                pass
            # final metrics push (mirror of the task-event flush above):
            # counters from workers shorter-lived than the 2s push cadence
            # land in the GCS aggregate instead of vanishing
            try:
                from ray_tpu.util.metrics import registry_snapshot
                payload = registry_snapshot()
                if payload:
                    await asyncio.wait_for(
                        self.gcs.notify("report_metrics",
                                        worker_id=self.worker_id,
                                        node_id=self.node_id,
                                        metrics=payload), 1.0)
            except Exception:
                pass
        # retire the registry pusher thread — a stopped worker must not
        # leave it spinning on is_initialized() forever
        try:
            from ray_tpu.util import metrics as _metrics
            _metrics.stop_pusher()
        except Exception:
            pass
        # seal the crash black box: final metrics snapshot + seal record
        # (atexit would also fire, but a clean stop should seal while the
        # ring is already drained, marking this box as a graceful exit)
        try:
            from ray_tpu._private import blackbox as _blackbox
            _blackbox.seal("clean_exit")
        except Exception:
            pass
        # cancel-and-await every background task (senders, dispatchers,
        # flushers, probes) BEFORE closing connections: nothing may outlive
        # shutdown (no "Task was destroyed but it is pending!")
        me = asyncio.current_task()
        # drain in rounds: a task cancelled mid-cleanup may spawn another
        # (it lands in _bg and is caught by the next round)
        for _ in range(10):
            victims = [t for t in self._bg if t is not me and not t.done()]
            if not victims:
                break
            for t in victims:
                t.cancel()
            await asyncio.gather(*victims, return_exceptions=True)
        if self.server:
            await self.server.close()
        if self.gcs:
            await self.gcs.close()
        if self.node_conn:
            await self.node_conn.close()
        await self.pool.close()
        if self.store is not None:
            self.store.close()
        # surface anything that escaped tracking (test hook: must be empty).
        # on a private loop every task belongs to this worker, so check the
        # whole loop (catches rpc-layer escapes too); on a shared loop
        # (owns_loop=False) only our tracked tasks are ours to judge
        pool = asyncio.all_tasks() if private_loop else self._bg
        leaked = [t for t in pool if t is not me and not t.done()]
        names = [f"{t.get_name()}:{getattr(t.get_coro(), '__qualname__', t.get_coro())}"
                 for t in leaked]
        if leaked:
            logger.warning("shutdown leaked %d pending tasks: %s",
                           len(leaked), names[:8])
        return names


global_worker: Optional["Worker"] = None


class Worker:
    """Sync facade over CoreWorker: runs the asyncio loop on a daemon thread
    and bridges public API calls with run_coroutine_threadsafe (the role the
    reference's Cython binding plays over its C++ event loops,
    reference: python/ray/_raylet.pyx:3282)."""

    def __init__(self, core: CoreWorker, owns_loop: bool = True):
        self.core = core
        self.owns_loop = owns_loop
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def start(cls, **kw) -> "Worker":
        core = CoreWorker(**kw)
        loop = asyncio.new_event_loop()
        started = threading.Event()

        def run():
            asyncio.set_event_loop(loop)
            loop.run_until_complete(core.start_async())
            started.set()
            loop.run_forever()

        t = threading.Thread(target=run, name="ray-tpu-loop", daemon=True)
        t.start()
        if not started.wait(timeout=30):
            raise TimeoutError("core worker failed to start")
        w = cls(core)
        w._thread = t
        return w

    def _run(self, coro, timeout=None):
        key = getattr(_exec_tls, "method_key", None)
        if key is not None:
            # task code used a blocking sync API on a pool thread: this
            # method must never migrate to inline execution
            self.core._inline_unsafe.add(key)
        if threading.get_ident() == self.core._loop_thread_ident:
            # inline-executed task code blocking on its own loop would
            # deadlock; fail fast (converted to a task error by _execute)
            coro.close()
            raise _InlineBridgeError(
                "blocking sync API called from inline task execution")
        return asyncio.run_coroutine_threadsafe(
            coro, self.core.loop).result(timeout)

    # public-api operations
    def put(self, value) -> ObjectRef:
        # no loop bridge: serialization + arena copy + seal run right here
        # on the calling thread (also makes put safe from inline-executed
        # task code — it no longer blocks on the loop it runs on)
        return self.core.put_local(value)

    def get(self, refs, timeout=None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        vals = self._run(self.core.get_many_async(refs, timeout))
        return vals[0] if single else vals

    def get_async(self, ref):
        return self.core.get_async(ref)

    def as_future(self, ref):
        return self.core.as_future(ref)

    def wait(self, refs, num_returns=1, timeout=None):
        return self._run(self.core.wait_async(refs, num_returns, timeout))

    def submit(self, func, args, kwargs, **opts) -> List[ObjectRef]:
        return self.core.submit_task_threadsafe(func, args, kwargs, **opts)

    def submit_streaming(self, func, args, kwargs, **opts):
        return self.core.submit_streaming_task_threadsafe(
            func, args, kwargs, **opts)

    def submit_actor_streaming(self, actor_id, method, args, kwargs,
                               **opts):
        return self.core.submit_streaming_actor_task_threadsafe(
            actor_id, method, args, kwargs, **opts)

    def create_actor(self, cls, args, kwargs, **opts) -> str:
        return self._run(self.core.create_actor_async(cls, args, kwargs, **opts))

    def submit_actor_task(self, actor_id, method, args, kwargs, **opts):
        return self.core.submit_actor_task_threadsafe(
            actor_id, method, args, kwargs, **opts)

    def kill_actor(self, actor_id, no_restart=True):
        return self._run(self.core.kill_actor_async(actor_id, no_restart))

    def broadcast(self, ref, node_ids):
        return self._run(self.core.broadcast_async(ref, node_ids))

    def broadcast_weights(self, ref, node_ids=None, max_retries=2):
        return self._run(self.core.broadcast_weights_async(
            ref, node_ids, max_retries=max_retries))

    def cancel(self, ref, force=False):
        return self._run(self.core.cancel_task_async(ref, force))

    def gcs_call(self, method, **kw):
        return self._run(self.core.gcs_call_async(method, **kw))

    def node_call(self, method, **kw):
        return self._run(self.core.node_conn.call(method, **kw))

    def stop(self):
        self.leaked_tasks: Optional[list] = None
        try:
            self.leaked_tasks = self._run(
                self.core.stop_async(private_loop=self.owns_loop), timeout=5)
        except Exception:
            pass
        if self.owns_loop and self.core.loop is not None:
            self.core.loop.call_soon_threadsafe(self.core.loop.stop)
