"""GCS hot-path observability: per-handler RPC histograms, slow-handler
spans, pubsub publish->deliver latency, table-size gauges.

The GCS is the component every other plane reports INTO — so it cannot
report through them the normal way (a util.metrics Metric would start a
pusher thread that needs a connected worker; the global flight recorder
would hijack the driver's ring when a GcsServer is embedded in-process
by tests). Instead this module keeps plain-dict accounting and exports
registry-SHAPED snapshot rows that the GCS self-ingests through its own
``h_report_metrics(None, "gcs", rows)`` — the exact pattern the ledger
sweep already uses — so `gcs_rpc_ms{handler=...}` lands on the same
time-series plane as every worker metric, queryable via
``query_metrics("gcs_rpc_ms", agg="p99")``.

Span policy (the PR 4 runtime-event track side): every handler call
slower than ``cfg.gcs_slow_rpc_ms`` writes a ``gcs.rpc`` span row
straight into the GCS task-event ring (no RPC — the ring lives in this
process); sub-threshold calls are sampled 1-in-``cfg.gcs_rpc_sample_n``
per handler so a healthy control plane still leaves a trace breadcrumb
trail without flooding the ring.

Reference: Ray's GCS treats control-plane metadata throughput as the
scaling bottleneck (PAPERS.md arxiv 1712.05889 §4) and exports
per-handler gRPC latency for exactly this reason
(src/ray/gcs/gcs_server/gcs_server_metrics defs).

Chaos: ``RAY_TPU_TESTING_GCS_RPC_DELAY="gcs_rpc=handler:ms[,...]"``
injects a deterministic asyncio sleep into the named handler — the
tested path for slow-handler spans and the status pane's p99 column
(util/chaos.py GcsRpcDelayer owns the spec format).
"""

from __future__ import annotations

import asyncio
import inspect
import os
import time
from typing import Any, Awaitable, Dict, List, Optional

from ray_tpu._private.config import cfg

__all__ = ["GcsObservability", "RPC_MS_BOUNDARIES", "delay_for",
           "DELAY_ENV"]

# sub-ms floor to multi-second ceiling: a healthy handler sits in the
# first two buckets, a snapshot-save stall or a delayed chaos handler
# is still resolvable at the top
RPC_MS_BOUNDARIES = [0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
                     50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0]

DELAY_ENV = "RAY_TPU_TESTING_GCS_RPC_DELAY"
_DELAY_SPEC: Optional[Dict[str, float]] = None

# Result types a handler can return that are definitely NOT awaitable —
# lets the wrapper skip the Future/coroutine/Awaitable isinstance ladder
# on the overwhelmingly common sync path.
_PLAIN_RESULTS = frozenset(
    (dict, list, tuple, set, str, bytes, int, float, bool))


def _parse_delay_spec() -> Dict[str, float]:
    """``gcs_rpc=handler:ms[,gcs_rpc=handler2:ms]`` -> {handler: ms}.
    Cached after first parse; chaos arm_local resets the cache."""
    out: Dict[str, float] = {}
    raw = os.environ.get(DELAY_ENV, "")
    for part in raw.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        key, val = part.split("=", 1)
        if key.strip() != "gcs_rpc" or ":" not in val:
            continue
        handler, ms = val.rsplit(":", 1)
        try:
            out[handler.strip()] = float(ms)
        except ValueError:
            continue
    return out


def delay_for(handler: str) -> float:
    global _DELAY_SPEC
    if _DELAY_SPEC is None:
        _DELAY_SPEC = _parse_delay_spec()
    return _DELAY_SPEC.get(handler, 0.0)


class _HandlerStats:
    """Cumulative per-handler accounting (plain dict arithmetic — the
    wrapper adds two clock reads and a few int ops per call)."""

    __slots__ = ("calls", "errors", "slow", "inflight", "counts", "sum",
                 "_since_sample")

    def __init__(self):
        self.calls = 0
        self.errors = 0
        self.slow = 0
        self.inflight = 0
        self.counts = [0] * (len(RPC_MS_BOUNDARIES) + 1)
        self.sum = 0.0
        self._since_sample = 0

    def observe(self, ms: float):
        self.calls += 1
        self.sum += ms
        i = 0
        b = RPC_MS_BOUNDARIES
        while i < len(b) and ms > b[i]:
            i += 1
        self.counts[i] += 1

    def p_quantile(self, q: float) -> float:
        """Approximate quantile from the cumulative bucket counts (upper
        boundary of the bucket holding the q-th call)."""
        total = sum(self.counts)
        if total == 0:
            return 0.0
        rank = q * total
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank:
                return (RPC_MS_BOUNDARIES[i]
                        if i < len(RPC_MS_BOUNDARIES)
                        else RPC_MS_BOUNDARIES[-1] * 2)
        return RPC_MS_BOUNDARIES[-1] * 2


class GcsObservability:
    """Owns handler instrumentation + pubsub accounting for one
    GcsServer. ``wrap_handlers`` must run before rpc.Server is built."""

    def __init__(self, gcs):
        self.gcs = gcs
        self.handlers: Dict[str, _HandlerStats] = {}
        self.inflight_total = 0
        # cfg attribute resolution walks the env on every read (~2us) —
        # far too hot for a per-RPC path, so the thresholds are cached
        # here and refreshed from the obs loop each interval.
        self._slow_ms = 0.0
        self._sample_n = 0
        self.refresh_config()
        # pubsub: publish->deliver latency + currently-pending notifies
        self.pubsub_pending = 0
        self.pubsub_delivered = 0
        self.pubsub_failed = 0
        self.pubsub_counts = [0] * (len(RPC_MS_BOUNDARIES) + 1)
        self.pubsub_sum = 0.0

    def refresh_config(self) -> None:
        self._slow_ms = float(cfg.gcs_slow_rpc_ms)
        self._sample_n = int(cfg.gcs_rpc_sample_n)

    # ------------------------------------------------------ handler wrap
    def wrap_handlers(self, handlers: Dict[str, Any]) -> Dict[str, Any]:
        self.refresh_config()
        wrapped = {}
        for name, fn in handlers.items():
            if getattr(fn, "streaming", False):
                wrapped[name] = fn       # different calling convention
                continue
            wrapped[name] = self._wrap(name, fn)
        return wrapped

    def _wrap(self, name: str, fn):
        stats = self.handlers[name] = _HandlerStats()

        # Hot path: every GCS RPC funnels through here, so globals and
        # attributes are pre-bound as defaults (LOAD_FAST) and the
        # common sync-return case touches nothing slower than counter
        # bumps — see reports/trace_probe.py's gcs_rpc_wrap_us guard.
        def call(conn, _fn=fn, _stats=stats, _name=name,
                 _perf=time.perf_counter, _delay=delay_for,
                 _finish=self._finish, _Future=asyncio.Future,
                 _iscoro=inspect.iscoroutine, **kwargs):
            delay_ms = _delay(_name)
            _stats.inflight += 1
            self.inflight_total += 1
            t0 = _perf()
            if delay_ms > 0:
                return self._delayed(_name, _stats, _fn, conn, t0,
                                     delay_ms, kwargs)
            try:
                result = _fn(conn, **kwargs)
            except BaseException as e:
                _finish(_name, _stats, t0, error=type(e).__name__)
                raise
            if result is None or result.__class__ in _PLAIN_RESULTS:
                _finish(_name, _stats, t0)
                return result
            if isinstance(result, _Future):
                result.add_done_callback(
                    lambda f: _finish(
                        _name, _stats, t0,
                        error=(type(f.exception()).__name__
                               if not f.cancelled() and f.exception()
                               else None)))
                return result
            if _iscoro(result) or isinstance(result, Awaitable):
                return self._awaited(_name, _stats, t0, result)
            _finish(_name, _stats, t0)
            return result

        call.__name__ = f"obs_{name}"
        return call

    async def _awaited(self, name, stats, t0, coro):
        try:
            result = await coro
        except BaseException as e:
            self._finish(name, stats, t0, error=type(e).__name__)
            raise
        self._finish(name, stats, t0)
        return result

    async def _delayed(self, name, stats, fn, conn, t0, delay_ms,
                       kwargs):
        await asyncio.sleep(delay_ms / 1000.0)
        try:
            result = fn(conn, **kwargs)
            if isinstance(result, asyncio.Future):
                result = await result
            elif inspect.iscoroutine(result) or isinstance(result,
                                                           Awaitable):
                result = await result
        except BaseException as e:
            self._finish(name, stats, t0, error=type(e).__name__)
            raise
        self._finish(name, stats, t0)
        return result

    def _finish(self, name: str, stats: _HandlerStats, t0: float,
                error: Optional[str] = None,
                _perf=time.perf_counter, _bounds=RPC_MS_BOUNDARIES,
                _nb=len(RPC_MS_BOUNDARIES)):
        ms = (_perf() - t0) * 1e3
        stats.inflight -= 1
        self.inflight_total -= 1
        # _HandlerStats.observe inlined — a call frame per RPC is real
        # money at this depth
        stats.calls += 1
        stats.sum += ms
        i = 0
        while i < _nb and ms > _bounds[i]:
            i += 1
        stats.counts[i] += 1
        if error:
            stats.errors += 1
        slow_ms = self._slow_ms
        emit = False
        if slow_ms and ms >= slow_ms:
            stats.slow += 1
            emit = True
        elif slow_ms and self._sample_n > 0:
            stats._since_sample += 1
            if stats._since_sample >= self._sample_n:
                stats._since_sample = 0
                emit = True
        if emit:
            self._emit_span(name, ms, error)

    def _emit_span(self, name: str, ms: float, error: Optional[str]):
        """One gcs.rpc span row, written straight into this GCS's own
        task-event ring (category 'gcs' renders as its own runtime
        track in `ray_tpu timeline`)."""
        try:
            from ray_tpu._private import events as _events
            now = time.time()
            span_id = _events.new_span_id()
            attrs = {"handler": name, "ms": round(ms, 3)}
            if error:
                attrs["error"] = error
            self.gcs.h_add_task_events(None, [{
                "task_id": span_id, "kind": "runtime_event",
                "type": "RUNTIME_EVENT", "event_kind": "span",
                "name": "gcs.rpc", "category": "gcs",
                "trace_id": _events.new_trace_id(), "span_id": span_id,
                "parent_span_id": None, "node_id": "gcs",
                "worker_id": "gcs", "attrs": attrs,
                "state": "RUNNING", "ts": now - ms / 1e3,
            }, {"task_id": span_id, "state": "FINISHED", "ts": now}])
        except Exception:
            pass

    # ----------------------------------------------------------- pubsub
    def note_publish(self) -> float:
        self.pubsub_pending += 1
        return time.perf_counter()

    def note_deliver(self, t0: float, ok: bool):
        self.pubsub_pending -= 1
        if not ok:
            self.pubsub_failed += 1
            return
        self.pubsub_delivered += 1
        ms = (time.perf_counter() - t0) * 1e3
        self.pubsub_sum += ms
        i = 0
        b = RPC_MS_BOUNDARIES
        while i < len(b) and ms > b[i]:
            i += 1
        self.pubsub_counts[i] += 1

    # ---------------------------------------------------------- exports
    def metric_rows(self) -> List[Dict]:
        """Registry-shaped snapshot rows (cumulative, so the TS plane's
        delta ingest works exactly as for a pushing worker)."""
        from ray_tpu.util.metrics import counter_snapshot, gauge_snapshot
        hist_samples = []
        calls_samples = []
        errors_samples = []
        inflight_samples = []
        for name, st in sorted(self.handlers.items()):
            if st.calls == 0 and st.inflight == 0:
                continue
            tags = [["handler", name]]
            hist_samples.append([tags, list(st.counts), st.sum])
            calls_samples.append([tags, float(st.calls)])
            if st.errors:
                errors_samples.append([tags, float(st.errors)])
            inflight_samples.append([tags, float(st.inflight)])
        rows: List[Dict] = [
            {"name": "gcs_rpc_ms", "type": "histogram",
             "help": "GCS handler latency (ms) by handler",
             "boundaries": RPC_MS_BOUNDARIES, "samples": hist_samples},
            {"name": "gcs_rpc_calls_total", "type": "counter",
             "help": "GCS handler calls by handler",
             "samples": calls_samples},
            {"name": "gcs_rpc_inflight", "type": "gauge",
             "help": "GCS handler calls currently executing",
             "samples": ([[[], float(self.inflight_total)]]
                         + inflight_samples)},
            {"name": "gcs_pubsub_deliver_ms", "type": "histogram",
             "help": "pubsub publish->deliver latency (ms)",
             "boundaries": RPC_MS_BOUNDARIES,
             "samples": [[[], list(self.pubsub_counts),
                          self.pubsub_sum]]},
            gauge_snapshot("gcs_pubsub_backlog",
                           float(self.pubsub_pending),
                           "pubsub notifies accepted but not yet "
                           "delivered"),
            counter_snapshot("gcs_pubsub_delivered_total",
                             float(self.pubsub_delivered),
                             "pubsub notifies delivered"),
            counter_snapshot("gcs_pubsub_failed_total",
                             float(self.pubsub_failed),
                             "pubsub notifies dropped (dead subscriber)"),
        ]
        if errors_samples:
            rows.append({"name": "gcs_rpc_errors_total",
                         "type": "counter",
                         "help": "GCS handler errors by handler",
                         "samples": errors_samples})
        rows.extend(self._table_rows())
        return rows

    def _table_rows(self) -> List[Dict]:
        from ray_tpu.util.metrics import gauge_snapshot
        g = self.gcs
        kv_keys = sum(len(t) for t in g.kv.values())
        return [
            gauge_snapshot("gcs_kv_keys", float(kv_keys),
                           "keys across all GCS KV namespaces"),
            gauge_snapshot("gcs_table_rows", float(len(g.nodes)),
                           "GCS table sizes", tags={"table": "nodes"}),
            gauge_snapshot("gcs_table_rows", float(len(g.actors)),
                           "", tags={"table": "actors"}),
            gauge_snapshot("gcs_table_rows", float(len(g.task_events)),
                           "", tags={"table": "task_events"}),
            gauge_snapshot("gcs_table_rows",
                           float(len(g.object_ledger)),
                           "", tags={"table": "object_ledger"}),
            gauge_snapshot("gcs_table_rows",
                           float(len(g.placement_groups)),
                           "", tags={"table": "placement_groups"}),
            gauge_snapshot("gcs_table_rows",
                           float(len(getattr(g, "metrics", {}) or {})),
                           "", tags={"table": "metric_workers"}),
        ]

    def top_handlers(self, n: int = 3) -> List[Dict]:
        """Top-N handlers by approximate p99 — the status pane rows."""
        scored = []
        for name, st in self.handlers.items():
            if st.calls == 0:
                continue
            scored.append({"handler": name, "calls": st.calls,
                           "errors": st.errors, "slow": st.slow,
                           "inflight": st.inflight,
                           "p50_ms": round(st.p_quantile(0.50), 3),
                           "p99_ms": round(st.p_quantile(0.99), 3),
                           "avg_ms": round(st.sum / st.calls, 3)})
        scored.sort(key=lambda r: (-r["p99_ms"], -r["calls"]))
        return scored[:n]
