"""Node-local shared-memory object store client.

Python side of ``ray_tpu/native/shm_store.cpp``. Every process on a node maps
the same file under /dev/shm; create/seal/get/release are direct
shared-memory calls into the native library — no daemon round trip on the hot
path (contrast with the reference's plasma client/server unix-socket protocol,
reference: src/ray/object_manager/plasma/client.cc).

Reads are zero-copy: ``get`` returns memoryviews over the mapped arena, kept
valid by a pin that is released when the returned buffer object is freed.
"""

from __future__ import annotations

import ctypes
import os
import sys
import threading
from typing import Optional, Tuple

from ray_tpu._private.markers import off_loop
from ray_tpu.native.build import build

ID_LEN = 20
DEFAULT_STORE_BYTES = int(os.environ.get("RAY_TPU_OBJECT_STORE_BYTES", 2 * 1024**3))


class _Lib:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            lib = ctypes.CDLL(build("shm_store"))
            lib.rt_store_create.restype = ctypes.c_void_p
            lib.rt_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                            ctypes.c_int]
            lib.rt_store_open.restype = ctypes.c_void_p
            lib.rt_store_open.argtypes = [ctypes.c_char_p]
            lib.rt_store_close.argtypes = [ctypes.c_void_p]
            lib.rt_store_base.restype = ctypes.c_void_p
            lib.rt_store_base.argtypes = [ctypes.c_void_p]
            lib.rt_store_capacity.restype = ctypes.c_uint64
            lib.rt_store_capacity.argtypes = [ctypes.c_void_p]
            lib.rt_store_total_size.restype = ctypes.c_uint64
            lib.rt_store_total_size.argtypes = [ctypes.c_void_p]
            lib.rt_create.restype = ctypes.c_int64
            lib.rt_create.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.c_int,
            ]
            for fn in ("rt_seal", "rt_release", "rt_contains", "rt_delete", "rt_abort"):
                f = getattr(lib, fn)
                f.restype = ctypes.c_int
                f.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.rt_get.restype = ctypes.c_int64
            lib.rt_get.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_int,
            ]
            lib.rt_evict.restype = ctypes.c_uint64
            lib.rt_evict.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
            lib.rt_evict_stripe.restype = ctypes.c_uint64
            lib.rt_evict_stripe.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64]
            lib.rt_gc_unsealed.restype = ctypes.c_uint64
            lib.rt_gc_unsealed.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
            lib.rt_stats.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
            lib.rt_stripe_stats.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32,
                ctypes.POINTER(ctypes.c_uint64)]
            lib.rt_num_stripes.restype = ctypes.c_uint32
            lib.rt_num_stripes.argtypes = [ctypes.c_void_p]
            lib.rt_list.restype = ctypes.c_uint64
            lib.rt_list.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
            lib.rt_list_stripe.restype = ctypes.c_uint64
            lib.rt_list_stripe.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32, ctypes.c_char_p,
                ctypes.c_uint64]
            lib.rt_write_parallel.restype = None
            lib.rt_write_parallel.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
                ctypes.c_int,
            ]
            lib.rt_max_alloc_bytes.restype = ctypes.c_uint64
            lib.rt_max_alloc_bytes.argtypes = [ctypes.c_void_p]
            lib.rt_create_spanning.restype = ctypes.c_int64
            lib.rt_create_spanning.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.c_int,
            ]
            lib.rt_is_span.restype = ctypes.c_int
            lib.rt_is_span.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.rt_span_stats.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
            lib.rt_object_info.restype = ctypes.c_int64
            lib.rt_object_info.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_uint64)]
            lib.rt_stripe_frag.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32,
                ctypes.POINTER(ctypes.c_uint64)]
            lib.rt_now_sec.restype = ctypes.c_uint64
            lib.rt_now_sec.argtypes = []
            cls._instance = super().__new__(cls)
            cls._instance.lib = lib
        return cls._instance


def copy_threads() -> int:
    """Thread count for chunked arena copies (env RAY_TPU_PUT_COPY_THREADS;
    default: min(4, cpu_count), so a 1-core host does one plain GIL-free
    memcpy with no pool handoff)."""
    global _COPY_THREADS
    if _COPY_THREADS is None:
        raw = os.environ.get("RAY_TPU_PUT_COPY_THREADS", "")
        try:
            n = int(raw)
        except ValueError:
            n = min(4, os.cpu_count() or 1)
        _COPY_THREADS = max(1, n)
    return _COPY_THREADS


_COPY_THREADS = None


def parallel_write(dst_mv: memoryview, src_mv: memoryview) -> bool:
    """GIL-free (optionally multi-threaded) copy src_mv -> dst_mv through
    the native store library. Returns False when the fast path can't be
    taken (native lib unavailable, non-contiguous buffers) so the caller
    falls back to a plain slice assignment."""
    if not (dst_mv.contiguous and src_mv.contiguous):
        return False
    try:
        lib = _Lib().lib
        # numpy is address extraction only; no copy, handles readonly views
        import numpy as np
    except Exception:
        return False
    dst = np.frombuffer(dst_mv, dtype=np.uint8)
    src = np.frombuffer(src_mv, dtype=np.uint8)
    lib.rt_write_parallel(dst.ctypes.data, src.ctypes.data, src.nbytes,
                          copy_threads())
    return True


def store_path(session_name: str, node_id_hex: str) -> str:
    return f"/dev/shm/raytpu_{session_name}_{node_id_hex[:12]}"


if sys.version_info < (3, 12):  # pragma: no cover
    raise ImportError(
        "ray_tpu requires Python >= 3.12: zero-copy object reads tie shm "
        "pins to derived views via the PEP 688 __buffer__ protocol "
        "(see pyproject.toml requires-python)")


class _PinnedRegion:
    """Buffer exporter for one pinned object in the shared arena.

    Every view derived from ``memoryview(region)`` — slices, PickleBuffers,
    numpy arrays reconstructed from them — keeps this object alive through
    the CPython buffer protocol (PEP 688: the exported Py_buffer's ``obj``
    is this region). The store pin is released only when the last such view
    dies, so zero-copy reads can never be reclaimed under live user views
    (the same guarantee plasma gives by tying the pin to the client buffer,
    reference: src/ray/object_manager/plasma/client.cc).
    """

    __slots__ = ("_client", "_oid", "_mv")

    def __init__(self, client: "ObjectStoreClient", oid: bytes, mv: memoryview):
        self._client = client
        self._oid = oid
        self._mv = mv

    def __buffer__(self, flags):
        return self._mv[:]

    def __del__(self):
        try:
            self._client._release(self._oid)
        except Exception:
            pass


class SharedBuffer:
    """A pinned zero-copy read of an object's payload.

    ``close`` drops this handle's references; the underlying pin lives until
    the last view derived from ``data`` is garbage-collected.
    """

    __slots__ = ("data", "metadata", "_region")

    def __init__(self, region: _PinnedRegion, data: memoryview, metadata: bytes):
        self._region = region
        self.data = data
        self.metadata = metadata

    def close(self):
        self.data = None
        self._region = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class ObjectStoreClient:
    """Maps the node's shared arena and exposes object operations.

    The arena is striped into independently locked sub-heaps (see
    shm_store.cpp): ``stripes=0`` resolves via ``RAY_TPU_ARENA_STRIPES``
    then size-based auto-striping, so small test arenas stay
    single-stripe while production arenas spread same-node clients
    across locks.
    """

    def __init__(self, path: str, create: bool = False,
                 size: int = DEFAULT_STORE_BYTES, stripes: int = 0):
        self._lib = _Lib().lib
        self.path = path
        if create:
            self._h = self._lib.rt_store_create(path.encode(), size, stripes)
        else:
            self._h = self._lib.rt_store_open(path.encode())
        if not self._h:
            raise OSError(f"failed to {'create' if create else 'open'} object store at {path}")
        base = self._lib.rt_store_base(self._h)
        total = self._lib.rt_store_total_size(self._h)
        self._mem = (ctypes.c_uint8 * total).from_address(base)
        self._view = memoryview(self._mem).cast("B")
        # oid -> live pin count held by this client; used so close() can
        # release pins a crashed/leaked SharedBuffer would otherwise hold
        # forever, and so we never munmap while zero-copy views are live.
        # Mutated from caller threads (off-loop gets), the owner loop, and
        # GC finalizers (_PinnedRegion.__del__ runs on whatever thread
        # drops the last view) — the get/release counter updates are
        # read-modify-writes, so they hold _pins_lock.
        self._pins: dict = {}
        self._pins_lock = threading.Lock()

    # -- object ops ---------------------------------------------------------

    def _handle(self):
        """Live native handle, or a clean OSError after close(). Puts run
        on caller threads now, so a put racing shutdown must fail as a
        Python exception — never reach native code with a NULL store."""
        h = self._h
        if not h:
            raise OSError(f"object store client for {self.path} is closed")
        return h

    @off_loop(lock="_pins_lock")
    def create(self, oid: bytes, data_size: int, meta_size: int = 0,
               evictable: bool = True) -> Optional[Tuple[memoryview, memoryview]]:
        """Allocate a buffer; returns (data_view, meta_view) to write into.

        Returns None if the object already exists. Raises MemoryError if the
        arena is full even after LRU eviction.

        Objects larger than one arena stripe route to the SPANNING path
        natively (contiguous whole stripes, see shm_store.cpp): callers
        need no size awareness — the returned views simply cover the
        multi-stripe region, so sharded checkpoints / weight blobs put
        and ``recv_into`` exactly like small objects.
        """
        off = self._lib.rt_create(self._handle(), oid, data_size, meta_size,
                                  1 if evictable else 0)
        if off == -17:  # EEXIST
            return None
        if off < 0:
            raise self._arena_full(oid, data_size, off)
        data = self._view[off:off + data_size]
        meta = self._view[off + data_size:off + data_size + meta_size]
        return data, meta

    def _arena_full(self, oid: bytes, requested: int,
                    rc: int, spanning: bool = False) -> MemoryError:
        """Arena exhaustion is the event that triggers synchronous spills
        upstream — mark it on the flight-recorder timeline (so spill
        spans line up with the allocation that forced them) WITH the
        fragmentation breakdown attached, and raise a MemoryError whose
        message carries the same per-stripe live/free/largest-hole view
        so bug reports are self-diagnosing."""
        summary = self._frag_summary(requested)
        try:
            from ray_tpu._private import events
            attrs = {"object_id": oid.hex()[:16], "requested": requested,
                     "rc": rc, "spanning": spanning}
            try:
                frag = self.fragmentation()
                attrs["stripes"] = [
                    [st["stripe"], st["live"], st["free"],
                     st["largest_hole"]] for st in frag["stripes"]]
                attrs["spans"] = frag["spans"]
            except Exception:
                pass
            events.record_instant("store.arena_full", category="store",
                                  **attrs)
        except Exception:
            pass
        kind = "spanning create" if spanning else "object store create"
        return MemoryError(
            f"{kind} failed (rc={rc}): {summary}" if summary
            else f"{kind} failed (rc={rc})")

    def seal(self, oid: bytes) -> None:
        rc = self._lib.rt_seal(self._handle(), oid)
        if rc != 0:
            raise KeyError(f"seal failed for {oid.hex()} rc={rc}")

    def seal_and_release(self, oid: bytes) -> None:
        # seal() resets pin_count; creator's implicit pin is consumed by it.
        self.seal(oid)

    def abort(self, oid: bytes) -> None:
        self._lib.rt_abort(self._handle(), oid)

    @off_loop(lock="_pins_lock")
    def get(self, oid: bytes) -> Optional[SharedBuffer]:
        """Zero-copy read of a sealed object; None if not present."""
        dsize = ctypes.c_uint64()
        msize = ctypes.c_uint64()
        off = self._lib.rt_get(self._handle(), oid, ctypes.byref(dsize),
                               ctypes.byref(msize), 1)
        if off < 0:
            return None
        with self._pins_lock:
            self._pins[oid] = self._pins.get(oid, 0) + 1
        region = _PinnedRegion(self, oid, self._view[off:off + dsize.value])
        meta = bytes(self._view[off + dsize.value:off + dsize.value + msize.value])
        return SharedBuffer(region, memoryview(region), meta)

    @off_loop(lock="_pins_lock")
    def _release(self, oid: bytes) -> None:
        # runs on whatever thread drops the last zero-copy view (GC
        # finalizer), so the counter decrement must hold the lock too
        with self._pins_lock:
            if not (self._h and self._pins.get(oid)):
                return
            n = self._pins[oid] - 1
            if n:
                self._pins[oid] = n
            else:
                del self._pins[oid]
        self._lib.rt_release(self._h, oid)

    def contains(self, oid: bytes) -> bool:
        return bool(self._lib.rt_contains(self._handle(), oid))

    def delete(self, oid: bytes) -> None:
        self._lib.rt_delete(self._handle(), oid)

    def evict(self, nbytes: int) -> int:
        return self._lib.rt_evict(self._handle(), nbytes)

    def evict_stripe(self, stripe: int, nbytes: int) -> int:
        """Evict up to nbytes from ONE stripe (node-manager sweep path;
        contends only with that stripe's clients)."""
        return self._lib.rt_evict_stripe(self._handle(), stripe, nbytes)

    def gc_unsealed(self, max_age_sec: int = 300) -> int:
        """Reclaim orphaned never-sealed objects (writer died before seal)."""
        return self._lib.rt_gc_unsealed(self._handle(), max_age_sec)

    @off_loop(lock="_pins_lock")
    def put_bytes(self, oid: bytes, payload, metadata: bytes = b"") -> bool:
        """Convenience: create+write+seal. False if already present."""
        payload = memoryview(payload)
        bufs = self.create(oid, payload.nbytes, len(metadata))
        if bufs is None:
            return False
        data, meta = bufs
        # same GIL-free chunked path as put's write_to (spill restores and
        # cross-node transfers land multi-MB payloads through here)
        if payload.nbytes < 4 * 1024 * 1024 or \
                not parallel_write(data, payload):
            data[:] = payload
        if metadata:
            meta[:] = metadata
        self.seal(oid)
        return True

    def stats(self) -> dict:
        """Aggregate store stats. Lock-free on the native side (seqlock
        snapshots per stripe) — polling this never queues behind a
        client's create."""
        arr = (ctypes.c_uint64 * 17)()
        self._lib.rt_stats(self._handle(), arr)
        keys = ["bytes_in_use", "capacity", "num_objects", "num_evictions",
                "bytes_evicted", "create_count", "get_hits", "get_misses",
                "poisoned", "num_stripes", "stripe_repairs",
                "create_fallbacks", "seal_count", "num_spans",
                "span_creates", "span_evictions", "span_repairs"]
        return dict(zip(keys, arr))

    def max_alloc_bytes(self) -> int:
        """Largest payload (data+meta) the per-stripe allocator holds;
        one byte more routes to the spanning path transparently."""
        return int(self._lib.rt_max_alloc_bytes(self._handle()))

    def is_span(self, oid: bytes) -> bool:
        """True when oid names a live spanning (multi-stripe) object."""
        return bool(self._lib.rt_is_span(self._handle(), oid))

    def create_spanning(self, oid: bytes, data_size: int, meta_size: int = 0,
                        evictable: bool = True):
        """Force the spanning path regardless of size (tests exercise
        span machinery without multi-GB arenas). Same contract as
        ``create``."""
        off = self._lib.rt_create_spanning(
            self._handle(), oid, data_size, meta_size,
            1 if evictable else 0)
        if off == -17:  # EEXIST
            return None
        if off < 0:
            raise self._arena_full(oid, data_size, off, spanning=True)
        data = self._view[off:off + data_size]
        meta = self._view[off + data_size:off + data_size + meta_size]
        return data, meta

    def span_stats(self) -> dict:
        """Span-plane snapshot (weight-distribution observability)."""
        arr = (ctypes.c_uint64 * 8)()
        self._lib.rt_span_stats(self._handle(), arr)
        keys = ["live_spans", "span_bytes", "stripes_claimed",
                "span_creates", "span_evictions", "span_repairs",
                "broken_slots", "max_span_bytes"]
        return dict(zip(keys, arr))

    def num_stripes(self) -> int:
        return int(self._lib.rt_num_stripes(self._handle()))

    def now_sec(self) -> int:
        """CLOCK_MONOTONIC seconds — the base of object ctime stamps, so
        `now_sec() - info["ctime_sec"]` is an object's age."""
        return int(self._lib.rt_now_sec())

    def object_info(self, oid: bytes) -> Optional[dict]:
        """Per-object probe for the observability surface: size, pin
        count, placement, age base — WITHOUT pinning, touching LRU, or
        reading the payload (contrast `get`, which does all three).
        None when the object is not live."""
        arr = (ctypes.c_uint64 * 8)()
        rc = self._lib.rt_object_info(self._handle(), oid, arr)
        if rc < 0:
            return None
        return {"data_size": int(arr[0]), "meta_size": int(arr[1]),
                "pins": int(arr[2]), "stripe": int(arr[3]),
                "ctime_sec": int(arr[4]), "is_span": bool(arr[5]),
                "sealed": bool(arr[6]), "flags": int(arr[7])}

    def stripe_frag(self, stripe: int) -> dict:
        """Free-list walk of one stripe: total free bytes, the largest
        single hole (the biggest create the stripe could serve), and
        the free-block count. Span-claimed stripes report zero free."""
        arr = (ctypes.c_uint64 * 4)()
        self._lib.rt_stripe_frag(self._handle(), stripe, arr)
        return {"free_bytes": int(arr[0]), "largest_hole": int(arr[1]),
                "free_blocks": int(arr[2]), "bytes_in_use": int(arr[3])}

    def fragmentation(self) -> dict:
        """Machine-readable occupancy breakdown: per-stripe live/free/
        largest-hole plus span residency — what an "arena full" error
        attaches so bug reports are self-diagnosing."""
        stripes = []
        for i in range(self.num_stripes()):
            ss = self.stripe_stats(i)
            fr = self.stripe_frag(i)
            stripes.append({
                "stripe": i, "capacity": int(ss["capacity"]),
                "live": int(ss["bytes_in_use"]),
                "free": fr["free_bytes"],
                "largest_hole": fr["largest_hole"],
                "free_blocks": fr["free_blocks"],
                "objects": int(ss["num_objects"])})
        return {"stripes": stripes, "spans": self.span_stats()}

    def _frag_summary(self, requested: int) -> str:
        """Compact one-line breakdown for MemoryError messages (capped
        at 8 stripes; the full dict rides the store.arena_full
        instant)."""
        try:
            frag = self.fragmentation()
        except Exception:
            return ""
        parts = [f"requested={requested}"]
        for st in frag["stripes"][:8]:
            parts.append(
                f"s{st['stripe']}[live={st['live']} free={st['free']} "
                f"hole={st['largest_hole']}]")
        if len(frag["stripes"]) > 8:
            parts.append(f"(+{len(frag['stripes']) - 8} stripes)")
        sp = frag["spans"]
        if sp.get("live_spans"):
            parts.append(f"spans[{sp['live_spans']} live, "
                         f"{sp['span_bytes']}B, "
                         f"{sp['stripes_claimed']} stripes claimed]")
        return " ".join(parts)

    def stripe_stats(self, stripe: int) -> dict:
        """Lock-free per-stripe snapshot (sweep targeting, bench
        attribution)."""
        arr = (ctypes.c_uint64 * 8)()
        self._lib.rt_stripe_stats(self._handle(), stripe, arr)
        keys = ["bytes_in_use", "capacity", "num_objects", "num_evictions",
                "bytes_evicted", "repairs", "poisoned", "seal_count"]
        return dict(zip(keys, arr))

    def list_objects(self, max_n: int = 65536) -> list:
        buf = ctypes.create_string_buffer(max_n * ID_LEN)
        n = self._lib.rt_list(self._handle(), buf, max_n)
        raw = buf.raw
        return [raw[i * ID_LEN:(i + 1) * ID_LEN] for i in range(n)]

    def list_spans(self, max_n: int = 65536) -> list:
        """Sealed spanning-object ids. rt_list appends sealed spans
        after the per-stripe listings (spans live in the header-level
        span table, not any stripe's entry segment — which is why the
        per-stripe spill sweep never sees them); filter them back out
        via the lock-free rt_is_span probe."""
        return [o for o in self.list_objects(max_n) if self.is_span(o)]

    def list_stripe(self, stripe: int, max_n: int = 65536) -> list:
        """Sealed object ids resident in one stripe."""
        buf = ctypes.create_string_buffer(max_n * ID_LEN)
        n = self._lib.rt_list_stripe(self._handle(), stripe, buf, max_n)
        raw = buf.raw
        return [raw[i * ID_LEN:(i + 1) * ID_LEN] for i in range(n)]

    @off_loop(lock="_pins_lock")
    def close(self):
        """Release this client's pins. Unmaps only when no zero-copy views
        remain — a live SharedBuffer keeps the mapping for process lifetime
        (munmap under a live view would be a use-after-free)."""
        with self._pins_lock:
            if not self._h:
                return
            h = self._h
            if self._pins:
                # Outstanding zero-copy views: drop the pins so the objects
                # stay evictable node-wide, but keep the mapping alive.
                for oid, n in list(self._pins.items()):
                    for _ in range(n):
                        self._lib.rt_release(h, oid)
                self._pins.clear()
                self._h = None
                return
            self._h = None
        self._view.release()
        self._lib.rt_store_close(h)
