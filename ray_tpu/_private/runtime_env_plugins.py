"""Runtime-env plugin protocol (reference:
python/ray/_private/runtime_env/plugin.py RuntimeEnvPlugin +
plugin_schema_manager — each runtime_env dict key is owned by one plugin,
plugins run in priority order and stack their effects into one context).

Two plugin planes, mirroring where the reference applies them:

- **Worker-scope** plugins (env_vars / working_dir / py_modules / pip /
  user plugins) materialize INSIDE the worker at task setup and mutate a
  RuntimeEnvContext that the worker applies/restores around execution
  (reference: RuntimeEnvContext, runtime_env/context.py).
- **Process-scope** env kinds (container) shape the worker process
  itself, so they are resolved by the NODE MANAGER at spawn time into a
  command wrapper (reference: runtime_env/image_uri.py — worker command
  runs under `podman run`).

Third-party plugins load from the RAY_TPU_RUNTIME_ENV_PLUGINS env var as
comma-separated ``module:Class`` paths (reference:
RAY_RUNTIME_ENV_PLUGINS), or programmatically via register_plugin().
"""

from __future__ import annotations

import importlib
import logging
import os
import shlex
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)


class RuntimeEnvContext:
    """Mutable effect accumulator a worker applies around execution."""

    def __init__(self):
        self.env_vars: Dict[str, str] = {}
        self.py_paths: List[str] = []          # restored after the task
        self.permanent_py_paths: List[str] = []  # pip site: worker-lifetime
        self.cwd: Optional[str] = None


class RuntimeEnvPlugin:
    """Worker-scope plugin: owns the runtime_env key `name`.

    setup() runs on the worker's executor thread (blocking IO is fine)
    with the key's value, the full runtime_env dict, the context to
    mutate, and the CoreWorker (for GCS KV access etc.)."""

    name: str = ""
    priority: int = 50     # lower runs first (reference: plugin priority)

    def setup(self, value: Any, renv: Dict, ctx: RuntimeEnvContext,
              worker) -> None:
        raise NotImplementedError


class EnvVarsPlugin(RuntimeEnvPlugin):
    name = "env_vars"
    priority = 10

    def setup(self, value, renv, ctx, worker):
        for k, v in (value or {}).items():
            ctx.env_vars[str(k)] = str(v)


class WorkingDirPlugin(RuntimeEnvPlugin):
    """Handles both a live local path and the packed working_dir_uri
    form produced at submission (worker.py _pack_runtime_env)."""
    name = "working_dir"
    priority = 20

    def setup(self, value, renv, ctx, worker):
        wd = value
        if not wd and renv.get("working_dir_uri"):
            wd = worker._materialize_uri(renv["working_dir_uri"],
                                         renv.get("working_dir_base", ""))
        if wd:
            ctx.cwd = wd
            ctx.py_paths.append(wd)


class PyModulesPlugin(RuntimeEnvPlugin):
    name = "py_modules"
    priority = 30

    def setup(self, value, renv, ctx, worker):
        for uri, base in renv.get("py_modules_uris") or []:
            root = worker._materialize_uri(uri, base)
            ctx.py_paths.append(os.path.dirname(root))


class PipPlugin(RuntimeEnvPlugin):
    name = "pip"
    priority = 40

    def setup(self, value, renv, ctx, worker):
        if not value:
            return
        if isinstance(value, dict):
            value = value.get("packages") or []
        site = worker._ensure_pip_env([str(x) for x in value])
        # worker-lifetime: the pool only reuses this worker for the same
        # env hash, so the site-dir stays correct (per-env worker pools)
        ctx.permanent_py_paths.append(site)


_BUILTIN = [EnvVarsPlugin(), WorkingDirPlugin(), PyModulesPlugin(),
            PipPlugin()]
_EXTRA: List[RuntimeEnvPlugin] = []
_LOADED_FROM_ENV = False


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    """Programmatic registration (dedup by plugin name)."""
    unregister_plugin(plugin.name)
    _EXTRA.append(plugin)


def unregister_plugin(name: str) -> None:
    _EXTRA[:] = [p for p in _EXTRA if p.name != name]


def _load_env_plugins() -> None:
    """RAY_TPU_RUNTIME_ENV_PLUGINS="pkg.mod:Class,..." (reference:
    RAY_RUNTIME_ENV_PLUGINS json spec; module:attr matches this repo's
    xlang convention). Loaded once, lazily, in the worker process."""
    global _LOADED_FROM_ENV
    if _LOADED_FROM_ENV:
        return
    _LOADED_FROM_ENV = True
    spec = os.environ.get("RAY_TPU_RUNTIME_ENV_PLUGINS", "")
    for path in filter(None, (s.strip() for s in spec.split(","))):
        try:
            mod, _, attr = path.partition(":")
            cls = getattr(importlib.import_module(mod), attr)
            register_plugin(cls())
        except Exception:
            logger.exception("failed to load runtime env plugin %r", path)


def plugins() -> List[RuntimeEnvPlugin]:
    _load_env_plugins()
    return sorted(_BUILTIN + _EXTRA, key=lambda p: p.priority)


def apply_worker_plugins(renv: Dict, worker) -> RuntimeEnvContext:
    """Dispatch every plugin whose key appears in `renv` (priority
    order), returning the accumulated context. Unknown renv keys without
    a plugin are ignored, matching the reference's pass-through for
    keys handled elsewhere (e.g. container at spawn time)."""
    ctx = RuntimeEnvContext()
    for p in plugins():
        if p.name in renv or (p.name == "working_dir"
                              and "working_dir_uri" in renv) \
                or (p.name == "py_modules" and "py_modules_uris" in renv):
            p.setup(renv.get(p.name), renv, ctx, worker)
    return ctx


def runtime_env_hash(renv: Optional[Dict]) -> Optional[str]:
    """Worker-pool key for a runtime env (reference: WorkerPool keyed by
    runtime-env hash, worker_pool.h:174): a pip env permanently shapes a
    worker's sys.path and a container permanently shapes the process, so
    such workers are never handed to tasks/actors of other envs. ONE
    hash scheme for both the task-lease and actor-creation paths —
    split schemes would let a container worker with one pip env be
    adopted for the same container with a different pip env."""
    if not renv:
        return None
    pip = renv.get("pip")
    proc = proc_env_of(renv)
    if not pip and not proc:
        return None
    import hashlib
    if isinstance(pip, dict):
        pip = pip.get("packages") or []
    parts = ["\n".join(sorted(map(str, pip or [])))]
    if proc:
        parts.append(repr(sorted(proc["container"].items())))
    return hashlib.sha1("\x00".join(parts).encode()).hexdigest()[:16]


# --------------------------------------------------- process-scope: container
def proc_env_of(renv: Optional[Dict]) -> Optional[Dict]:
    """The process-level subset of a runtime env — what the node manager
    needs at worker SPAWN time (today: container). Rides the lease
    request next to env_hash."""
    if not renv:
        return None
    container = renv.get("container") or (
        {"image": renv["image_uri"]} if renv.get("image_uri") else None)
    if not container:
        return None
    if isinstance(container, str):
        container = {"image": container}
    return {"container": container}


# env vars forwarded into the container (the worker needs its node/GCS
# wiring plus accelerator/runtime knobs; a blanket pass-through would
# leak host state the image should not see)
_FORWARD_PREFIXES = ("RAY_TPU_", "JAX_", "XLA_", "TPU_", "PYTHON")


def container_command(proc_env: Dict, cmd: List[str],
                      env: Dict[str, str]) -> List[str]:
    """Wrap a worker command in `<runtime> run` (reference:
    runtime_env/image_uri.py _modify_context — worker under podman).
    --network=host keeps the RPC plane flat; /tmp/raytpu (sockets, shm
    store, logs, runtime-env cache) is bind-mounted so the containered
    worker shares the node's data plane.

    The runtime binary defaults to podman, overridable via
    RAY_TPU_CONTAINER_RUNTIME (also how tests inject a stub)."""
    spec = proc_env["container"]
    image = spec["image"]
    runtime = os.environ.get("RAY_TPU_CONTAINER_RUNTIME",
                             spec.get("runtime", "podman"))
    wrapped = [runtime, "run", "--rm", "--network=host",
               "-v", "/tmp/raytpu:/tmp/raytpu"]
    for k, v in env.items():
        if k.startswith(_FORWARD_PREFIXES) or k == "PATH":
            wrapped += ["--env", f"{k}={v}"]
    for opt in spec.get("run_options") or []:
        wrapped += shlex.split(str(opt)) if isinstance(opt, str) else [opt]
    wrapped.append(image)
    wrapped += cmd
    return wrapped
