"""Object serialization: cloudpickle v5 with out-of-band zero-copy buffers.

Counterpart of the reference's SerializationContext (reference:
python/ray/_private/serialization.py): pickle-5 out-of-band buffers give
zero-copy reads of numpy/jax-host arrays straight from the shm arena, and
ObjectRefs embedded in values are detected during pickling so ownership and
reference counting can track them (the borrowing protocol's entry point,
reference: src/ray/core_worker/reference_count.h:64).

Store layout for one object:
  data region  = concat of 64-byte-aligned out-of-band buffers
  meta region  = msgpack {kind, pkl, offs, lens}
Inline objects (< INLINE_THRESHOLD) travel as (pkl, [buf bytes...]) tuples
inside RPC frames instead of the store.
"""

from __future__ import annotations

import pickle
from typing import Any, Callable, List, Optional, Tuple

import cloudpickle
import msgpack

INLINE_THRESHOLD = 100 * 1024
_ALIGN = 64

# Buffers at or above this size are copied into the arena through the
# native rt_write_parallel entry point (object_store.parallel_write):
# ctypes drops the GIL for the call, so concurrent putters overlap, and
# multi-core hosts additionally chunk the copy across a small pool.
PARALLEL_COPY_MIN = 4 * 1024 * 1024

_parallel_write = None     # resolved lazily; False = permanently unavailable


def _native_copy(dst_mv: memoryview, src_mv: memoryview) -> bool:
    global _parallel_write
    if _parallel_write is None:
        try:
            from ray_tpu._private.object_store import parallel_write
            _parallel_write = parallel_write
        except Exception:
            _parallel_write = False
    if not _parallel_write:
        return False
    try:
        return _parallel_write(dst_mv, src_mv)
    except Exception:
        return False

KIND_PY = 0       # ordinary python object
KIND_ERR = 1      # serialized exception (raised on get)
KIND_RAW = 2      # raw bytes payload (zero pickling)
KIND_MSGPACK = 3  # msgpack payload (cross-language: C++ API frontend)


class SerializedObject:
    __slots__ = ("kind", "pkl", "buffers", "contained_refs")

    def __init__(self, kind: int, pkl: bytes, buffers: List, contained_refs: List):
        self.kind = kind
        self.pkl = pkl
        self.buffers = buffers          # list of objects with buffer protocol
        self.contained_refs = contained_refs

    @property
    def total_bytes(self) -> int:
        n = len(self.pkl)
        for b in self.buffers:
            n += _ALIGN + memoryview(b).nbytes
        return n

    def is_inline(self) -> bool:
        return self.total_bytes < INLINE_THRESHOLD

    # -------- wire form (inline objects inside rpc frames)
    def to_wire(self) -> Tuple[int, bytes, List[bytes]]:
        return (self.kind, self.pkl,
                [memoryview(b).tobytes() if not isinstance(b, bytes) else b
                 for b in self.buffers])

    # -------- store form
    def write_to(self, data_mv: memoryview) -> None:
        off = 0
        for b in self.buffers:
            mv = memoryview(b).cast("B")
            n = mv.nbytes
            if n < PARALLEL_COPY_MIN or \
                    not _native_copy(data_mv[off:off + n], mv):
                data_mv[off:off + n] = mv
            off += _aligned(n)

    def store_meta(self) -> bytes:
        offs, lens = [], []
        off = 0
        for b in self.buffers:
            n = memoryview(b).nbytes
            offs.append(off)
            lens.append(n)
            off += _aligned(n)
        return msgpack.packb({"k": self.kind, "p": self.pkl,
                              "o": offs, "l": lens}, use_bin_type=True)

    def data_size(self) -> int:
        return sum(_aligned(memoryview(b).nbytes) for b in self.buffers)


def _aligned(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


def serialize(obj: Any, ref_hook: Optional[Callable] = None) -> SerializedObject:
    """ref_hook(ref) is called for every ObjectRef encountered while pickling."""
    contained: List = []
    if isinstance(obj, bytes) and len(obj) > INLINE_THRESHOLD:
        return SerializedObject(KIND_RAW, b"", [obj], contained)
    buffers: List[pickle.PickleBuffer] = []

    def buffer_cb(pb: pickle.PickleBuffer):
        buffers.append(pb)
        return False  # out-of-band

    from ray_tpu._private import object_ref  # cycle-free at call time
    prev = getattr(object_ref._ser_tls, "hook", None)
    try:
        def hook(ref):
            contained.append(ref)
            if ref_hook is not None:
                ref_hook(ref)
        object_ref._ser_tls.hook = hook
        pkl = cloudpickle.dumps(obj, protocol=5, buffer_callback=buffer_cb)
    finally:
        object_ref._ser_tls.hook = prev
    return SerializedObject(KIND_PY, pkl, buffers, contained)


def serialize_error(exc: BaseException) -> SerializedObject:
    import traceback
    try:
        pkl = cloudpickle.dumps(exc, protocol=5)
    except Exception:
        pkl = cloudpickle.dumps(
            RuntimeError(f"{type(exc).__name__}: {exc}\n"
                         + "".join(traceback.format_exception(exc))),
            protocol=5)
    return SerializedObject(KIND_ERR, pkl, [], [])


def deserialize_wire(kind: int, pkl: bytes, buffers: List[bytes]) -> Any:
    if kind == KIND_RAW:
        return buffers[0]
    if kind == KIND_MSGPACK:
        return msgpack.unpackb(buffers[0], raw=False, strict_map_key=False)
    obj = pickle.loads(pkl, buffers=[pickle.PickleBuffer(b) for b in buffers])
    if kind == KIND_ERR:
        raise TaskError(obj)
    return obj


def deserialize_from_store(data_mv: memoryview, meta: bytes) -> Any:
    m = msgpack.unpackb(meta, raw=False)
    kind = m["k"]
    bufs = [data_mv[o:o + n] for o, n in zip(m["o"], m["l"])]
    if kind == KIND_RAW:
        return bytes(bufs[0])
    if kind == KIND_MSGPACK:
        return msgpack.unpackb(bufs[0], raw=False, strict_map_key=False)
    obj = pickle.loads(m["p"], buffers=[pickle.PickleBuffer(b) for b in bufs])
    if kind == KIND_ERR:
        raise TaskError(obj)
    return obj


class TaskError(Exception):
    """Wraps an exception raised inside a remote task/actor method
    (reference: python/ray/exceptions.py RayTaskError). Raised on ray.get."""

    def __init__(self, cause: BaseException):
        self.cause = cause
        super().__init__(f"task failed: {type(cause).__name__}: {cause}")

    def __reduce__(self):
        return (TaskError, (self.cause,))


class ActorDiedError(Exception):
    pass


class ObjectLostError(Exception):
    pass


class WorkerCrashedError(Exception):
    pass


class TaskCancelledError(Exception):
    pass
