"""Process-lifetime plumbing shared by every ray_tpu daemon.

The round-4 audit found 131 ray_tpu processes alive after a green test
suite: daemons are spawned with start_new_session=True (so they never
get the driver's SIGINT), and nothing tied their lifetime to their
spawner. The reference solves this with parent-death signals and the
raylet's bounded GCS-reconnect timeout
(src/ray/raylet/main.cc:123 shutdown path,
gcs_rpc_server_reconnect_timeout_s); this module is the TPU-runtime
equivalent:

- the SPAWNER exports RAY_TPU_PDEATHSIG=<signo> in the child's env;
- the CHILD calls set_pdeathsig_from_env() first thing in main(), which
  arms prctl(PR_SET_PDEATHSIG) against ITS OWN parent — so a dead
  driver reaps its GCS/node manager, and a dead node manager reaps its
  workers, transitively, even on SIGKILL.

Detached clusters (`ray_tpu start --head`) simply don't export the
variable and outlive the CLI as before.
"""

from __future__ import annotations

import os
import signal
import subprocess
from typing import Iterable

PDEATHSIG_ENV = "RAY_TPU_PDEATHSIG"
PDEATHSIG_PARENT_ENV = "RAY_TPU_PDEATHSIG_PARENT"
_PR_SET_PDEATHSIG = 1


def set_pdeathsig_from_env() -> None:
    """Arm PR_SET_PDEATHSIG from the spawner's env marker (no-op when
    unset or on non-Linux). Call first thing in a daemon's main()."""
    raw = os.environ.get(PDEATHSIG_ENV)
    if not raw:
        return
    try:
        signo = int(raw)
        import ctypes
        libc = ctypes.CDLL(None, use_errno=True)
        libc.prctl(_PR_SET_PDEATHSIG, signo, 0, 0, 0)
        # the parent may have died in the fork->here window, in which
        # case the signal was never delivered. Compare against the
        # RECORDED spawner pid — a bare getppid()==1 check would
        # self-kill legitimate children of a PID-1 driver (containers)
        expected = os.environ.get(PDEATHSIG_PARENT_ENV)
        if expected and os.getppid() != int(expected):
            os.kill(os.getpid(), signo)
    except Exception:
        pass    # best-effort; the bounded-reconnect timeout still holds


def child_env(env: dict | None = None, signo: int = signal.SIGTERM) -> dict:
    """Env dict for a non-detached child: spawner's env + the
    parent-death marker (and the spawner's pid, to detect a parent that
    died before the child could arm the signal)."""
    out = dict(os.environ if env is None else env)
    out[PDEATHSIG_ENV] = str(int(signo))
    out[PDEATHSIG_PARENT_ENV] = str(os.getpid())
    return out


def kill_process_group(proc: subprocess.Popen,
                       sig: int = signal.SIGKILL) -> None:
    """Kill a start_new_session child AND anything it spawned into its
    process group (user tasks fork; reaping just the leader leaks the
    grandchildren)."""
    if proc.pid is None:
        return
    try:
        os.killpg(proc.pid, sig)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            proc.kill()
        except OSError:
            pass


def find_session_processes(marker: str) -> Iterable[int]:
    """PIDs of live ray_tpu daemons whose environment carries the given
    RAY_TPU_TEST_SESSION marker value (used by the suite-final hygiene
    check). Scans /proc; skips unreadable entries."""
    me = os.getpid()
    for pid_s in os.listdir("/proc"):
        if not pid_s.isdigit() or int(pid_s) == me:
            continue
        try:
            with open(f"/proc/{pid_s}/stat") as f:
                state = f.read().rsplit(")", 1)[1].split()[0]
            if state == "Z":    # exited, just not yet reaped
                continue
            with open(f"/proc/{pid_s}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ")
            if b"ray_tpu" not in cmd:
                continue
            with open(f"/proc/{pid_s}/environ", "rb") as f:
                env = f.read()
            if f"RAY_TPU_TEST_SESSION={marker}".encode() in env:
                yield int(pid_s)
        except OSError:
            continue


def format_thread_stacks() -> dict:
    """{thread_name: formatted stack} for every live thread in THIS
    process — the in-process substrate of `ray_tpu stack` (reference:
    `ray stack` shells out to py-spy, ray/scripts/scripts.py; here every
    daemon serves its own frames over RPC, no ptrace needed)."""
    import sys
    import threading
    import traceback
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        name = names.get(ident, f"thread-{ident}")
        out[f"{name} ({ident})"] = "".join(traceback.format_stack(frame))
    return out
