"""Binary data plane for cross-node object transfer.

Each node manager listens on a second raw-stream TCP socket (advertised
next to the RPC address in the GCS cluster view) that carries ONLY bulk
object chunk bytes. The control plane keeps negotiating transfers
(``request_push``/``push_begin``) over the msgpack RPC connection; the
chunk payloads move here, framed as ``[u32 header_len][msgpack header]
[raw chunk bytes]`` with no serialization of the payload itself:

- the sender writes ``memoryview`` slices of the pinned arena buffer
  straight into ``loop.sock_sendall`` (no ``bytes()`` staging copy, no
  msgpack encode of the chunk);
- the receiver ``recv_into()``s straight into the ``store.create``
  region for the object (no intermediate buffer, no decode copy).

This keeps heartbeats / lease grants / pubsub off the bulk path — an
8 MB chunk can no longer head-of-line-block a lease grant behind it on
the shared RPC socket (the round-5 false-node-death risk during large
broadcasts), and drops the per-chunk copy count from ~4 to the two
irreducible kernel copies.

Large objects stripe across up to ``cfg.transfer_streams`` parallel
data connections with contiguous per-stripe offset ranges; each stripe
keeps the existing ``cfg.push_window_chunks`` in-flight window (an
8-byte ack per chunk provides the flow control and surfaces receiver
aborts mid-stream). Reference shape: the dedicated chunked transfer
path distinct from control RPCs in the reference object manager
(object_manager Push/Pull, pull_manager.h:52, push_manager.h:30).

Wire protocol (one direction per role; a connection is used by exactly
one stripe of one transfer at a time, so acks return in order):

  client -> server   MAGIC(8B) once, then per chunk:
                     [u32 header_len][msgpack [oid, offset, len, seq]]
                     [len raw bytes]
  server -> client   per chunk: [u32 seq][u32 status]

Status codes: 0 chunk ok; 1 no receive state / aborted (sender must
error the push — the pull side retries); 2 finish failed (seal or relay
subtree error); 3 final chunk ok, object sealed and relay subtree done
(the ack for the completing chunk resolves only after the receiver's
relay fan-out finishes, so a broadcast root's await still covers the
whole tree, exactly like the msgpack path's last-chunk response).
"""

from __future__ import annotations

import asyncio
import logging
import socket
import time
from collections import deque
from typing import Dict, List, Optional

import msgpack

from ray_tpu._private import rpc
from ray_tpu._private.config import cfg

logger = logging.getLogger(__name__)

MAGIC = b"RTPDATA1"
_MAX_HEADER = 4096
# ack status codes
OK = 0
ABORTED = 1
FINISH_FAILED = 2
DONE = 3


class DataPlaneError(RuntimeError):
    """Transfer failed mid-stream (bytes may be half-delivered)."""


class DataPlaneUnavailable(ConnectionError):
    """No data connection could be established; ZERO payload bytes were
    sent, so the caller may safely fall back to the msgpack path against
    the same negotiated receive state."""


def adaptive_streams(size: int) -> int:
    """Stream count for one transfer of `size` bytes: weight-sized
    objects (>= cfg.transfer_large_object_bytes) escalate from the
    cfg.transfer_streams default to cfg.transfer_streams_large — a
    multi-GB broadcast wants every core's kernel-copy bandwidth, while
    small transfers keep striping overhead off the wire. The escalation
    is off whenever transfer_streams_large <= transfer_streams."""
    streams = cfg.transfer_streams
    large = cfg.transfer_streams_large
    if large > streams and size >= cfg.transfer_large_object_bytes:
        return large
    return streams


def stripe_ranges(size: int, streams: int, stripe_min: int) -> List[tuple]:
    """Split [0, size) into contiguous (offset, length) stripes: at most
    `streams`, each at least `stripe_min` bytes (except a small final
    object's single stripe)."""
    if size <= 0:
        return [(0, 0)]
    n = max(1, min(int(streams), size // max(1, int(stripe_min))))
    base, rem = divmod(size, n)
    ranges, off = [], 0
    for i in range(n):
        length = base + (1 if i < rem else 0)
        ranges.append((off, length))
        off += length
    return ranges


def binomial_split(targets: List[str]) -> List[tuple]:
    """Binomial-tree fan-out plan: split `targets` into (head, rest)
    pairs — the sender pushes to each head with `rest` delegated as its
    relay subtree, so the source sends O(log n) copies instead of n.
    Pure planning half of NodeManager.h_broadcast_object (unit-testable
    on any interpreter)."""
    plan = []
    targets = list(targets)
    while targets:
        mid = (len(targets) + 1) // 2
        plan.append((targets[0], targets[1:mid]))
        targets = targets[mid:]
    return plan


def plan_rebroadcast(missing: List[str], holders: List[str]) -> List[tuple]:
    """Retry plan after a relay node died mid-subtree: shard the nodes
    that never received the object across every SURVIVING holder
    (round-robin), each holder re-broadcasting its shard through its own
    relay tree. Returns (holder, [targets]) pairs; empty when nothing is
    missing or no holder survives."""
    holders = [h for h in holders if h]
    if not missing or not holders:
        return []
    shards: Dict[str, List[str]] = {h: [] for h in holders}
    for i, node in enumerate(missing):
        shards[holders[i % len(holders)]].append(node)
    return [(h, nodes) for h, nodes in shards.items() if nodes]


async def _recv_exact_into(loop, sock, view: memoryview, *,
                           on_bytes=None) -> None:
    pos, total = 0, len(view)
    while pos < total:
        n = await loop.sock_recv_into(sock, view[pos:])
        if n == 0:
            raise ConnectionError("data-plane peer closed mid-frame")
        pos += n
        if on_bytes is not None:
            on_bytes(n)


class DataPlaneServer:
    """Receiver side: accepts raw data connections and writes incoming
    chunks straight into the node manager's in-progress receive regions
    (``nm._receiving``). Runs on the node manager's event loop; every
    await point is a socket op, never a Python-level copy of the payload
    (the kernel copies into the mapped arena)."""

    def __init__(self, node_manager):
        self.nm = node_manager
        self._sock: Optional[socket.socket] = None
        self._accept_task: Optional[asyncio.Task] = None
        self._conn_tasks: set = set()
        self.address: Optional[str] = None
        # observability counters (surfaced via get_node_info)
        self.bytes_in = 0
        self.chunks_in = 0

    async def start(self, host: str = "0.0.0.0", port: int = 0) -> str:
        sock = socket.create_server((host, port), backlog=128)
        sock.setblocking(False)
        self._sock = sock
        self._accept_task = asyncio.ensure_future(self._accept_loop())
        addr_port = sock.getsockname()[1]
        self.address = f"tcp:{rpc._advertise_host(host)}:{addr_port}"
        return self.address

    async def close(self):
        victims = [t for t in [self._accept_task, *self._conn_tasks]
                   if t is not None and not t.done()]
        for t in victims:
            t.cancel()
        if victims:
            await asyncio.gather(*victims, return_exceptions=True)
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    @property
    def active_conns(self) -> int:
        return len(self._conn_tasks)

    async def _accept_loop(self):
        loop = asyncio.get_event_loop()
        while True:
            try:
                conn, _peer = await loop.sock_accept(self._sock)
            except (asyncio.CancelledError, OSError):
                return
            conn.setblocking(False)
            try:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            t = asyncio.ensure_future(self._serve_conn(conn))
            self._conn_tasks.add(t)
            t.add_done_callback(self._conn_tasks.discard)

    async def _serve_conn(self, conn: socket.socket):
        loop = asyncio.get_event_loop()
        current_oid = None
        try:
            magic = bytearray(len(MAGIC))
            await _recv_exact_into(loop, conn, memoryview(magic))
            if bytes(magic) != MAGIC:
                return
            hdr4 = bytearray(4)
            while True:
                await _recv_exact_into(loop, conn, memoryview(hdr4))
                hlen = int.from_bytes(hdr4, "little")
                if not 0 < hlen <= _MAX_HEADER:
                    raise ConnectionError(
                        f"bad data-plane header length {hlen}")
                hbuf = bytearray(hlen)
                await _recv_exact_into(loop, conn, memoryview(hbuf))
                oid, offset, length, seq = msgpack.unpackb(bytes(hbuf))
                current_oid = oid
                status = await self._receive_chunk(loop, conn, oid,
                                                   offset, length)
                current_oid = None
                await loop.sock_sendall(
                    conn, seq.to_bytes(4, "little")
                    + status.to_bytes(4, "little"))
        except asyncio.CancelledError:
            raise
        except (ConnectionError, OSError):
            # pusher died (or was reaped) mid-frame: a half-written chunk
            # poisons the receive — abort it NOW so parked pulls retry on
            # a surviving path instead of waiting out the 60s sweep
            if current_oid is not None:
                self._abort_mid_chunk(current_oid)
        except Exception:
            logger.exception("data-plane connection handler failed")
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _abort_mid_chunk(self, oid: bytes):
        st = self.nm._receiving.get(oid)
        if st is None:
            return
        st["aborted"] = True
        if not st.get("writers"):
            self.nm._abort_receive(
                oid, "data connection lost mid-chunk (pusher died?)")

    async def _receive_chunk(self, loop, conn, oid: bytes, offset: int,
                             length: int) -> int:
        nm = self.nm
        st = nm._receiving.get(oid)
        if st is None or st.get("aborted"):
            if st is not None and not st.get("writers"):
                # marked aborted while no writer was active (e.g. the
                # reap sweep raced a chunk boundary): release it here —
                # the deferred-to-writer cleanup has no writer to run in
                nm._abort_receive(oid, "receive aborted mid-stream")
            await self._drain(loop, conn, length)
            return ABORTED
        st["writers"] = st.get("writers", 0) + 1
        st.setdefault("conns", set()).add(conn)

        def _touch(n):
            st["t"] = time.monotonic()
            self.bytes_in += n

        try:
            view = st["data"][offset:offset + length]
            await _recv_exact_into(loop, conn, view, on_bytes=_touch)
        finally:
            st["writers"] -= 1
            st["conns"].discard(conn)
        self.chunks_in += 1
        if st.get("aborted"):
            # the reap sweep marked us stale while the chunk was in
            # flight; it deferred the store abort to the active writer
            if not st["writers"]:
                nm._abort_receive(oid, "receive reaped mid-stream")
            return ABORTED
        st["remaining"] -= length
        if st["remaining"] > 0:
            return OK
        res = nm._finish_receive(oid)
        if asyncio.isfuture(res) or isinstance(res, asyncio.Task):
            # completing chunk's ack resolves only after the relay
            # subtree: the broadcast root's await covers the whole tree
            try:
                await res
            except Exception:
                return FINISH_FAILED
        return DONE

    async def _drain(self, loop, conn, length: int):
        """Consume a chunk that has no live receive state (e.g. reaped):
        the framing must stay in sync so the NEXT transfer on this
        connection still parses."""
        scratch = bytearray(min(length, 1 << 20))
        left = length
        while left > 0:
            view = memoryview(scratch)[:min(left, len(scratch))]
            n = await loop.sock_recv_into(conn, view)
            if n == 0:
                raise ConnectionError("data-plane peer closed mid-drain")
            left -= n


class DataPlaneClient:
    """Sender side: pools raw data connections per peer data address and
    streams pinned-arena memoryview slices over them, striped across up
    to ``cfg.transfer_streams`` connections."""

    def __init__(self, name: str = "dp"):
        self.name = name
        self._free: Dict[str, List[socket.socket]] = {}
        self._max_pooled = 8
        self.bytes_out = 0
        self.chunks_out = 0

    async def _connect(self, addr: str) -> socket.socket:
        parsed = rpc.parse_address(addr)
        if parsed[0] != "tcp":
            raise DataPlaneUnavailable(f"data plane needs tcp, got {addr}")
        loop = asyncio.get_event_loop()
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setblocking(False)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            await loop.sock_connect(sock, (parsed[1], parsed[2]))
            await loop.sock_sendall(sock, MAGIC)
        except (OSError, asyncio.CancelledError):
            sock.close()
            raise
        return sock

    async def _acquire(self, addr: str, n: int) -> List[socket.socket]:
        socks = []
        free = self._free.get(addr)
        while free and len(socks) < n:
            socks.append(free.pop())
        try:
            while len(socks) < n:
                socks.append(await self._connect(addr))
        except OSError as e:
            for s in socks:
                self._release(addr, s)
            raise DataPlaneUnavailable(
                f"cannot reach data plane at {addr}: {e}")
        return socks

    def _release(self, addr: str, sock: socket.socket):
        free = self._free.setdefault(addr, [])
        if len(free) < self._max_pooled:
            free.append(sock)
        else:
            sock.close()

    def _discard(self, sock: socket.socket):
        try:
            sock.close()
        except OSError:
            pass

    def close(self):
        for socks in self._free.values():
            for s in socks:
                self._discard(s)
        self._free.clear()

    async def push(self, addr: str, oid: bytes, data: memoryview,
                   size: int) -> List[int]:
        """Stream `data` (the object's pinned arena view) to the peer's
        data plane. Returns per-stripe byte counts. Raises
        DataPlaneUnavailable before any payload byte moved,
        DataPlaneError after (the receive state is then poisoned; the
        caller must error the push and let the pull side retry)."""
        ranges = stripe_ranges(size, adaptive_streams(size),
                               cfg.transfer_stripe_min_bytes)
        socks = await self._acquire(addr, len(ranges))
        sent = [0]      # payload bytes this push put on the wire
        tasks = [asyncio.ensure_future(
            self._send_stripe(socks[i], oid, data, off, length, sent))
            for i, (off, length) in enumerate(ranges)]
        try:
            await asyncio.gather(*tasks)
        except BaseException as e:
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            # a failed/cancelled stripe leaves its connection mid-frame:
            # never return it to the pool
            for s in socks:
                self._discard(s)
            if isinstance(e, (DataPlaneError, asyncio.CancelledError)):
                raise
            if not sent[0]:
                # a stale pooled connection died on the first header:
                # nothing moved, the msgpack fallback is still safe
                raise DataPlaneUnavailable(
                    f"data plane at {addr} dropped before payload: {e}")
            raise DataPlaneError(
                f"data-plane push of {oid.hex()[:16]} failed: {e}") from e
        for s in socks:
            self._release(addr, s)
        return [length for _off, length in ranges]

    async def _send_stripe(self, sock, oid: bytes, data: memoryview,
                           start: int, length: int, sent: List[int]):
        loop = asyncio.get_event_loop()
        chunk = cfg.transfer_chunk_bytes
        window: deque = deque()
        seq = 0
        off, stop = start, start + length
        while off < stop:
            n = min(chunk, stop - off)
            # same chaos spec key as the msgpack path: the fault-
            # injection suites keep covering chunk pushes on this
            # transport (RAY_TPU_TESTING_RPC_FAILURE="push_chunk=p")
            rpc._maybe_inject_failure("push_chunk")
            hdr = msgpack.packb([oid, off, n, seq])
            await loop.sock_sendall(
                sock, len(hdr).to_bytes(4, "little") + hdr)
            # header committed: the receiver is now engaged mid-chunk, so
            # a later failure must NOT fall back to msgpack (count the
            # chunk as sent before the payload write can partially fail)
            sent[0] += n
            # the payload leaves as a memoryview slice of the pinned
            # arena: the only copy is the kernel's
            await loop.sock_sendall(sock, data[off:off + n])
            self.bytes_out += n
            self.chunks_out += 1
            window.append(seq)
            seq += 1
            off += n
            if len(window) >= cfg.push_window_chunks:
                await self._read_ack(loop, sock, window.popleft(), oid)
        while window:
            await self._read_ack(loop, sock, window.popleft(), oid)

    async def _read_ack(self, loop, sock, want_seq: int, oid: bytes):
        buf = bytearray(8)
        await _recv_exact_into(loop, sock, memoryview(buf))
        seq = int.from_bytes(buf[:4], "little")
        status = int.from_bytes(buf[4:], "little")
        if seq != want_seq:
            raise DataPlaneError(
                f"data-plane ack out of order (got {seq}, want {want_seq})")
        if status == ABORTED:
            raise DataPlaneError(
                f"receiver aborted transfer of {oid.hex()[:16]} mid-stream")
        if status == FINISH_FAILED:
            raise DataPlaneError(
                f"receiver failed to seal/relay {oid.hex()[:16]}")
