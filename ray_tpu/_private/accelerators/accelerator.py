"""Accelerator manager interface (reference:
python/ray/_private/accelerators/accelerator.py — 8-method ABC per vendor).
Here TPU is the first-class citizen; the ABC stays so other vendors can
plug in."""

from __future__ import annotations

from typing import Dict, List, Optional


class AcceleratorManager:
    """Static-method interface: detection, isolation, extra resources."""

    @staticmethod
    def get_resource_name() -> str:
        raise NotImplementedError

    @staticmethod
    def get_visible_accelerator_ids_env_var() -> str:
        raise NotImplementedError

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        raise NotImplementedError

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        raise NotImplementedError

    @staticmethod
    def get_current_process_visible_accelerator_ids() -> Optional[List[str]]:
        raise NotImplementedError

    @staticmethod
    def set_current_process_visible_accelerator_ids(ids: List[str]) -> None:
        raise NotImplementedError

    @staticmethod
    def get_current_node_additional_resources() -> Dict[str, float]:
        return {}

    @staticmethod
    def validate_resource_request_quantity(quantity: float):
        return (True, None)
