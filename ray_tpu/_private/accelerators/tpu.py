"""TPU accelerator manager: chip detection, visibility isolation, and
pod-slice scheduling resources.

Re-design of the reference's TPU support (reference:
python/ray/_private/accelerators/tpu.py:71 TPUAcceleratorManager — chip
autodetect :48, TPU_VISIBLE_CHIPS isolation :155, pod-type detection :198,
pod-slice resources :334). Differences: slice gang scheduling is meant to
be first-class here — a node in a TPU pod slice advertises
  TPU-{accelerator_type}-head : 1.0   (worker 0 only)
  tpu-slice:{pod_name}        : 1.0   (every worker in the slice)
so a trainer reserves a whole slice by taking the head resource and then
fanning out per-host actors pinned by the pod-name resource.
"""

from __future__ import annotations

import glob
import json
import logging
import os
import re
from typing import Dict, List, Optional

from ray_tpu._private.accelerators.accelerator import AcceleratorManager

logger = logging.getLogger(__name__)

TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
# GCE TPU-VM metadata (gated: zero-egress or non-GCE boxes skip silently)
GCE_TPU_ACCEL_TYPE_ENV = "TPU_ACCELERATOR_TYPE"   # e.g. v4-32, v5litepod-8
GCE_TPU_NAME_ENV = "TPU_NAME"
GCE_TPU_WORKER_ID_ENV = "TPU_WORKER_ID"

_SINGLE_HOST_CHIPS = {"v2": 4, "v3": 4, "v4": 4, "v5litepod": 8, "v5p": 4,
                      "v6e": 8}


def _chips_per_host(accel_type: str) -> int:
    gen = accel_type.split("-")[0]
    return _SINGLE_HOST_CHIPS.get(gen, 4)


# --------------------------------------------------- GCE metadata autodetect
# Real TPU-VMs publish accelerator-type / worker-number / instance-id on the
# GCE metadata server (reference: tpu.py:198 pod-type detection). Consulted
# BEFORE the env-var fallback so unattended TPU-VMs work with no env setup;
# gated behind a DMI platform sniff + short timeout + negative caching so
# non-GCE boxes (and unit tests) never pay a network wait.
_GCE_METADATA_URL = "http://metadata.google.internal/computeMetadata/v1/"
_GCE_TIMEOUT_S = 0.5
_metadata_cache: Dict[str, Optional[str]] = {}


def _on_gce() -> bool:
    if os.environ.get("RAY_TPU_DISABLE_GCE_METADATA"):
        return False
    try:
        with open("/sys/class/dmi/id/product_name") as f:
            return "Google" in f.read()
    except OSError:
        return False


def _gce_metadata(path: str) -> Optional[str]:
    """One metadata attribute, cached (including misses) per process."""
    if path in _metadata_cache:
        return _metadata_cache[path]
    value = None
    if _on_gce():
        try:
            import urllib.request
            req = urllib.request.Request(
                _GCE_METADATA_URL + path,
                headers={"Metadata-Flavor": "Google"})
            with urllib.request.urlopen(req, timeout=_GCE_TIMEOUT_S) as r:
                if r.status == 200:
                    value = r.read().decode().strip() or None
        except Exception:
            value = None
    _metadata_cache[path] = value
    return value


# ------------------------------------------------------ preemption notice
# Spot/preemptible TPU-VMs get ~30s of warning: GCE flips the
# instance/preempted metadata attribute (and delivers the ACPI G2 soft
# off) before the hard kill. Serving replicas poll this channel and
# drain instead of dying mid-stream (serve/replica.py); chaos tests
# inject the notice without a cloud via the env/file hooks below.
PREEMPT_TEST_ENV = "RAY_TPU_TESTING_PREEMPTED"
PREEMPT_TEST_FILE_ENV = "RAY_TPU_TESTING_PREEMPT_FILE"


def preemption_watch_enabled() -> bool:
    """Whether polling for preemption notices can ever observe one:
    on GCE, or when a chaos injection hook is armed."""
    return bool(os.environ.get(PREEMPT_TEST_ENV)
                or os.environ.get(PREEMPT_TEST_FILE_ENV)
                or _on_gce())


def check_preemption_notice() -> bool:
    """True once the platform announced this VM is being preempted.
    Deliberately NOT cached (unlike _gce_metadata) — the whole point is
    observing the flip; callers poll on a ~1s cadence. Chaos channels
    are checked first: the env flag arms a whole process at spawn, the
    marker file lets a test flip a LIVE replica from outside."""
    if os.environ.get(PREEMPT_TEST_ENV):
        return True
    marker = os.environ.get(PREEMPT_TEST_FILE_ENV)
    if marker:
        return os.path.exists(marker)
    if not _on_gce():
        return False
    try:
        import urllib.request
        req = urllib.request.Request(
            _GCE_METADATA_URL + "instance/preempted",
            headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=_GCE_TIMEOUT_S) as r:
            return r.read().decode().strip().upper() == "TRUE"
    except Exception:
        return False


class TPUAcceleratorManager(AcceleratorManager):
    @staticmethod
    def get_resource_name() -> str:
        return "TPU"

    @staticmethod
    def get_visible_accelerator_ids_env_var() -> str:
        return TPU_VISIBLE_CHIPS_ENV

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        visible = TPUAcceleratorManager.get_current_process_visible_accelerator_ids()
        if visible is not None:
            return len(visible)
        # /dev/accel* (TPU VM) or vfio devices
        n = len(glob.glob("/dev/accel*"))
        if n == 0:
            n = len(glob.glob("/dev/vfio/*")) - (1 if os.path.exists(
                "/dev/vfio/vfio") else 0)
            n = max(0, n)
        if n == 0:
            # no device nodes visible (some TPU-VM images mount them
            # late): infer the per-host chip count from the detected
            # accelerator type so unattended bring-up still advertises TPU
            accel = TPUAcceleratorManager.get_current_node_accelerator_type()
            if accel:
                n = _chips_per_host(accel)
        if n == 0 and os.environ.get("RAY_TPU_FAKE_CHIPS"):
            n = int(os.environ["RAY_TPU_FAKE_CHIPS"])
        return n

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        # autodetect first (GCE metadata, short timeout, cached), env last
        # — a real TPU-VM then works unattended with no env setup
        return (_gce_metadata("instance/attributes/accelerator-type")
                or os.environ.get(GCE_TPU_ACCEL_TYPE_ENV))

    @staticmethod
    def get_current_process_visible_accelerator_ids() -> Optional[List[str]]:
        v = os.environ.get(TPU_VISIBLE_CHIPS_ENV)
        if v is None or v == "":
            return None
        return [x for x in v.split(",") if x != ""]

    @staticmethod
    def set_current_process_visible_accelerator_ids(ids: List[str]) -> None:
        os.environ[TPU_VISIBLE_CHIPS_ENV] = ",".join(str(i) for i in ids)
        # JAX on TPU-VM also honors TPU_PROCESS_BOUNDS-style vars; chip
        # masking alone suffices for same-host isolation.

    @staticmethod
    def get_current_node_tpu_pod_name() -> Optional[str]:
        return (_gce_metadata("instance/attributes/instance-id")
                or os.environ.get(GCE_TPU_NAME_ENV))

    @staticmethod
    def is_pod_worker_0() -> bool:
        wid = (_gce_metadata("instance/attributes/agent-worker-number")
               or os.environ.get(GCE_TPU_WORKER_ID_ENV, "0"))
        return wid == "0"

    @staticmethod
    def get_current_node_additional_resources() -> Dict[str, float]:
        """Slice resources: tpu-slice:{pod_name}: 1 on every slice
        host, TPU-{type}-head: 1 on worker 0 (reference:
        tpu.py:334-397)."""
        out: Dict[str, float] = {}
        accel_type = TPUAcceleratorManager.get_current_node_accelerator_type()
        pod_name = TPUAcceleratorManager.get_current_node_tpu_pod_name()
        if accel_type and _is_multi_host(accel_type):
            if pod_name:
                # prefixed so slice-membership markers are recognizable to
                # the gang scheduler (train/slice.py) among arbitrary
                # custom resources
                out[f"tpu-slice:{pod_name}"] = 1.0
            if TPUAcceleratorManager.is_pod_worker_0():
                out[f"TPU-{accel_type}-head"] = 1.0
        return out

    @staticmethod
    def validate_resource_request_quantity(quantity: float):
        if quantity not in (0,) and quantity > 0 and quantity != int(quantity):
            return (False, "TPU chips are not fractionally shareable")
        return (True, None)


def _is_multi_host(accel_type: str) -> bool:
    m = re.match(r"^[^-]+-(\d+)$", accel_type)
    if not m:
        return False
    return int(m.group(1)) > _chips_per_host(accel_type)


def slice_hosts(accel_type: str) -> int:
    """Number of hosts in a slice, e.g. v4-32 -> 4 (v4: 2 chips/core-count
    unit; core count 32 -> 16 chips -> 4 hosts of 4 chips)."""
    m = re.match(r"^v(\d+)[a-z]*-(\d+)$", accel_type)
    if not m:
        return 1
    gen = accel_type.split("-")[0]
    count = int(accel_type.split("-")[-1])
    if gen in ("v2", "v3", "v4", "v5p"):   # N = core count, 2 cores/chip
        chips = count // 2
    else:                                   # v5litepod/v6e: N = chip count
        chips = count
    return max(1, chips // _chips_per_host(accel_type))
