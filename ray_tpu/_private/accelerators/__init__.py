from ray_tpu._private.accelerators.accelerator import AcceleratorManager
from ray_tpu._private.accelerators.tpu import TPUAcceleratorManager

_MANAGERS = {"TPU": TPUAcceleratorManager}


def get_accelerator_manager(resource_name: str):
    return _MANAGERS.get(resource_name)


def all_accelerator_managers():
    return dict(_MANAGERS)


def detect_chip_ids():
    """Actual TPU chip ids this node owns (respects TPU_VISIBLE_CHIPS on a
    partitioned host — ids are NOT simply range(n))."""
    visible = TPUAcceleratorManager.get_current_process_visible_accelerator_ids()
    if visible is not None:
        return list(visible)
    n = TPUAcceleratorManager.get_current_node_num_accelerators()
    return [str(i) for i in range(n)]


def detect_node_accelerators():
    """Returns {resource_name: count} plus any extra slice resources, and
    env isolation info, for this node."""
    resources = {}
    for name, mgr in _MANAGERS.items():
        n = mgr.get_current_node_num_accelerators()
        if n > 0:
            resources[name] = float(n)
            resources.update(mgr.get_current_node_additional_resources())
    return resources


__all__ = ["AcceleratorManager", "TPUAcceleratorManager",
           "get_accelerator_manager", "detect_node_accelerators"]
