"""Asyncio RPC: length-prefixed msgpack frames over unix/tcp sockets.

The control-plane transport for the whole runtime — the role gRPC plays in
the reference (reference: src/ray/rpc/grpc_server.h, client_call.h). Design
differences, deliberately: one tiny symmetric protocol instead of per-service
protobuf schemas; connections are bidirectional (either side may issue
requests over an established connection), which removes the server→client
callback channels the reference needs (pubsub long-polling, owner RPCs).

Frame:   [u32 little-endian length][msgpack payload]
Payload: [type, seq, method, kwargs]          type: 0=request 1=response
         [1, seq, ok, result_or_error]              2=notify (no response)
Large binary values ride inside msgpack bin fields; bulk object payloads
never transit this layer (they live in the shm store / object transfer path).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import socket
import traceback
from typing import Any, Awaitable, Callable, Dict, Optional

import msgpack

logger = logging.getLogger(__name__)

REQUEST = 0
RESPONSE = 1
NOTIFY = 2

_MAX_FRAME = 1 << 31


class RpcError(Exception):
    """Remote handler raised; carries the remote traceback text."""

    def __init__(self, kind: str, message: str, remote_tb: str = ""):
        super().__init__(f"{kind}: {message}")
        self.kind = kind
        self.message = message
        self.remote_tb = remote_tb


_CHAOS_SPEC = None


def _maybe_inject_failure(method: str):
    """RPC chaos for fault-injection tests (reference: RpcFailureManager
    src/ray/rpc/rpc_chaos.cc:35 + RAY_testing_rpc_failure). Spec via env
    RAY_TPU_TESTING_RPC_FAILURE="method=prob,method2=prob"."""
    global _CHAOS_SPEC
    if _CHAOS_SPEC is None:
        import os
        spec = {}
        raw = os.environ.get("RAY_TPU_TESTING_RPC_FAILURE", "")
        for part in raw.split(","):
            if "=" in part:
                m, p = part.split("=", 1)
                try:
                    spec[m.strip()] = float(p)
                except ValueError:
                    pass
        _CHAOS_SPEC = spec
    prob = _CHAOS_SPEC.get(method)
    if prob:
        import random
        if random.random() < prob:
            raise RpcError("ChaosInjected",
                           f"injected chaos failure for {method!r}")


class ConnectionLost(Exception):
    pass


def _pack(obj) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def _unpack(data) -> Any:
    return msgpack.unpackb(data, raw=False, strict_map_key=False)


class Connection:
    """One bidirectional framed connection. Both peers can call/notify."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 handlers: Optional[Dict[str, Callable]] = None, name: str = "?"):
        self.reader = reader
        self.writer = writer
        self.handlers = handlers if handlers is not None else {}
        self.name = name
        self._seq = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._closed = False
        self._writer_lock = asyncio.Lock()
        self._task: Optional[asyncio.Task] = None
        self._dispatch_tasks: set = set()
        self.on_close: Optional[Callable[["Connection"], None]] = None
        # opaque slot for servers to stash peer identity (node id, worker id)
        self.peer_info: Dict[str, Any] = {}

    def start(self):
        self._task = asyncio.ensure_future(self._read_loop())
        return self

    @property
    def closed(self) -> bool:
        return self._closed

    async def _read_loop(self):
        try:
            while True:
                hdr = await self.reader.readexactly(4)
                n = int.from_bytes(hdr, "little")
                if n > _MAX_FRAME:
                    raise ConnectionLost(f"frame too large: {n}")
                body = await self.reader.readexactly(n)
                msg = _unpack(body)
                mtype = msg[0]
                if mtype == REQUEST or mtype == NOTIFY:
                    t = asyncio.ensure_future(self._dispatch(msg))
                    self._dispatch_tasks.add(t)
                    t.add_done_callback(self._dispatch_tasks.discard)
                elif mtype == RESPONSE:
                    _, seq, ok, payload = msg
                    fut = self._pending.pop(seq, None)
                    if fut is not None and not fut.done():
                        if ok:
                            fut.set_result(payload)
                        else:
                            fut.set_exception(RpcError(*payload))
        except (asyncio.IncompleteReadError, ConnectionResetError,
                ConnectionLost, BrokenPipeError, OSError):
            pass
        except Exception:
            logger.exception("rpc read loop error on %s", self.name)
        finally:
            await self._shutdown()

    async def _shutdown(self):
        if self._closed:
            return
        self._closed = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionLost(f"connection {self.name} lost"))
        self._pending.clear()
        try:
            self.writer.close()
        except Exception:
            pass
        if self.on_close is not None:
            try:
                cb = self.on_close
                self.on_close = None
                res = cb(self)
                if asyncio.iscoroutine(res):
                    await res
            except Exception:
                logger.exception("on_close callback failed for %s", self.name)

    async def _dispatch(self, msg):
        mtype, seq, method, kwargs = msg
        handler = self.handlers.get(method)
        if handler is None:
            if mtype == REQUEST:
                await self._send([RESPONSE, seq, False,
                                  ("NotImplementedError", f"no handler {method!r}", "")])
            return
        try:
            result = handler(self, **kwargs)
            if asyncio.iscoroutine(result) or isinstance(result, Awaitable):
                result = await result
            if mtype == REQUEST:
                await self._send([RESPONSE, seq, True, result])
        except asyncio.CancelledError:
            raise
        except Exception as e:
            if mtype == REQUEST:
                await self._send([RESPONSE, seq, False,
                                  (type(e).__name__, str(e), traceback.format_exc())])
            else:
                logger.exception("notify handler %s failed", method)

    async def _send(self, obj):
        data = _pack(obj)
        async with self._writer_lock:
            if self._closed:
                raise ConnectionLost(f"connection {self.name} closed")
            if len(data) < 65536:
                # one buffer -> one syscall for the common small message
                self.writer.write(len(data).to_bytes(4, "little") + data)
            else:
                self.writer.write(len(data).to_bytes(4, "little"))
                self.writer.write(data)
            await self.writer.drain()

    async def call(self, method: str, timeout: Optional[float] = None, **kwargs) -> Any:
        _maybe_inject_failure(method)
        fut = await self.call_start(method, **kwargs)
        if timeout is not None:
            return await asyncio.wait_for(fut, timeout)
        return await fut

    async def call_start(self, method: str, **kwargs) -> asyncio.Future:
        """Issue the request and return the response future without awaiting
        it — callers that must preserve send order serialize on this, then
        pipeline the responses."""
        seq = next(self._seq)
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[seq] = fut
        try:
            await self._send([REQUEST, seq, method, kwargs])
        except BaseException:
            self._pending.pop(seq, None)
            fut.cancel()
            raise
        return fut

    async def notify(self, method: str, **kwargs):
        await self._send([NOTIFY, 0, method, kwargs])

    async def close(self):
        me = asyncio.current_task()
        victims = [t for t in [self._task, *self._dispatch_tasks]
                   if t is not None and t is not me and not t.done()]
        for t in victims:
            t.cancel()
        if victims:
            await asyncio.gather(*victims, return_exceptions=True)
        await self._shutdown()


def parse_address(addr: str):
    """'unix:/path' or 'tcp:host:port' -> (kind, ...)."""
    if addr.startswith("unix:"):
        return ("unix", addr[5:])
    if addr.startswith("tcp:"):
        host, port = addr[4:].rsplit(":", 1)
        return ("tcp", host, int(port))
    # bare host:port
    host, port = addr.rsplit(":", 1)
    return ("tcp", host, int(port))


class Server:
    """RPC server accepting unix and/or tcp connections with shared handlers."""

    def __init__(self, handlers: Dict[str, Callable], name: str = "server"):
        self.handlers = handlers
        self.name = name
        self._servers = []
        self.connections: set = set()
        self.on_connection: Optional[Callable[[Connection], None]] = None
        self.on_disconnect: Optional[Callable[[Connection], None]] = None

    async def _on_client(self, reader, writer):
        conn = Connection(reader, writer, self.handlers,
                          name=f"{self.name}-peer").start()
        self.connections.add(conn)

        def _closed(c):
            self.connections.discard(c)
            if self.on_disconnect is not None:
                self.on_disconnect(c)

        conn.on_close = _closed
        if self.on_connection is not None:
            self.on_connection(conn)

    async def listen_unix(self, path: str):
        if os.path.exists(path):
            os.unlink(path)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        srv = await asyncio.start_unix_server(self._on_client, path=path)
        self._servers.append(srv)
        return f"unix:{path}"

    async def listen_tcp(self, host: str = "0.0.0.0", port: int = 0) -> str:
        srv = await asyncio.start_server(self._on_client, host=host, port=port,
                                         reuse_address=True)
        self._servers.append(srv)
        port = srv.sockets[0].getsockname()[1]
        return f"tcp:{_advertise_host(host)}:{port}"

    async def close(self):
        for srv in self._servers:
            srv.close()
            await srv.wait_closed()
        for conn in list(self.connections):
            await conn.close()


def _advertise_host(bind_host: str) -> str:
    if bind_host not in ("0.0.0.0", "::", ""):
        return bind_host
    return node_ip_address()


_cached_ip: Optional[str] = None


def node_ip_address() -> str:
    global _cached_ip
    if _cached_ip is None:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            # no traffic is sent; just picks the interface with a default route
            s.connect(("8.8.8.8", 80))
            _cached_ip = s.getsockname()[0]
        except OSError:
            _cached_ip = "127.0.0.1"
        finally:
            s.close()
    return _cached_ip


async def connect(addr: str, handlers: Optional[Dict[str, Callable]] = None,
                  name: str = "client", retries: int = 0,
                  retry_delay: float = 0.1) -> Connection:
    parsed = parse_address(addr)
    last_err: Optional[Exception] = None
    for attempt in range(retries + 1):
        try:
            if parsed[0] == "unix":
                reader, writer = await asyncio.open_unix_connection(parsed[1])
            else:
                reader, writer = await asyncio.open_connection(parsed[1], parsed[2])
                sock = writer.get_extra_info("socket")
                if sock is not None:
                    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return Connection(reader, writer, handlers, name=name).start()
        except (ConnectionRefusedError, FileNotFoundError, OSError) as e:
            last_err = e
            if attempt < retries:
                await asyncio.sleep(min(retry_delay * (1.5 ** attempt), 2.0))
    raise ConnectionError(f"cannot connect to {addr}: {last_err}")


class ConnectionPool:
    """Caches one Connection per address; reconnects lazily on loss."""

    def __init__(self, handlers: Optional[Dict[str, Callable]] = None,
                 name: str = "pool"):
        self.handlers = handlers or {}
        self.name = name
        self._conns: Dict[str, Connection] = {}
        self._locks: Dict[str, asyncio.Lock] = {}
        self._closing: set = set()

    async def get(self, addr: str) -> Connection:
        conn = self._conns.get(addr)
        if conn is not None and not conn.closed:
            return conn
        lock = self._locks.setdefault(addr, asyncio.Lock())
        async with lock:
            conn = self._conns.get(addr)
            if conn is not None and not conn.closed:
                return conn
            conn = await connect(addr, self.handlers,
                                 name=f"{self.name}->{addr}", retries=3)
            self._conns[addr] = conn
            return conn

    async def call(self, addr: str, method: str, **kwargs):
        conn = await self.get(addr)
        return await conn.call(method, **kwargs)

    def invalidate(self, addr: str):
        conn = self._conns.pop(addr, None)
        if conn is not None and not conn.closed:
            t = asyncio.ensure_future(conn.close())
            self._closing.add(t)
            t.add_done_callback(self._closing.discard)

    async def close(self):
        conns, self._conns = list(self._conns.values()), {}
        if conns:
            await asyncio.gather(*(c.close() for c in conns),
                                 return_exceptions=True)
        if self._closing:
            await asyncio.gather(*list(self._closing),
                                 return_exceptions=True)
